"""Combine + reduce: merge per-segment partials, finalize, project, HAVING/ORDER/LIMIT.

Analog of the reference's combine operators + broker reduce
(`pinot-core/.../operator/combine/GroupByOrderByCombineOperator.java` merging into
`ConcurrentIndexedTable`, then `core/query/reduce/GroupByDataTableReducer.java`,
`PostAggregationHandler.java`, `HavingFilterHandler.java`). Here both levels use the same
value-keyed hash merge, because group keys are decoded to *values* before leaving a segment
(per-segment dictionaries don't align across segments).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..sql.ast import Expr, Function, Identifier, Literal
from ..engine.expr import eval_expr
from .aggregates import AggFunc
from .context import QueryContext
from .result import ResultTable


@dataclass
class DensePartial:
    """A group-by partial in ARRAY form over an aligned dense key space.

    At high cardinality the dict-of-states partial is the bottleneck: building
    (and merging, and wire-encoding) 500k Python state lists costs seconds
    while the kernel runs in tens of milliseconds. When every aggregation is
    dense-finalizable and the servers share aligned dictionaries (`token`
    matches), partials stay as the kernel's dense output arrays end to end:
    merge is elementwise (+/min/max), the wire carries flat ndarrays, and the
    broker finalizes vectorized (reference contrast: GroupByDataTableReducer's
    IndexedTable hash merge).
    """

    token: Tuple                  # (group cols, cards, dict hashes, num_keys)
    cards: Tuple[int, ...]
    strides: Tuple[int, ...]
    num_keys_real: int
    counts: np.ndarray            # int64[num_keys_real] (exact, mergeable by +)
    outs: Dict[str, np.ndarray]   # "<agg idx>.<out>" arrays, trimmed to real keys
    group_values: List[Any]       # per group col: the full dictionary value table
    # build-side only (never on the wire): lets server-local consumers
    # materialize classic state dicts without replanning
    aggs: Optional[List[AggFunc]] = None

    def merge_from(self, other: "DensePartial") -> None:
        self.counts = self.counts + other.counts
        for k, v in other.outs.items():
            if k.endswith(".min"):
                self.outs[k] = np.minimum(self.outs[k], v)
            elif k.endswith(".max"):
                self.outs[k] = np.maximum(self.outs[k], v)
            else:
                self.outs[k] = self.outs[k] + v


@dataclass
class SegmentResult:
    """Partial result of one segment (reference: IntermediateResultsBlock)."""

    kind: str  # "groups" | "scalar" | "selection"
    groups: Dict[Tuple, List[Any]] = field(default_factory=dict)  # key values -> agg states
    scalar: Optional[List[Any]] = None                            # agg states (no group-by)
    rows: List[Tuple] = field(default_factory=list)               # selection output rows
    sort_keys: List[Tuple] = field(default_factory=list)          # selection sort keys
    num_docs_scanned: int = 0
    # segments this SERVER-LEVEL partial actually covered (None for per-segment
    # results): lets the broker detect a replica that silently skipped a
    # segment mid-transition and retry it on another replica
    served: Optional[List[str]] = None
    # high-cardinality array-form partial; when set, `groups` is EMPTY until
    # `materialize_dense` converts (consumers that need the dict form call it)
    dense: Optional[DensePartial] = None
    # per-query ExecutionStats counters accumulated producing this partial
    # (flat summable dict — see query/stats.py); rides the wire and merges
    # into the broker's record
    stats: Optional[Dict[str, float]] = None

    def materialize_dense(self, aggs: Optional[List[AggFunc]] = None) -> None:
        """Convert the array-form partial into the classic state dict (for
        dict-merge with non-dense partials, hash-partition shuffles, ...)."""
        dp = self.dense
        if dp is None:
            return
        use_aggs = aggs if aggs is not None else dp.aggs
        if use_aggs is None:
            raise ValueError("dense partial needs aggs to materialize")
        occupied = np.nonzero(dp.counts > 0)[0]
        value_cols = [
            np.asarray(dp.group_values[j])[
                (occupied // dp.strides[j]) % max(dp.cards[j], 1)]
            for j in range(len(dp.strides))]
        keys = (list(zip(*[c.tolist() for c in value_cols]))
                if len(occupied) else [])
        for row, k in enumerate(occupied):
            states = []
            for i, agg in enumerate(use_aggs):
                o = {"count": int(dp.counts[k])}
                for out_name in agg.device_outputs:
                    if out_name != "count":
                        o[out_name] = dp.outs[f"{i}.{out_name}"][k]
                states.append(agg.state_from_device(o))
            self.groups[keys[row]] = states
        self.dense = None


def merge_segment_results(results: List[SegmentResult], aggs: List[AggFunc]) -> SegmentResult:
    """Server-level combine (also reused broker-side across servers)."""
    if not results:
        return SegmentResult("scalar", scalar=None)
    kind = results[0].kind
    out = SegmentResult(kind)
    out.num_docs_scanned = sum(r.num_docs_scanned for r in results)
    from .stats import MAX_KEYS, MIN_KEYS
    merged_stats: Dict[str, float] = {}
    for r in results:
        for k, v in (r.stats or {}).items():
            if k in MIN_KEYS:   # freshness timestamps: stalest side wins
                cur = merged_stats.get(k)
                merged_stats[k] = v if cur is None else min(cur, v)
            elif k in MAX_KEYS:  # per-launch skew: worst side wins
                cur = merged_stats.get(k)
                merged_stats[k] = v if cur is None else max(cur, v)
            else:
                merged_stats[k] = merged_stats.get(k, 0) + v
    out.stats = merged_stats or None  # set BEFORE the dense early return
    if kind == "groups":
        denses = [r.dense for r in results]
        if all(d is not None for d in denses) and \
                len({d.token for d in denses}) == 1:
            # partition-wise partial merge: servers with aligned dictionaries
            # agree on dense keys, so high-card partials combine elementwise
            # WITHOUT densifying 100k+ Python state dicts per server
            base = denses[0]
            acc = DensePartial(base.token, base.cards, base.strides,
                               base.num_keys_real,
                               base.counts.astype(np.int64, copy=True),
                               {k: v.copy() for k, v in base.outs.items()},
                               base.group_values, aggs=base.aggs)
            for d in denses[1:]:
                acc.merge_from(d)
            out.dense = acc
            return out
        for r in results:
            # mixed dense/dict (or unaligned dictionaries): densify once here
            r.materialize_dense(aggs)
        merged: Dict[Tuple, List[Any]] = {}
        for r in results:
            for key, states in r.groups.items():
                cur = merged.get(key)
                if cur is None:
                    merged[key] = list(states)
                else:
                    for i, agg in enumerate(aggs):
                        cur[i] = agg.merge(cur[i], states[i])
        out.groups = merged
    elif kind == "scalar":
        merged_states: Optional[List[Any]] = None
        for r in results:
            if r.scalar is None:
                continue
            if merged_states is None:
                merged_states = list(r.scalar)
            else:
                for i, agg in enumerate(aggs):
                    merged_states[i] = agg.merge(merged_states[i], r.scalar[i])
        out.scalar = merged_states
    else:
        for r in results:
            out.rows.extend(r.rows)
            out.sort_keys.extend(r.sort_keys)
    return out


def _object_array(vals: List[Any]) -> np.ndarray:
    """1-D object array of exactly len(vals) cells. np.array(vals, dtype=object)
    would splat equal-length LIST values (e.g. HISTOGRAM results) into a 2-D
    array instead of keeping one list per cell."""
    out = np.empty(len(vals), dtype=object)
    for i, v in enumerate(vals):
        out[i] = v
    return out


def reduce_to_result(ctx: QueryContext, merged: SegmentResult, aggs: List[AggFunc],
                     group_exprs: List[Expr]) -> ResultTable:
    """Broker-side reduce: finalize states, post-aggregate, HAVING, ORDER BY, LIMIT."""
    if merged.kind == "selection":
        return _reduce_selection(ctx, merged)

    # -- build the result-expression environment ---------------------------
    env: Dict[str, np.ndarray] = {}
    if merged.kind == "groups" and merged.dense is not None:
        # array-form partial: finalize VECTORIZED over occupied dense keys
        # (dense_values per agg + dictionary takes per group column) instead
        # of the per-group Python state loop below
        dp = merged.dense
        occupied = np.nonzero(dp.counts > 0)[0]
        n = len(occupied)
        counts_occ = dp.counts[occupied]
        for j, g in enumerate(group_exprs):
            ids_j = (occupied // dp.strides[j]) % max(dp.cards[j], 1)
            env[repr(g)] = _object_array(
                np.asarray(dp.group_values[j])[ids_j].tolist())
        for i, call in enumerate(ctx.aggregations):
            agg = aggs[i]

            def get(name, i=i):
                if name == "count":
                    return counts_occ
                return dp.outs[f"{i}.{name}"][occupied]

            vals = np.asarray(agg.dense_values(get, counts_occ))
            cells = _object_array(vals.tolist())
            if agg.dense_nan_is_null and vals.dtype.kind == "f":
                # scalar finalize returns None where the dense form emits NaN
                for bad in np.nonzero(vals != vals)[0]:
                    cells[bad] = None
            env[repr(call)] = cells
    elif merged.kind == "groups":
        keys = list(merged.groups.keys())
        n = len(keys)
        for j, g in enumerate(group_exprs):
            env[repr(g)] = np.array([k[j] for k in keys], dtype=object)
        for i, call in enumerate(ctx.aggregations):
            vals = [aggs[i].finalize(merged.groups[k][i]) for k in keys]
            env[repr(call)] = _object_array(vals)
    else:
        n = 1
        states = merged.scalar
        for i, call in enumerate(ctx.aggregations):
            v = (aggs[i].finalize(states[i]) if states is not None
                 else aggs[i].empty_result())
            env[repr(call)] = _object_array([v])

    # -- HAVING ------------------------------------------------------------
    keep = np.ones(n, dtype=bool)
    if ctx.having is not None:
        keep &= np.asarray(_eval_result(ctx.having, env, n), dtype=bool)

    # -- project select items ---------------------------------------------
    out_cols: List[np.ndarray] = []
    for expr, _name in ctx.select_items:
        out_cols.append(np.asarray(_eval_result(expr, env, n), dtype=object))

    # -- ORDER BY ----------------------------------------------------------
    idx = np.nonzero(keep)[0].tolist()
    if ctx.order_by:
        sort_cols = [np.asarray(_eval_result(o.expr, env, n), dtype=object)
                     for o in ctx.order_by]
        idx.sort(key=lambda i: _sort_key(
            [c[i] for c in sort_cols], ctx.order_by))

    if ctx.gapfill is not None:
        rows = _apply_gapfill(ctx, group_exprs,
                              [[col[i] for col in out_cols] for i in idx])
        if ctx.order_by:
            # gap rows were generated series-first/bucket-ascending; re-apply the
            # query's ORDER BY (over select-item columns) before OFFSET/LIMIT
            sel_repr = {repr(e): j for j, (e, _) in enumerate(ctx.select_items)}
            cols = [sel_repr.get(repr(o.expr)) for o in ctx.order_by]
            if all(c is not None for c in cols):
                rows.sort(key=lambda r: _sort_key([r[c] for c in cols],
                                                  ctx.order_by))
        rows = rows[ctx.offset:ctx.offset + ctx.limit]
        return ResultTable([name for _, name in ctx.select_items], _pyify(rows),
                           {"numDocsScanned": merged.num_docs_scanned,
                            "gapfilled": True})

    idx = idx[ctx.offset:ctx.offset + ctx.limit]
    rows = [[col[i] for col in out_cols] for i in idx]
    return ResultTable([name for _, name in ctx.select_items], _pyify(rows),
                       {"numDocsScanned": merged.num_docs_scanned,
                        "numGroupsTotal": n if merged.kind == "groups" else None})


def _apply_gapfill(ctx: QueryContext, group_exprs: List[Expr],
                   rows: List[List[Any]]) -> List[List[Any]]:
    """Fill missing time buckets per series (reference: GapfillProcessor).

    Output is ordered (series in first-seen order, then time bucket ascending);
    series keys are the non-time group-by select items."""
    gf = ctx.gapfill
    ti = gf.index
    group_reprs = {repr(g) for g in group_exprs}
    key_idx = [j for j, (e, _) in enumerate(ctx.select_items)
               if j != ti and repr(e) in group_reprs]

    series: Dict[Tuple, Dict[Any, List[Any]]] = {}
    for row in rows:
        key = tuple(row[j] for j in key_idx)
        series.setdefault(key, {})[row[ti]] = row

    buckets = range(gf.start, gf.end, gf.bucket)
    out: List[List[Any]] = []
    for key, by_time in series.items():
        prev: Dict[int, Any] = {}
        for b in buckets:
            row = by_time.get(b)
            if row is None:
                row = [None] * len(ctx.select_items)
                row[ti] = b
                for j, v in zip(key_idx, key):
                    row[j] = v
                for j in range(len(row)):
                    if j == ti or j in key_idx:
                        continue
                    mode, default = gf.fills.get(j, (None, None))
                    if mode == "FILL_PREVIOUS_VALUE":
                        row[j] = prev.get(j)
                    elif mode == "FILL_DEFAULT_VALUE":
                        row[j] = default
            else:
                for j in range(len(row)):
                    prev[j] = row[j]
            out.append(row)
    return out


def _reduce_selection(ctx: QueryContext, merged: SegmentResult) -> ResultTable:
    order = list(range(len(merged.rows)))
    if ctx.order_by:
        order.sort(key=lambda i: _sort_key(list(merged.sort_keys[i]), ctx.order_by))
    order = order[ctx.offset:ctx.offset + ctx.limit]
    rows = [list(merged.rows[i]) for i in order]
    return ResultTable([name for _, name in ctx.select_items], _pyify(rows),
                       {"numDocsScanned": merged.num_docs_scanned})


class _Reverse:
    """Inverts comparison order for DESC keys of arbitrary comparable type."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


def _sort_key(values: List[Any], order_by) -> Tuple:
    key = []
    for v, o in zip(values, order_by):
        # Null ordering: reference treats null as largest unless NULLS FIRST/LAST given.
        nulls_last = o.nulls_last if o.nulls_last is not None else not o.desc
        is_null = v is None
        null_rank = (1 if is_null else 0) if nulls_last else (0 if is_null else 1)
        v = 0 if is_null else v
        key.append((null_rank, _Reverse(v) if o.desc else v))
    return tuple(key)


def _eval_result(e: Expr, env: Dict[str, np.ndarray], n: int):
    """Evaluate a result-shaping expression: aggregation/group subtrees come from `env`
    (keyed by canonical repr), remaining arithmetic evaluates vectorized on host."""
    sub, bindings = _substitute(e, env)
    out = eval_expr(sub, bindings, np)
    if np.isscalar(out) or not hasattr(out, "__len__"):
        return np.full(n, out, dtype=object)
    return out


def _substitute(e: Expr, env: Dict[str, np.ndarray], bindings=None):
    if bindings is None:
        bindings = {}
    r = repr(e)
    if r in env:
        name = f"\x00{len(bindings)}"
        # reuse binding for identical subtrees
        for k, v in bindings.items():
            if v is env[r]:
                name = k
                break
        bindings[name] = env[r]
        return Identifier(name), bindings
    if isinstance(e, Function):
        new_args = []
        for a in e.args:
            na, bindings = _substitute(a, env, bindings)
            new_args.append(na)
        return Function(e.name, tuple(new_args), e.distinct), bindings
    if isinstance(e, Identifier):
        raise KeyError(f"unresolved column {e.name!r} in post-aggregation expression")
    return e, bindings


def _pyify(rows: List[List[Any]]) -> List[List[Any]]:
    out = []
    for row in rows:
        out.append([v.item() if isinstance(v, np.generic) else v for v in row])
    return out
