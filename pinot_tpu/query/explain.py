"""EXPLAIN PLAN FOR <query>: operator-tree description of the execution plan.

Analog of the reference's explain support (`ExplainPlanQueriesTest`,
`core/query/reduce/ExplainPlanDataTableReducer`): the response is a ResultTable
with columns [Operator, Operator_Id, Parent_Id], one row per operator node,
ids in pre-order so the tree reconstructs from parent links.

The plan surface here is the per-segment `SegmentPlan` (planner.py): segments
sharing a plan shape collapse into one subtree with a `segments=N` count —
the analog of the reference grouping identical server plans in v2 explain.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..sql.ast import to_sql
from .context import QueryContext
from .planner import SegmentPlan, plan_segment
from .predicate import CmpLeaf, DocSetLeaf, LutLeaf, NullLeaf
from .result import ResultTable


class _Node:
    def __init__(self, label: str, children: Optional[List["_Node"]] = None):
        self.label = label
        self.children = children or []

    def signature(self) -> Tuple:
        return (self.label, tuple(c.signature() for c in self.children))


def _filter_node(plan: SegmentPlan) -> Optional[_Node]:
    prog = plan.filter_prog
    if prog is None or prog.is_match_all:
        return _Node("FILTER_MATCH_ALL")

    def leaf_node(i: int) -> _Node:
        leaf = prog.leaves[i]
        if isinstance(leaf, LutLeaf):
            kind = ("ID_INTERVALS" if leaf.intervals is not None else "LUT")
            return _Node(f"FILTER_DICT_{kind}(column={leaf.col})")
        if isinstance(leaf, NullLeaf):
            op = "IS_NOT_NULL" if leaf.negated else "IS_NULL"
            return _Node(f"FILTER_{op}(column={leaf.col})")
        if isinstance(leaf, DocSetLeaf):
            return _Node(f"FILTER_DOCSET(column={leaf.col}; {leaf.desc})")
        assert isinstance(leaf, CmpLeaf)
        return _Node(f"FILTER_EXPR({to_sql(leaf.expr)} {leaf.op} {list(leaf.operands)})")

    def walk(node) -> _Node:
        kind = node[0]
        if kind == "const":
            return _Node(f"FILTER_CONST({'ALL' if node[1] else 'NONE'})")
        if kind == "leaf":
            return leaf_node(node[1])
        if kind == "not":
            return _Node("FILTER_NOT", [walk(node[1])])
        return _Node(f"FILTER_{kind.upper()}", [walk(c) for c in node[1]])

    return walk(prog.tree)


def _segment_plan_node(ctx: QueryContext, plan: SegmentPlan) -> _Node:
    if plan.kind == "empty":
        return _Node("PRUNED(filter folds to constant false)")
    if plan.kind == "metadata":
        aggs = ", ".join(a.call.name for a in plan.aggs)
        return _Node(f"METADATA_ONLY_AGGREGATE(aggregations:{aggs})")

    children: List[_Node] = []
    f = _filter_node(plan)
    if f is not None:
        children.append(f)

    if plan.kind == "selection":
        cols = ", ".join(name for _, name in ctx.select_items)
        label = ("SELECT_ORDERBY" if ctx.order_by else "SELECT") + f"(columns:{cols})"
        return _Node(label, children)

    if plan.group_exprs:
        keys = ", ".join(to_sql(g) for g in plan.group_exprs)
        aggs = ", ".join(a.call.name for a in plan.aggs) or "-"
        if plan.kind == "device":
            label = (f"DEVICE_FUSED_GROUP_BY(keys:{keys}; aggregations:{aggs}; "
                     f"denseKeys:{plan.num_keys_real or '?'})")
        else:
            label = f"HOST_GROUP_BY(keys:{keys}; aggregations:{aggs})"
    else:
        aggs = ", ".join(a.call.name for a in plan.aggs)
        label = (f"DEVICE_FUSED_AGGREGATE(aggregations:{aggs})"
                 if plan.kind == "device" else
                 f"HOST_AGGREGATE(aggregations:{aggs})")
    if plan.kind == "host" and plan.fallback_reason:
        label = label[:-1] + f"; fallback:{plan.fallback_reason})"
    return _Node(label, children)


def explain_plan_nodes(ctx: QueryContext, segments: Sequence[Any],
                       table: Optional[str] = None) -> List[_Node]:
    """One node per DISTINCT per-segment plan shape, each tagged segments=N."""
    shapes: Dict[Tuple, Tuple[_Node, int]] = {}
    order: List[Tuple] = []
    for seg in segments:
        node = None
        if not getattr(seg, "is_mutable", False):
            # mirror the executor: the star-tree rewrite happens before planning
            from .startree_exec import try_star_tree
            stp = try_star_tree(ctx, seg)
            if stp is not None:
                sub = plan_segment(ctx2 := stp.ctx2, stp.tree.view)
                if sub.kind == "device":
                    from .planner import build_device_geometry
                    build_device_geometry(sub)
                node = _Node(f"STAR_TREE_REWRITE(records:{stp.tree.view.num_docs})",
                             [_segment_plan_node(ctx2, sub)])
        if node is None:
            plan = plan_segment(ctx, seg)
            if plan.kind == "device":
                from .planner import build_device_geometry
                build_device_geometry(plan)
            node = _segment_plan_node(ctx, plan)
        sig = node.signature()
        if sig in shapes:
            shapes[sig] = (shapes[sig][0], shapes[sig][1] + 1)
        else:
            shapes[sig] = (node, 1)
            order.append(sig)
    out = []
    tbl = f"table:{table}; " if table else ""
    for sig in order:
        node, count = shapes[sig]
        out.append(_Node(f"SEGMENT_PLAN({tbl}segments:{count})", [node]))
    return out


def explain_result(ctx: QueryContext, segments: Sequence[Any],
                   broker_prefix: Optional[List[str]] = None,
                   table: Optional[str] = None) -> ResultTable:
    """Full EXPLAIN response. `broker_prefix` lets the broker prepend its own
    operators (reduce, combine) above the per-segment subtrees."""
    root_labels = broker_prefix if broker_prefix is not None else \
        _default_prefix(ctx)
    # nest the prefix chain, then hang segment-plan subtrees off the last one
    root = _Node(root_labels[0])
    cur = root
    for label in root_labels[1:]:
        nxt = _Node(label)
        cur.children.append(nxt)
        cur = nxt
    cur.children.extend(explain_plan_nodes(ctx, segments, table))

    rows: List[List[Any]] = []

    def emit(node: _Node, parent_id: int) -> None:
        my_id = len(rows)
        rows.append([node.label, my_id, parent_id])
        for c in node.children:
            emit(c, my_id)

    emit(root, -1)
    return ResultTable(["Operator", "Operator_Id", "Parent_Id"], rows,
                       {"explain": True})


ANALYZE_COLUMNS = ["Operator", "Operator_Id", "Parent_Id", "Rows", "Ms"]


def annotate_plan_rows(plan_rows: Sequence[Sequence[Any]], stats,
                       result_rows: int, total_ms: float) -> List[List[Any]]:
    """Extend 3-column EXPLAIN rows with [Rows, Ms] from the executed query's
    ExecutionStats per-operator rollups. Labels prefix-match longest-first:
    "DEVICE_FUSED" annotates DEVICE_FUSED_GROUP_BY(...), "SELECT" annotates
    SELECT_ORDERBY(...), "SEGMENT_PLAN" its wrapper, etc. The root row always
    carries the result row count and total wall time."""
    ops = stats.operators()
    keys = sorted(ops, key=len, reverse=True)

    def annotate(label: str) -> Tuple[Any, Any]:
        for k in keys:
            if label.startswith(k):
                op = ops[k]
                return int(op.get("rows", 0)), round(float(op.get("ms", 0)), 3)
        return None, None

    rows = []
    for label, my_id, parent_id in plan_rows:
        r, ms = annotate(label)
        if my_id == 0:
            r = result_rows if r is None else r
            ms = round(total_ms, 3)
        rows.append([label, my_id, parent_id, r, ms])
    return rows


def analyze_result(ctx: QueryContext, segments: Sequence[Any], stats,
                   inner: ResultTable, total_ms: float,
                   broker_prefix: Optional[List[str]] = None,
                   table: Optional[str] = None) -> ResultTable:
    """EXPLAIN ANALYZE response: the same operator tree as EXPLAIN, with two
    extra columns [Rows, Ms] filled from the executed query's ExecutionStats
    per-operator rollups. `inner` is the already-executed query's ResultTable;
    its stats ride along so the response carries the full telemetry record."""
    base = explain_result(ctx, segments, broker_prefix=broker_prefix,
                          table=table)
    rows = annotate_plan_rows(base.rows, stats, len(inner.rows), total_ms)
    res = ResultTable(list(ANALYZE_COLUMNS), rows, dict(inner.stats))
    res.stats.update(stats.to_public_dict())
    res.stats["explain"] = True
    res.stats["analyze"] = True
    return res


def _default_prefix(ctx: QueryContext) -> List[str]:
    parts = []
    if ctx.order_by:
        keys = ", ".join(to_sql(o.expr) + (" DESC" if o.desc else "")
                         for o in ctx.order_by)
        parts.append(f"sort:[{keys}]")
    parts.append(f"limit:{ctx.limit}")
    if ctx.having is not None:
        parts.append(f"having:{to_sql(ctx.having)}")
    prefix = [f"BROKER_REDUCE({', '.join(parts)})"]
    if ctx.is_aggregation_query or ctx.distinct:
        prefix.append("COMBINE_GROUP_BY" if (ctx.group_by or ctx.distinct)
                      else "COMBINE_AGGREGATE")
    else:
        prefix.append("COMBINE_SELECT")
    return prefix
