"""AST-level filter optimizer: rewrites applied once at compile time.

Analog of the reference's filter optimizer chain
(`pinot-core/src/main/java/org/apache/pinot/core/query/optimizer/filter/`):

* MergeEqInFilterOptimizer  — OR of EQ/IN on one column -> one IN (here: one
  LUT leaf / id-interval set on the device, instead of N separate leaf masks)
* MergeRangeFilterOptimizer — AND of ranges on one column -> one BETWEEN
* IdenticalPredicateFilterOptimizer — duplicate subtrees collapse
* FlattenAndOrFilterOptimizer — nested AND/OR flattening (predicate._simplify
  also flattens during compile; flattening here lets the merges above see
  siblings)

Runs BEFORE per-segment predicate compilation, so every segment benefits and
the rewritten tree is what EXPLAIN shows.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..sql.ast import Expr, Function, Identifier, Literal

_RANGE_OPS = {"gt", "gte", "lt", "lte", "between"}


def optimize_filter(e: Optional[Expr], schema=None) -> Optional[Expr]:
    if e is None or not isinstance(e, Function):
        return e
    return _dedupe(_merge(_flatten(e), schema))


def _flatten(e: Expr) -> Expr:
    if not isinstance(e, Function):
        return e
    args = tuple(_flatten(a) for a in e.args)
    if e.name in ("and", "or"):
        flat: List[Expr] = []
        for a in args:
            if isinstance(a, Function) and a.name == e.name:
                flat.extend(a.args)
            else:
                flat.append(a)
        return Function(e.name, tuple(flat))
    return Function(e.name, args, e.distinct)


def _eq_in_column(e: Expr) -> Optional[Tuple[str, List]]:
    """(column, values) when e is EQ/IN over a plain column and literals."""
    if isinstance(e, Function) and e.name in ("eq", "in") \
            and isinstance(e.args[0], Identifier) \
            and all(isinstance(a, Literal) for a in e.args[1:]):
        return e.args[0].name, [a.value for a in e.args[1:]]
    return None


def _range_bounds(e: Expr):
    """(column, lo, lo_inc, hi, hi_inc) for a range predicate over a column."""
    if not (isinstance(e, Function) and e.name in _RANGE_OPS
            and isinstance(e.args[0], Identifier)
            and all(isinstance(a, Literal) for a in e.args[1:])):
        return None
    col = e.args[0].name
    if e.name == "between":
        return col, e.args[1].value, True, e.args[2].value, True
    v = e.args[1].value
    return {
        "gt": (col, v, False, None, True),
        "gte": (col, v, True, None, True),
        "lt": (col, None, True, v, False),
        "lte": (col, None, True, v, True),
    }[e.name]


def _merge(e: Expr, schema=None) -> Expr:
    if not isinstance(e, Function):
        return e
    args = [_merge(a, schema) for a in e.args]

    if e.name == "or":
        # MergeEqInFilter: OR of EQ/IN per column -> one IN
        by_col: Dict[str, List] = {}
        rest: List[Expr] = []
        for a in args:
            hit = _eq_in_column(a)
            if hit is not None:
                by_col.setdefault(hit[0], []).extend(hit[1])
            else:
                rest.append(a)
        for col, values in by_col.items():
            uniq = list(dict.fromkeys(values))  # order-stable dedupe
            if len(uniq) == 1:
                rest.append(Function("eq", (Identifier(col), Literal(uniq[0]))))
            else:
                rest.append(Function("in", (Identifier(col),
                                            *[Literal(v) for v in uniq])))
        return rest[0] if len(rest) == 1 else Function("or", tuple(rest))

    if e.name == "and":
        # MergeRangeFilter: AND of ranges per column -> tightest single range.
        # ONLY for provably single-value columns: an MV column's conjuncts use
        # ANY-value semantics ("some value >= 5 AND some value <= 10" can be
        # satisfied by DIFFERENT values), which a merged BETWEEN would break —
        # the reference's MergeRangeFilterOptimizer has the same SV guard.
        per_col: Dict[str, List] = {}
        originals: Dict[str, List[Expr]] = {}
        rest: List[Expr] = []
        for a in args:
            rb = _range_bounds(a)
            if rb is None or not _mergeable_sv_column(rb[0], schema):
                rest.append(a)
            else:
                per_col.setdefault(rb[0], []).append(rb[1:])
                originals.setdefault(rb[0], []).append(a)
        for col, items in per_col.items():
            merged = _merge_range_items(col, items)
            if merged is None:  # mixed literal type families: don't touch
                rest.extend(originals[col])
            else:
                rest.append(merged)
        return rest[0] if len(rest) == 1 else Function("and", tuple(rest))

    return Function(e.name, tuple(args), e.distinct)


def _value_family(v) -> Optional[str]:
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return "num"
    if isinstance(v, str):
        return "str"
    return None


def _merge_range_items(col: str, items: List[Tuple]) -> Optional[Expr]:
    """Fold (lo, lo_inc, hi, hi_inc) conjuncts to the tightest range; None when
    literal families mix (e.g. `v > 5 AND v > '3'`) — cross-type comparison
    would raise, and the per-type normalization downstream already copes."""
    fams = {_value_family(b) for lo, _, hi, _ in items
            for b in (lo, hi) if b is not None}
    if len(fams) != 1 or None in fams:
        return None
    key = (lambda v: float(v)) if fams == {"num"} else (lambda v: v)
    lo = hi = None
    lo_inc = hi_inc = True
    for b_lo, b_lo_inc, b_hi, b_hi_inc in items:
        if b_lo is not None:
            if lo is None or key(b_lo) > key(lo):
                lo, lo_inc = b_lo, b_lo_inc
            elif key(b_lo) == key(lo):
                lo_inc = lo_inc and b_lo_inc
        if b_hi is not None:
            if hi is None or key(b_hi) < key(hi):
                hi, hi_inc = b_hi, b_hi_inc
            elif key(b_hi) == key(hi):
                hi_inc = hi_inc and b_hi_inc
    return _range_expr(col, lo, lo_inc, hi, hi_inc)


def _mergeable_sv_column(col: str, schema) -> bool:
    """Range merge requires knowing the column is single-value."""
    if schema is None or not schema.has_column(col):
        return False
    return schema.field_spec(col).single_value


def _range_expr(col: str, lo, lo_inc: bool, hi, hi_inc: bool) -> Expr:
    ident = Identifier(col)
    if lo is not None and hi is not None and lo_inc and hi_inc:
        return Function("between", (ident, Literal(lo), Literal(hi)))
    parts: List[Expr] = []
    if lo is not None:
        parts.append(Function("gte" if lo_inc else "gt", (ident, Literal(lo))))
    if hi is not None:
        parts.append(Function("lte" if hi_inc else "lt", (ident, Literal(hi))))
    if not parts:  # unbounded on both sides cannot happen (caller guards)
        return Function("eq", (Literal(1), Literal(1)))
    return parts[0] if len(parts) == 1 else Function("and", tuple(parts))


def _dedupe(e: Expr) -> Expr:
    """IdenticalPredicateFilter: equal siblings under AND/OR collapse to one."""
    if not isinstance(e, Function):
        return e
    args = [_dedupe(a) for a in e.args]
    if e.name in ("and", "or"):
        seen = {}
        for a in args:
            seen.setdefault(repr(a), a)
        uniq = list(seen.values())
        return uniq[0] if len(uniq) == 1 else Function(e.name, tuple(uniq))
    return Function(e.name, tuple(args), e.distinct)
