"""QueryContext: the compiled, resolved representation a server executes.

Analog of `pinot-core/.../query/request/context/QueryContext.java:72` plus the broker-side
query rewriters (`pinot-common/.../sql/parsers/rewriter/`): alias and ordinal resolution for
GROUP BY / ORDER BY / HAVING, aggregation extraction, and column validation happen here, so
the execution engine below sees only resolved expression trees.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..schema import Schema
from ..sql.ast import (Expr, Function, Identifier, Literal, OrderByItem, QueryStatement,
                       contains_aggregation, identifiers_in, is_aggregation, walk)
from ..sql.parser import parse_query


class QueryValidationError(ValueError):
    pass


@dataclass
class GapfillSpec:
    """GAPFILL(timeExpr, start, end, bucket) + per-column FILL modes (reference:
    `core/query/reduce/GapfillProcessor.java` family, broker post-processing)."""

    index: int               # select-item position of the time bucket column
    start: int
    end: int                 # exclusive
    bucket: int
    fills: Dict[int, Tuple[str, object]] = field(default_factory=dict)
    # select-item position -> (mode, default); modes: FILL_PREVIOUS_VALUE,
    # FILL_DEFAULT_VALUE


@dataclass
class QueryContext:
    table: str
    select_items: List[Tuple[Expr, str]]            # (resolved expr, output column name)
    filter: Optional[Expr]
    group_by: List[Expr]
    aggregations: List[Function]                    # unique aggregation calls, in order
    having: Optional[Expr]
    order_by: List[OrderByItem]
    limit: int
    offset: int
    distinct: bool
    options: Dict[str, object] = field(default_factory=dict)
    gapfill: Optional[GapfillSpec] = None
    sql: str = ""   # original SQL text; the HTTP transport re-compiles server-side
    explain: bool = False
    analyze: bool = False  # EXPLAIN ANALYZE: execute, then annotate the plan

    @property
    def is_aggregation_query(self) -> bool:
        return bool(self.aggregations) or bool(self.group_by)

    @property
    def output_names(self) -> List[str]:
        return [name for _, name in self.select_items]


def compile_query(sql_or_stmt, schema: Optional[Schema] = None) -> QueryContext:
    """SQL text / parsed statement -> QueryContext.

    Mirrors BaseBrokerRequestHandler compile steps
    (`pinot-broker/.../BaseBrokerRequestHandler.java:207` onwards): parse, rewrite
    aliases/ordinals, extract aggregations, validate against the schema when given.
    """
    stmt = parse_query(sql_or_stmt) if isinstance(sql_or_stmt, str) else sql_or_stmt
    if stmt.joins:
        raise QueryValidationError(
            "JOIN queries run on the multistage engine (multistage/)"
        )

    # -- expand SELECT *, strip GAPFILL/FILL wrappers ----------------------
    select: List[Tuple[Expr, str]] = []
    gapfill: Optional[GapfillSpec] = None
    fills: Dict[int, Tuple[str, object]] = {}
    for expr, alias in stmt.select:
        if isinstance(expr, Identifier) and expr.name == "*":
            if schema is None:
                raise QueryValidationError("SELECT * requires a schema to expand")
            select.extend((Identifier(c), c) for c in schema.column_names)
            continue
        if isinstance(expr, Function) and expr.name == "gapfill":
            if gapfill is not None:
                raise QueryValidationError("only one GAPFILL column is allowed")
            if len(expr.args) != 4 or not all(
                    isinstance(a, Literal) for a in expr.args[1:]):
                raise QueryValidationError(
                    "GAPFILL(timeExpr, start, end, bucket) with literal bounds")
            gapfill = GapfillSpec(index=len(select), start=int(expr.args[1].value),
                                  end=int(expr.args[2].value),
                                  bucket=int(expr.args[3].value))
            if gapfill.bucket <= 0:
                raise QueryValidationError("GAPFILL bucket must be positive")
            expr = expr.args[0]
        elif isinstance(expr, Function) and expr.name == "fill":
            if len(expr.args) < 2 or not isinstance(expr.args[1], Literal):
                raise QueryValidationError("FILL(expr, 'MODE'[, default])")
            mode = str(expr.args[1].value).upper()
            if mode not in ("FILL_PREVIOUS_VALUE", "FILL_DEFAULT_VALUE"):
                raise QueryValidationError(f"unknown FILL mode {mode!r}")
            default = expr.args[2].value if len(expr.args) > 2 else None
            fills[len(select)] = (mode, default)
            expr = expr.args[0]
        select.append((expr, alias or _default_name(expr)))
    if gapfill is not None:
        gapfill.fills = fills
    elif fills:
        raise QueryValidationError("FILL requires a GAPFILL column in the select list")

    alias_map = {name: expr for expr, name in select}

    # -- resolve ordinals + aliases in GROUP BY / ORDER BY / HAVING --------
    group_by = [_resolve(e, select, alias_map) for e in stmt.group_by]
    order_by = [OrderByItem(_resolve(o.expr, select, alias_map), o.desc, o.nulls_last)
                for o in stmt.order_by]
    having = _resolve(stmt.having, select, alias_map) if stmt.having is not None else None

    # -- collect unique aggregations over every result-shaping expression --
    aggregations: List[Function] = []
    seen = set()
    for e in ([expr for expr, _ in select] + [o.expr for o in order_by]
              + ([having] if having is not None else [])):
        for node in walk(e):
            if is_aggregation(node):
                key = repr(node)
                if key not in seen:
                    seen.add(key)
                    aggregations.append(node)
                    _validate_aggregation(node)

    # -- validation --------------------------------------------------------
    if stmt.where is not None and contains_aggregation(stmt.where):
        raise QueryValidationError("aggregation not allowed in WHERE clause")
    if aggregations and not stmt.distinct:
        group_keys = {repr(g) for g in group_by}
        for expr, name in select:
            if not contains_aggregation(expr) and repr(expr) not in group_keys:
                raise QueryValidationError(
                    f"select item {name!r} is neither aggregated nor in GROUP BY")
    if schema is not None:
        exprs = [e for e, _ in select] + group_by + [o.expr for o in order_by]
        if stmt.where is not None:
            exprs.append(stmt.where)
        if having is not None:
            exprs.append(having)
        for e in exprs:
            for col in identifiers_in(e):
                if not schema.has_column(col):
                    raise QueryValidationError(f"unknown column {col!r}")

    return QueryContext(
        table=stmt.table,
        select_items=select,
        # AST-level filter rewrites (merge EQ->IN, range tightening, dedupe) —
        # reference: core/query/optimizer/filter/ chain in BrokerRequestOptimizer
        filter=_optimize_filter(stmt.where, schema),
        group_by=group_by,
        aggregations=aggregations,
        having=having,
        order_by=order_by,
        limit=stmt.limit,
        offset=stmt.offset,
        distinct=stmt.distinct,
        options=dict(stmt.options),
        explain=stmt.explain,
        analyze=stmt.analyze,
        gapfill=gapfill,
        sql=stmt.raw or (sql_or_stmt if isinstance(sql_or_stmt, str) else ""),
    )


def _resolve(e: Expr, select: List[Tuple[Expr, str]], alias_map: Dict[str, Expr]) -> Expr:
    """Resolve ordinals (GROUP BY 1) and select aliases (ORDER BY total).

    Ordinals only apply to a *whole* GROUP BY/ORDER BY item (top level); a literal inside
    an expression (HAVING COUNT(*) > 2) stays a literal. Aliases resolve at any depth.
    """
    if isinstance(e, Literal) and isinstance(e.value, int) and not isinstance(e.value, bool):
        idx = e.value - 1
        if 0 <= idx < len(select):
            return select[idx][0]
        raise QueryValidationError(f"ordinal {e.value} out of range")
    return _resolve_aliases(e, alias_map)


def _resolve_aliases(e: Expr, alias_map: Dict[str, Expr]) -> Expr:
    if isinstance(e, Identifier) and e.name in alias_map:
        return alias_map[e.name]
    if isinstance(e, Function):
        return Function(e.name, tuple(_resolve_aliases(a, alias_map) for a in e.args),
                        e.distinct)
    return e


def _validate_aggregation(f: Function) -> None:
    for a in f.args:
        if contains_aggregation(a):
            raise QueryValidationError(f"nested aggregation in {f!r}")
    if f.name == "count" and not f.args:
        raise QueryValidationError("COUNT requires an argument (use COUNT(*))")


def _default_name(e: Expr) -> str:
    """Output column name for an unaliased select expression (reference naming:
    `count(*)` style lowercase canonical forms)."""
    if isinstance(e, Identifier):
        return e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Function):
        inner = ",".join(_default_name(a) for a in e.args)
        d = "distinct " if e.distinct else ""
        return f"{e.name}({d}{inner})"
    return repr(e)


def _optimize_filter(e, schema=None):
    from .optimizer import optimize_filter
    return optimize_filter(e, schema)
