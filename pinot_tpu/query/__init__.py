"""Query compilation and execution: context, planner, executor, combine/reduce."""

from .context import QueryContext, QueryValidationError, compile_query

__all__ = ["QueryContext", "QueryValidationError", "compile_query"]
