"""Star-tree query execution: fit check, query rewrite, state reassembly.

Analog of `StarTreeUtils.isFitForStarTree` (`pinot-core/.../startree/StarTreeUtils.java:144`)
+ `StarTreeAggregationExecutor`/`StarTreeGroupByExecutor`. A fitting query is rewritten
onto the pre-aggregated record table (`segment/startree.py` StarTreeView): each original
aggregation decomposes into SUM/MIN/MAX "slots" over the stored partial columns
(COUNT(*) -> SUM($count), AVG(c) -> SUM($sum__c)/SUM($count), ...), the host-side tree
traversal supplies a record mask (riding the executor's valid-docs path), and the regular
fused device kernel runs over the mini-table. Slot states reassemble into the original
aggregation's merge state, so cross-segment combine is oblivious to which segments
answered from a star-tree.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..segment.startree import COUNT_COL, StarTree, metric_col
from ..sql.ast import Function, Identifier, identifiers_in
from .context import QueryContext
from .predicate import LutLeaf, compile_filter


@dataclass
class StarTreePlan:
    tree: StarTree
    ctx2: QueryContext                       # slot query against the view
    record_mask: np.ndarray                  # traversal-selected records
    slots_per_agg: List[List[int]]           # original agg -> slot indices
    assemble: List[Callable[[List[Any]], Any]]


def _is_count_star(f: Function) -> bool:
    return f.name == "count" and (not f.args or
                                  (isinstance(f.args[0], Identifier)
                                   and f.args[0].name == "*"))


def try_star_tree(ctx: QueryContext, segment) -> Optional[StarTreePlan]:
    """Return a star-tree plan when one of the segment's trees fits the query."""
    if ctx.distinct or not ctx.aggregations:
        return None
    if ctx.filter is None and not ctx.group_by:
        return None  # metadata-only path on the base segment is already optimal
    trees = getattr(segment, "star_trees", None) or []
    for st in trees:
        plan = _fit(ctx, st)
        if plan is not None:
            return plan
    return None


def _fit(ctx: QueryContext, st: StarTree) -> Optional[StarTreePlan]:
    dim_set = set(st.dims)

    group_dims: Set[str] = set()
    for e in ctx.group_by:
        if not isinstance(e, Identifier) or e.name not in dim_set:
            return None
        group_dims.add(e.name)

    filter_dims: Set[str] = set()
    if ctx.filter is not None:
        filter_dims = set(identifiers_in(ctx.filter))
        if not filter_dims <= dim_set:
            return None

    # -- aggregation decomposition ----------------------------------------
    pairs = st.storable_pairs()
    slot_calls: List[Function] = []
    slot_index: Dict[str, int] = {}

    def slot(func: str, col: str) -> int:
        call = Function(func, (Identifier(col),))
        key = repr(call)
        if key not in slot_index:
            slot_index[key] = len(slot_calls)
            slot_calls.append(call)
        return slot_index[key]

    slots_per_agg: List[List[int]] = []
    assemble: List[Callable[[List[Any]], Any]] = []
    for f in ctx.aggregations:
        if _is_count_star(f):
            slots_per_agg.append([slot("sum", COUNT_COL)])
            assemble.append(lambda s: 0 if s[0] is None else int(round(s[0])))
            continue
        if len(f.args) != 1 or not isinstance(f.args[0], Identifier) or f.distinct:
            return None
        col = f.args[0].name
        if f.name == "sum" and ("sum", col) in pairs:
            slots_per_agg.append([slot("sum", metric_col("sum", col))])
            assemble.append(lambda s: s[0])
        elif f.name == "min" and ("min", col) in pairs:
            slots_per_agg.append([slot("min", metric_col("min", col))])
            assemble.append(lambda s: s[0])
        elif f.name == "max" and ("max", col) in pairs:
            slots_per_agg.append([slot("max", metric_col("max", col))])
            assemble.append(lambda s: s[0])
        elif f.name == "avg" and ("sum", col) in pairs:
            slots_per_agg.append([slot("sum", metric_col("sum", col)),
                                  slot("sum", COUNT_COL)])
            assemble.append(lambda s: (float(s[0] or 0.0),
                                       0 if s[1] is None else int(round(s[1]))))
        elif f.name == "minmaxrange" and ("min", col) in pairs and ("max", col) in pairs:
            slots_per_agg.append([slot("min", metric_col("min", col)),
                                  slot("max", metric_col("max", col))])
            assemble.append(lambda s: None if s[0] is None else (s[0], s[1]))
        else:
            return None

    # -- filter must compile to pure dict-id LUT leaves over tree dims -----
    view = st.view
    prune_luts: Dict[str, np.ndarray] = {}
    if ctx.filter is not None:
        try:
            prog = compile_filter(ctx.filter, view)
        except Exception:
            return None
        if not all(isinstance(l, LutLeaf) for l in prog.leaves):
            return None
        # conjunctive-only trees allow per-dimension child pruning during traversal
        tree = prog.tree
        conj = [tree] if tree[0] == "leaf" else \
            list(tree[1]) if tree[0] == "and" else []
        if conj and all(c[0] == "leaf" for c in conj):
            for c in conj:
                leaf = prog.leaves[c[1]]
                if leaf.col in prune_luts:
                    prune_luts[leaf.col] = prune_luts[leaf.col] & leaf.lut
                else:
                    prune_luts[leaf.col] = leaf.lut

    record_mask = st.traverse(group_dims | filter_dims, prune_luts)

    ctx2 = QueryContext(
        table=ctx.table,
        select_items=[(c, f"slot{i}") for i, c in enumerate(slot_calls)]
        + [(e, repr(e)) for e in ctx.group_by],
        filter=ctx.filter,
        group_by=list(ctx.group_by),
        aggregations=slot_calls,
        having=None,
        order_by=[],
        limit=ctx.limit,
        offset=0,
        distinct=False,
        options=dict(ctx.options),
    )
    return StarTreePlan(st, ctx2, record_mask, slots_per_agg, assemble)


def reassemble(plan: StarTreePlan, sub) -> None:
    """Rewrite the slot-query SegmentResult's states into original-agg states, in
    place. `sub.kind` is 'groups' or 'scalar'."""
    if sub.kind == "groups":
        for key, states in sub.groups.items():
            sub.groups[key] = [asm([states[i] for i in slots])
                               for slots, asm in zip(plan.slots_per_agg, plan.assemble)]
    elif sub.kind == "scalar" and sub.scalar is not None:
        sub.scalar = [asm([sub.scalar[i] for i in slots])
                      for slots, asm in zip(plan.slots_per_agg, plan.assemble)]
