"""Filter compilation: predicate AST + segment dictionaries -> device filter program.

Analog of the reference's predicate evaluators
(`pinot-core/.../operator/filter/predicate/`, 13 factories): every predicate over a
dict-encoded column is resolved host-side against the *sorted dictionary* into a boolean
lookup table (LUT) over dict ids, so on device it is one gather (`lut[ids]`) regardless of
whether it was EQ/IN/RANGE/LIKE/REGEXP. Predicates over raw numeric columns (and arbitrary
expressions — the reference's `ExpressionFilterOperator`) compile to vectorized comparisons
with scalar operands passed as runtime inputs, keeping the jit kernel reusable across
literal changes.

Integer normalization: float literals against integer expressions are normalized host-side
(`x > 2.5` -> `x >= 3`) so the device compares integers exactly instead of in float32.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..segment.reader import ColumnReader, ImmutableSegment
from ..sql.ast import Expr, Function, Identifier, Literal
from .context import QueryValidationError

# filter tree: ("and"|"or", (children...)) | ("not", child) | ("leaf", index) | ("const", bool)
FilterTree = Tuple


# A LUT whose true-set decomposes into at most this many contiguous id runs is
# evaluated on device as interval compares over the id vector — zero gathers, zero
# matmuls. Sorted dictionaries make this the common case: EQ is one run, RANGE is one
# run, small IN-lists are <= k runs. (The axon TPU relay serializes every gather into
# an extra host round trip, so gather-free filters are the difference between the
# latency floor and multiples of it.)
MAX_LUT_INTERVALS = 8


def _lut_intervals(lut: np.ndarray) -> Optional[List[Tuple[int, int]]]:
    """Decompose a boolean LUT into inclusive [lo, hi] runs of True, or None if the
    decomposition exceeds MAX_LUT_INTERVALS (dense scattered sets: big IN / LIKE)."""
    idx = np.flatnonzero(lut)
    if len(idx) == 0:
        return []
    breaks = np.flatnonzero(np.diff(idx) > 1)
    if len(breaks) + 1 > MAX_LUT_INTERVALS:
        return None
    starts = np.concatenate(([idx[0]], idx[breaks + 1]))
    ends = np.concatenate((idx[breaks], [idx[-1]]))
    return [(int(lo), int(hi)) for lo, hi in zip(starts, ends)]


@dataclass
class LutLeaf:
    """Dict-column predicate resolved to a boolean LUT over dict ids.

    `intervals` is the contiguous-run decomposition of the LUT (None when the true-set
    is too scattered): the device kernel evaluates intervals as id-range compares with
    runtime scalar operands, and falls back to a one-hot matmul (small dictionaries) or
    a gather (large ones) only for scattered sets.
    """
    col: str
    lut: np.ndarray  # bool[lut_size(card)] — padding ids map to False
    intervals: Optional[List[Tuple[int, int]]] = field(default=None)
    # source predicate (op + literal values), kept so the LUT can be REBUILT
    # against a different dictionary snapshot (mutable segments: dict ids remap
    # as the sorted dictionary grows). Excluded from signature(): same kernel.
    op: Optional[str] = field(default=None)
    values: Optional[List[Any]] = field(default=None)

    def __post_init__(self):
        if self.intervals is None:
            self.intervals = _lut_intervals(self.lut)

    def rebuild_lut(self, dictionary, cardinality: int) -> np.ndarray:
        """The same predicate resolved against another dictionary snapshot."""
        assert self.op is not None
        return build_lut(self.op, self.values, dictionary, cardinality)

    @property
    def kind(self) -> str:
        return "lut"

    def signature(self) -> Tuple:
        # interval count is structural (operand values are runtime inputs); scattered
        # LUTs key on size only, their contents are runtime inputs too
        mode = len(self.intervals) if self.intervals is not None else "dense"
        return ("lut", self.col, len(self.lut), mode)


@dataclass
class CmpLeaf:
    """Comparison of a device-evaluable numeric expression against scalar operands.

    op in {eq, neq, gt, gte, lt, lte, between, in}; operands live in the runtime scalar
    arrays (int slots for integer compares, float slots otherwise).
    """
    expr: Expr
    op: str
    operands: List[Any]
    is_int: bool

    @property
    def kind(self) -> str:
        return "cmp"

    def signature(self) -> Tuple:
        return ("cmp", repr(self.expr), self.op, len(self.operands), self.is_int)


@dataclass
class NullLeaf:
    col: str
    negated: bool  # True for IS NOT NULL

    @property
    def kind(self) -> str:
        return "null"

    def signature(self) -> Tuple:
        return ("null", self.col, self.negated)


@dataclass
class DocSetLeaf:
    """Predicate resolved host-side into a per-doc bitmap: JSON_MATCH / TEXT_MATCH.

    The reference's JsonMatchFilterOperator / TextMatchFilterOperator likewise resolve
    these against their index into a doc bitmap before the scan; on the device path the
    bitmap ships as a runtime input (padded bool vector) consumed by one load.
    """
    col: str
    desc: str
    mask: np.ndarray  # bool[num_docs]
    # fully identifies the mask's CONTENTS for a given immutable segment
    # (kind + every predicate parameter); "" = not content-addressable
    # (id-set leaves), never cache. Excluded from signature(): masks are
    # runtime inputs and must not fragment the kernel cache.
    cache_token: str = ""

    @property
    def kind(self) -> str:
        return "docset"

    def signature(self) -> Tuple:
        # mask contents are runtime inputs; only structure keys the kernel cache
        return ("docset", self.col)


Leaf = Union[LutLeaf, CmpLeaf, NullLeaf, DocSetLeaf]


@dataclass
class FilterProgram:
    tree: FilterTree = ("const", True)
    leaves: List[Leaf] = field(default_factory=list)

    def signature(self) -> Tuple:
        return (_tree_sig(self.tree), tuple(l.signature() for l in self.leaves))

    @property
    def is_match_all(self) -> bool:
        return self.tree == ("const", True)


def _tree_sig(tree: FilterTree) -> Tuple:
    kind = tree[0]
    if kind in ("and", "or"):
        return (kind, tuple(_tree_sig(c) for c in tree[1]))
    if kind == "not":
        return ("not", _tree_sig(tree[1]))
    return tree  # ("leaf", i) / ("const", b)


_RANGE_OPS = {"gt", "gte", "lt", "lte", "between"}
_NEGATIONS = {"neq": "eq", "not_in": "in", "not_like": "like"}
# boolean transform functions usable bare or as `f(...) = 1/0` comparisons
_BOOL_PREDICATES = {"in_id_set", "inidset", "json_match", "text_match"}


def _as_bool(v) -> Optional[bool]:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)) and v in (0, 1):
        return bool(v)
    if isinstance(v, str) and v.lower() in ("true", "false"):
        return v.lower() == "true"
    return None


def compile_filter(expr: Optional[Expr], segment: ImmutableSegment) -> FilterProgram:
    """Compile a WHERE tree for one segment (reference: FilterPlanNode.run, per-segment
    because dictionaries — and therefore LUT contents — are per-segment)."""
    prog = FilterProgram()
    if expr is None:
        return prog
    prog.tree = _compile_node(expr, segment, prog.leaves)
    prog.tree = _simplify(prog.tree)
    return prog


def _compile_node(e: Expr, seg: ImmutableSegment, leaves: List[Leaf]) -> FilterTree:
    if isinstance(e, Literal):
        return ("const", bool(e.value))
    if isinstance(e, Identifier):
        raise QueryValidationError(f"bare column {e.name!r} is not a boolean predicate")
    assert isinstance(e, Function)
    name = e.name
    if name == "and":
        return ("and", tuple(_compile_node(a, seg, leaves) for a in e.args))
    if name == "or":
        return ("or", tuple(_compile_node(a, seg, leaves) for a in e.args))
    if name == "not":
        return ("not", _compile_node(e.args[0], seg, leaves))
    if name in _NEGATIONS:
        return ("not", _compile_node(Function(_NEGATIONS[name], e.args), seg, leaves))
    if name == "eq" and len(e.args) == 2:
        # `IN_ID_SET(col,'…') = 1` / `TEXT_MATCH(col,'…') = 0` — the
        # reference's documented comparison form for boolean transform
        # functions (InIdSetTransformFunction and friends return 1/0):
        # normalize to the bare predicate / its negation. (`!= n` arrives
        # here too: _NEGATIONS rewrites neq to not(eq(...)) above.)
        for fn, lit in (e.args, e.args[::-1]):
            if isinstance(fn, Function) and fn.name in _BOOL_PREDICATES \
                    and isinstance(lit, Literal) and _as_bool(lit.value) is not None:
                node = _compile_node(fn, seg, leaves)
                return node if _as_bool(lit.value) else ("not", node)
    if name in ("is_null", "is_not_null"):
        col = e.args[0]
        if not isinstance(col, Identifier):
            raise QueryValidationError("IS NULL requires a plain column")
        leaves.append(NullLeaf(col.name, negated=(name == "is_not_null")))
        return ("leaf", len(leaves) - 1)
    if name in ("json_match", "text_match"):
        if len(e.args) != 2 or not isinstance(e.args[0], Identifier) \
                or not isinstance(e.args[1], Literal):
            raise QueryValidationError(f"{name.upper()}(column, 'filter') expected: {e!r}")
        col, arg = e.args[0], e.args[1]
        reader = seg.column(col.name)
        query = str(arg.value)
        try:
            if name == "json_match":
                # mutable (realtime) column readers carry no aux indexes -> scan fallback
                idx = getattr(reader, "json_index", None)
                if idx is not None:
                    mask = idx.match(query)
                else:
                    from ..segment.indexes.jsonidx import json_match_scan
                    mask = json_match_scan(reader.values(), query)
            else:
                idx = getattr(reader, "text_index", None)
                if idx is not None:
                    mask = idx.match(query)
                else:
                    from ..segment.indexes.text import text_match_scan
                    mask = text_match_scan(reader.values(), query)
        except (ValueError, AssertionError, IndexError, KeyError) as exc:
            raise QueryValidationError(f"{name.upper()}: {exc}") from exc
        leaves.append(DocSetLeaf(col.name, query, mask,
                                 cache_token=f"{name}:{query}"))
        return ("leaf", len(leaves) - 1)
    if name in ("in_id_set", "inidset"):
        # membership against a serialized IdSet literal (reference:
        # InIdSetTransformFunction). Dict column -> LUT once over the sorted
        # dictionary; raw column -> host doc mask (same shape as TEXT_MATCH).
        from .idset import IdSet, IdSetError
        if len(e.args) != 2 or not isinstance(e.args[0], Identifier) \
                or not isinstance(e.args[1], Literal):
            raise QueryValidationError(
                f"IN_ID_SET(column, 'serialized-idset') expected: {e!r}")
        col, lit = e.args[0], e.args[1]
        try:
            ids = IdSet.deserialize(str(lit.value))
        except IdSetError as exc:
            raise QueryValidationError(str(exc)) from exc
        reader = seg.column(col.name)
        if reader.has_dictionary:
            lut = build_lut("idset", [ids], reader.dictionary,
                            reader.cardinality)
            leaves.append(LutLeaf(col.name, lut, op="idset", values=[ids]))
        else:
            import hashlib
            mask = ids.contains(reader.values())
            # content-addressed token: the serialized literal IS the set
            digest = hashlib.sha1(str(lit.value).encode()).hexdigest()
            leaves.append(DocSetLeaf(col.name, f"idset[{len(ids)}]", mask,
                                     cache_token=f"idset:{digest}"))
        return ("leaf", len(leaves) - 1)
    geo = _try_geo_predicate(e, seg, leaves)
    if geo is not None:
        return geo
    if name in ("stwithin", "stcontains", "stequals"):
        # boolean geo function used directly as a predicate -> compare to true
        return _compile_predicate(Function("eq", (e, Literal(1))), seg, leaves)
    return _compile_predicate(e, seg, leaves)


def _try_geo_predicate(e: Function, seg: ImmutableSegment,
                       leaves: List[Leaf]):
    """`ST_DISTANCE(ST_POINT(lngCol, latCol), <const point>) < r`:
    geo-cell-index candidate mask (when the segment has one for the column
    pair) ANDed with the exact haversine compare — the H3 coarse-cover +
    exact-refine pattern (reference: H3IndexFilterOperator). Without an index
    the predicate still compiles: the rewrite below turns it into elementwise
    device math."""
    from ..engine.geo_fns import distance_predicate_parts
    parts = distance_predicate_parts(e)
    if parts is None:
        return None
    lng_col, lat_col, cx, cy, radius = parts
    exact = _compile_predicate(e, seg, leaves)  # rewrites to haversine inside
    geo_idx = None
    getter = getattr(seg, "geo_index", None)
    if getter is not None:
        geo_idx = getter(lng_col, lat_col)
    if geo_idx is None:
        return exact
    mask = geo_idx.candidate_mask(cx, cy, radius, seg.num_docs)
    leaves.append(DocSetLeaf(f"{lng_col},{lat_col}",
                             f"geo cells r={radius:g}m", mask,
                             cache_token=f"geo:{cx!r}:{cy!r}:{radius!r}"))
    return ("and", (("leaf", len(leaves) - 1), exact))


def _compile_predicate(e: Function, seg: ImmutableSegment, leaves: List[Leaf]) -> FilterTree:
    from ..engine.geo_fns import rewrite_geo
    lhs = e.args[0]
    rhs = list(e.args[1:])
    # normalize `literal op column` to `column op' literal`
    if isinstance(lhs, Literal) and len(rhs) == 1 and not isinstance(rhs[0], Literal):
        flip = {"eq": "eq", "gt": "lt", "gte": "lte", "lt": "gt", "lte": "gte"}
        if e.name in flip:
            lhs, rhs = rhs[0], [lhs]
            e = Function(flip[e.name], (lhs, *rhs))
    # AFTER the flip, so `r > stdistance(...)` rewrites too:
    # distance-over-columns -> elementwise device haversine
    lhs = rewrite_geo(lhs)
    if not all(isinstance(r, Literal) for r in rhs):
        raise QueryValidationError(f"predicate operands must be literals: {e!r}")
    values = [r.value for r in rhs]

    # dictionary-encoded single-column predicate -> LUT leaf
    if isinstance(lhs, Identifier):
        reader = seg.column(lhs.name)
        if reader.has_dictionary:
            leaves.append(LutLeaf(lhs.name, _build_lut(e.name, values, reader),
                                  op=e.name, values=values))
            return ("leaf", len(leaves) - 1)

    # raw column / expression predicate -> comparison leaf
    op, operands, is_int, const = _normalize_cmp(e.name, values, lhs, seg)
    if const is not None:
        return ("const", const)
    leaves.append(CmpLeaf(lhs, op, operands, is_int))
    return ("leaf", len(leaves) - 1)


def _build_lut(op: str, values: List[Any], reader: ColumnReader) -> np.ndarray:
    return build_lut(op, values, reader.dictionary, reader.cardinality,
                     fst_index=getattr(reader, "fst_index", None))


def build_lut(op: str, values: List[Any], d, cardinality: int,
              fst_index=None) -> np.ndarray:
    """Resolve a predicate against a specific dictionary snapshot. Factored out
    of the reader-based path so mutable segments can rebuild LUTs against the
    one dictionary snapshot the whole filter evaluates under."""
    from ..engine.datablock import lut_size  # local import to avoid jax at module import
    lut = np.zeros(lut_size(cardinality), dtype=bool)
    if op == "idset":
        if cardinality:
            lut[:cardinality] = values[0].contains(d._np_values)
    elif op == "eq":
        i = d.index_of(values[0])
        if i >= 0:
            lut[i] = True
    elif op == "in":
        lut[d.ids_for_values(values)] = True
    elif op == "between":
        lo, hi = d.id_range(values[0], values[1])
        lut[lo:hi] = True
    elif op in ("gt", "gte"):
        lo, hi = d.id_range(values[0], None, lower_inclusive=(op == "gte"))
        lut[lo:hi] = True
    elif op in ("lt", "lte"):
        lo, hi = d.id_range(None, values[0], upper_inclusive=(op == "lte"))
        lut[lo:hi] = True
    elif op == "like":
        lut[d.ids_matching_like(str(values[0]))] = True
    elif op == "regexp_like":
        # trigram FST-analog index prefilters the dictionary scan when present
        # (reference: FSTBasedRegexpPredicateEvaluatorFactory); falls back to
        # the full per-distinct-value regex otherwise
        ids = None
        if fst_index is not None:
            from ..segment.indexes.fst import ids_matching_regex_indexed
            ids = ids_matching_regex_indexed(fst_index, d.values, str(values[0]))
        if ids is None:
            ids = d.ids_matching_regex(str(values[0]))
        lut[ids] = True
    else:
        raise QueryValidationError(f"unsupported predicate {op} on dictionary column")
    return lut


def _normalize_cmp(op: str, values: List[Any], lhs: Expr, seg: ImmutableSegment):
    """Normalize operands for a raw/expression compare; returns (op, operands, is_int, const).

    const is a bool when the predicate folds to a constant (e.g. `int_col = 2.5` -> False).
    """
    is_int = _expr_is_integer(lhs, seg)
    if op == "like" or op == "regexp_like":
        raise QueryValidationError("LIKE/REGEXP on raw (non-dictionary) columns is unsupported")
    if not is_int:
        return op, [float(v) for v in values], False, None

    # integer expression: normalize float literals to exact integer comparisons
    if op == "eq":
        v = values[0]
        if float(v) != int(v):
            return op, [], True, False
        return op, [int(v)], True, None
    if op == "in":
        ints = [int(v) for v in values if float(v) == int(v)]
        if not ints:
            return op, [], True, False
        return op, ints, True, None
    if op == "between":
        lo, hi = math.ceil(values[0]), math.floor(values[1])
        if lo > hi:
            return op, [], True, False
        return op, [lo, hi], True, None
    if op == "gt":
        return "gte", [math.floor(values[0]) + 1], True, None
    if op == "gte":
        return "gte", [math.ceil(values[0])], True, None
    if op == "lt":
        return "lte", [math.ceil(values[0]) - 1], True, None
    if op == "lte":
        return "lte", [math.floor(values[0])], True, None
    raise QueryValidationError(f"unsupported comparison {op}")


def _expr_is_integer(e: Expr, seg: ImmutableSegment) -> bool:
    """Conservatively: integer iff all leaves are integer columns/literals and ops preserve
    integrality (no divide)."""
    if isinstance(e, Literal):
        return isinstance(e.value, int) and not isinstance(e.value, bool)
    if isinstance(e, Identifier):
        reader = seg.column(e.name)
        return np.dtype(reader.meta["fwdDtype"]).kind in "iu" and (
            not reader.has_dictionary or reader.data_type.is_numeric)
    if isinstance(e, Function):
        if e.name in ("plus", "minus", "times", "mod"):
            return all(_expr_is_integer(a, seg) for a in e.args)
        return False
    return False


def _simplify(tree: FilterTree) -> FilterTree:
    """Constant-fold and flatten (reference: filter optimizer, `core/query/optimizer/filter/`)."""
    kind = tree[0]
    if kind in ("and", "or"):
        absorb, identity = (False, True) if kind == "and" else (True, False)
        children = []
        for c in tree[1]:
            c = _simplify(c)
            if c[0] == "const":
                if c[1] == absorb:
                    return ("const", absorb)
                continue  # identity: drop
            if c[0] == kind:  # flatten nested and(and(...)) — reference: FlattenAndOrFilterOptimizer
                children.extend(c[1])
            else:
                children.append(c)
        if not children:
            return ("const", identity)
        if len(children) == 1:
            return children[0]
        return (kind, tuple(children))
    if kind == "not":
        c = _simplify(tree[1])
        if c[0] == "const":
            return ("const", not c[1])
        if c[0] == "not":
            return c[1]
        return ("not", c)
    return tree
