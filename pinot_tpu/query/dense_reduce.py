"""Vectorized decode of dense group-by kernel outputs straight to a ResultTable.

The classic decode walks occupied dense keys in a Python loop building one
state list per group (`executor._decode_group_partials`), then the broker
reduce walks them again to finalize (`reduce.reduce_to_result`). At high
cardinality that loop costs more than the fused kernel: ~2us/group x 20k-500k
groups dwarfs a ~39ms dispatch. This module decodes POST-COLLECTIVE (global)
kernel outputs for the common aggregation shapes entirely in numpy:

    counts > 0 -> occupied keys -> (vectorized order-by) -> offset/limit slice
    -> dictionary.take per group column + AggFunc.dense_values per agg -> rows

Exactly the reference's `GroupByDataTableReducer` job, vectorized over the
dense key space instead of a hash map of group keys
(`pinot-core/.../query/reduce/GroupByDataTableReducer.java`).

Applies only to FULL results (single server owning every segment, or the mesh
executor's post-psum outputs) — server partials that merge with other servers
keep the state-dict form. Falls back (returns None) whenever any shape needs
the classic path: non-dense-finalizable aggs (sketches/value sets), HAVING,
gapfill, DISTINCT rewrites, post-aggregation arithmetic in the select list,
or an ORDER BY that is not a plain group column / aggregation reference.

ORDER BY on a group column sorts by DICT IDS: dictionaries are sorted, so id
order IS value order — no value materialization for the sort keys.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .context import QueryContext
from .result import ResultTable


def _dense_capable(agg) -> bool:
    from .aggregates import AggFunc
    return type(agg).dense_values is not AggFunc.dense_values


def try_dense_decode(ctx: QueryContext, plan, outs) -> Optional[ResultTable]:
    """ResultTable from global dense kernel outputs, or None -> classic path."""
    if not plan.group_cols or ctx.having is not None or ctx.gapfill is not None \
            or ctx.distinct:
        return None
    if len(ctx.group_by) != len(plan.group_cols):
        return None
    if not all(_dense_capable(a) for a in plan.aggs):
        return None

    group_reprs = {repr(g): j for j, g in enumerate(ctx.group_by)}
    agg_reprs = {repr(call): i for i, call in enumerate(ctx.aggregations)}

    # select items must be plain group/agg references (no post-arithmetic)
    sel: list = []  # ("group", j) | ("agg", i)
    for expr, _name in ctx.select_items:
        r = repr(expr)
        if r in group_reprs:
            sel.append(("group", group_reprs[r]))
        elif r in agg_reprs:
            sel.append(("agg", agg_reprs[r]))
        else:
            return None
    order: list = []  # (("group", j) | ("agg", i), OrderByItem)
    for o in ctx.order_by or []:
        r = repr(o.expr)
        if r in group_reprs:
            order.append((("group", group_reprs[r]), o))
        elif r in agg_reprs:
            order.append((("agg", agg_reprs[r]), o))
        else:
            return None

    counts_all = np.asarray(outs["count"][:plan.num_keys_real])
    occupied = np.nonzero(counts_all > 0)[0]
    num_docs = int(counts_all.sum())
    counts = counts_all[occupied]

    def ids_for(j: int) -> np.ndarray:
        return (occupied // plan.strides[j]) % max(plan.cards[j], 1)

    agg_vals: dict = {}

    def agg_for(i: int) -> np.ndarray:
        v = agg_vals.get(i)
        if v is None:
            agg = plan.aggs[i]

            def get(name, i=i):
                if name == "count":
                    return counts
                return np.asarray(outs[f"{i}.{name}"][:plan.num_keys_real]
                                  )[occupied]

            v = agg.dense_values(get, counts)
            agg_vals[i] = v
        return v

    # -- ORDER BY over all occupied groups, then offset/limit ---------------
    if order:
        keys = []
        for (kind, idx), o in reversed(order):  # lexsort: last key primary
            arr = ids_for(idx) if kind == "group" else agg_for(idx)
            arr = np.asarray(arr, dtype=np.float64 if arr.dtype.kind == "f"
                             else np.int64)
            # NaN-as-null ranking, mirrored off reduce._sort_key: null sorts
            # as LARGEST unless NULLS FIRST/LAST overrides. Group dict ids are
            # never null on the device path; agg NaN means dense-null.
            is_null = (arr != arr) if arr.dtype.kind == "f" else None
            if is_null is not None and is_null.any():
                arr = np.where(is_null, 0.0, arr)
            keys.append(-arr if o.desc else arr)
            if is_null is not None and is_null.any():
                nulls_last = (o.nulls_last if o.nulls_last is not None
                              else not o.desc)
                keys.append(is_null if nulls_last else ~is_null)
        take = np.lexsort(keys)
    else:
        take = np.arange(len(occupied))
    take = take[ctx.offset:ctx.offset + ctx.limit]

    # -- materialize only the emitted slice ---------------------------------
    # rows build through ONE object ndarray + C-level tolist(): a Python
    # zip/list loop costs ~1us/row and would rival the kernel at 20k+ groups
    table = np.empty((len(take), len(sel)), dtype=object)
    nan_null_cols = []
    for ci, (kind, idx) in enumerate(sel):
        if kind == "group":
            ids_j = ids_for(idx)[take].astype(np.int64)
            col = plan.group_cols[idx]
            # typed-array tolist() converts np scalars -> Python values in C
            table[:, ci] = plan.segment.column(col).dictionary.take(
                ids_j).tolist()
        else:
            agg = plan.aggs[idx]
            table[:, ci] = np.asarray(agg_for(idx))[take].tolist()
            if agg.dense_nan_is_null:
                nan_null_cols.append(ci)
    rows = table.tolist()
    for ci in nan_null_cols:
        for r in rows:
            v = r[ci]
            if isinstance(v, float) and v != v:
                r[ci] = None
    return ResultTable([name for _, name in ctx.select_items], rows,
                       {"numDocsScanned": num_docs, "numGroups": len(occupied),
                        "denseReduce": True})
