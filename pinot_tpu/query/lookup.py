"""Lookup join: dimension tables fully resident per server + LOOKUP() transform.

Analog of `DimensionTableDataManager` (`pinot-core/.../data/manager/offline/
DimensionTableDataManager.java:50`) and `LookupTransformFunction`
(`core/operator/transform/function/LookupTransformFunction.java:65`):
a dimension table (small, replicated to every server) is loaded into a primary-key
hash map; `LOOKUP('dimTable', 'valueColumn', 'pkColumn', pkExpression, ...)` resolves
at scan time on the host path (strings/PK hashing are host-side work in the reference
scan path too).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..engine.expr import register_function


class DimensionTable:
    """PK -> row mapping over fully materialized columns."""

    def __init__(self, name: str, pk_columns: Sequence[str],
                 columns: Dict[str, np.ndarray]):
        self.name = name
        self.pk_columns = list(pk_columns)
        self.columns = {c: np.asarray(v) for c, v in columns.items()}
        # a table built from zero segments has no columns at all; treat it as an
        # empty dim table (every lookup misses) instead of KeyError-ing on the pk
        pk_arrays = ([self.columns[c] for c in self.pk_columns]
                     if all(c in self.columns for c in self.pk_columns) else [])
        n = len(pk_arrays[0]) if pk_arrays else 0
        self._index: Dict[Tuple, int] = {}
        for i in range(n):
            # last write wins on duplicate PKs, matching the reference's map put
            self._index[tuple(_py(a[i]) for a in pk_arrays)] = i

    def lookup_rows(self, pk_tuples: List[Tuple]) -> np.ndarray:
        """Row index per key; -1 for missing keys."""
        idx = np.empty(len(pk_tuples), dtype=np.int64)
        get = self._index.get
        for i, k in enumerate(pk_tuples):
            idx[i] = get(k, -1)
        return idx


class DimensionTableRegistry:
    """Server-wide registry (reference: DimensionTableDataManager statics)."""

    def __init__(self) -> None:
        self._tables: Dict[str, DimensionTable] = {}
        self._lock = threading.RLock()

    def register(self, table: DimensionTable) -> None:
        with self._lock:
            self._tables[table.name] = table

    def unregister(self, name: str) -> None:
        with self._lock:
            self._tables.pop(name, None)

    def get(self, name: str) -> Optional[DimensionTable]:
        with self._lock:
            return self._tables.get(name)


# process-wide default registry (one server per process in the reference too)
REGISTRY = DimensionTableRegistry()


def register_dim_table_from_segments(name: str, pk_columns: Sequence[str],
                                     segments) -> DimensionTable:
    """Materialize every segment's columns into one dimension table."""
    columns: Dict[str, List[np.ndarray]] = {}
    col_names: Optional[List[str]] = None
    for seg in segments:
        col_names = col_names or list(seg.column_names)
        for c in col_names:
            columns.setdefault(c, []).append(np.asarray(seg.column(c).values()))
    merged = {c: (np.concatenate([a.astype(object) for a in arrs])
                  if any(a.dtype == object for a in arrs) else np.concatenate(arrs))
              for c, arrs in columns.items()} if columns else {}
    table = DimensionTable(name, pk_columns, merged)
    REGISTRY.register(table)
    return table


def _py(v: Any) -> Any:
    return v.item() if isinstance(v, np.generic) else v


@register_function("lookup")
def _lookup(xp, table_name, value_col, *pk_pairs):
    """LOOKUP('dimTable', 'valueCol', 'pk1', expr1[, 'pk2', expr2...]).

    Missing keys produce Python None (object-dtype output); when every key hits, the
    value column's native dtype is preserved. Mirrors the reference's null-handling
    on lookup misses."""
    if xp is not np:
        raise ValueError("LOOKUP is host-side only")
    name = str(table_name)
    table = REGISTRY.get(name)
    if table is None:
        raise ValueError(f"dimension table {name!r} is not loaded")
    if len(pk_pairs) % 2 != 0 or not pk_pairs:
        raise ValueError("LOOKUP needs ('pkColumn', expression) pairs")
    pk_cols = [str(pk_pairs[i]) for i in range(0, len(pk_pairs), 2)]
    if pk_cols != table.pk_columns:
        raise ValueError(f"LOOKUP pk columns {pk_cols} != table pk {table.pk_columns}")
    exprs = [np.asarray(pk_pairs[i]) for i in range(1, len(pk_pairs), 2)]
    n = max((len(e) for e in exprs if e.ndim), default=1)
    tuples = list(zip(*[
        [_py(v) for v in (e if e.ndim else np.full(n, e.item()))] for e in exprs]))
    rows = table.lookup_rows(tuples)
    if str(value_col) not in table.columns:  # zero-segment table: every key misses
        return np.full(n, None, dtype=object)
    values = table.columns[str(value_col)]
    missing = rows < 0
    safe = np.clip(rows, 0, max(len(values) - 1, 0))
    if not missing.any() and len(values):
        return values[safe]  # keep the column's native dtype when every key hits
    # misses present: surface them as None in an object array so hits keep their
    # native values (int stays int) and the same column is type-stable across
    # segments with and without misses
    out = (values[safe].astype(object) if len(values)
           else np.full(n, None, dtype=object))
    out[missing] = None
    return out
