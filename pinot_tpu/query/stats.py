"""Typed per-query execution statistics (the tentpole of the telemetry layer).

Reference pattern: the reference's BrokerResponseNative metadata block
(numDocsScanned, numSegmentsQueried/Processed/Matched, numServersResponded,
timeUsedMs) plus ServerQueryPhase/BrokerQueryPhase timers — but carried as ONE
typed record created per request and threaded through
scatter -> server -> executor/pipeline -> partial -> wire -> combine -> reduce,
then merged back into `QueryResult.stats` under well-known keys.

Accounting sites publish through a thread-local "current stats" slot (same
pattern as `utils.trace`): the server activates a fresh record on its
execution thread, kernel/launch/fetch hooks `record()` into whatever record is
active (a no-op when none is — e.g. pipeline dispatcher threads serving many
queries at once, which attribute per-item launch stats explicitly instead),
and the record rides `SegmentResult.stats` back across the wire as a flat
summable dict. Per-operator rows/ms breakdowns (EXPLAIN ANALYZE) flatten into
the same dict under `op:<label>:rows` / `op:<label>:ms` keys so one merge rule
covers everything; the public export strips them.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

# -- well-known stats keys ---------------------------------------------------
# Every key the executor/broker can emit into `QueryResult.stats`, with the
# operator-facing meaning. README's "Observability" glossary and the tier-1
# drift-guard test are checked against THIS table: add a key here (and to
# README) before emitting it.
NUM_SEGMENTS_QUERIED = "numSegmentsQueried"
NUM_SEGMENTS_PRUNED = "numSegmentsPruned"
# per-pruner-kind breakdown of NUM_SEGMENTS_PRUNED: which pruner rejected the
# segment first (broker metadata pruners) or that the filter folded to
# constant-false server-side (the pre-existing numSegmentsPruned path)
NUM_SEGMENTS_PRUNED_BY_PARTITION = "numSegmentsPrunedByPartition"
NUM_SEGMENTS_PRUNED_BY_TIME = "numSegmentsPrunedByTime"
NUM_SEGMENTS_PRUNED_BY_RANGE = "numSegmentsPrunedByRange"
NUM_SEGMENTS_PRUNED_BY_BLOOM = "numSegmentsPrunedByBloom"
# docs that were never scanned because their segment was pruned (broker
# metadata pruning + server constant-false folds) — the "work avoided" number
SCAN_ROWS_AVOIDED = "scanRowsAvoided"
NUM_SEGMENTS_MATCHED = "numSegmentsMatched"
NUM_DOCS_SCANNED = "numDocsScanned"
DEVICE_LAUNCHES = "deviceLaunches"
COMPILE_CACHE_HITS = "compileCacheHits"
COMPILE_CACHE_MISSES = "compileCacheMisses"
COMPILE_MS = "compileMs"
DEVICE_EXEC_MS = "deviceExecMs"
DEVICE_FETCH_MS = "deviceFetchMs"
BYTES_FETCHED = "bytesFetched"
QUEUE_WAIT_MS = "queueWaitMs"
DEDUPED_LAUNCHES = "dedupedLaunches"
STACKED_LAUNCHES = "stackedLaunches"
# fused-vs-staged execution split (PR 16): fusedLaunches counts single-launch
# kernels that decode compressed forms (dict ids / FOR deltas) in-register;
# stagedLaunches counts the sub-launches of the two-dispatch fallback
# (mask kernel + aggregate kernel over decoded HBM columns)
FUSED_LAUNCHES = "fusedLaunches"
STAGED_LAUNCHES = "stagedLaunches"
NUM_CONSUMING_SEGMENTS_QUERIED = "numConsumingSegmentsQueried"
MIN_CONSUMING_FRESHNESS_TIME_MS = "minConsumingFreshnessTimeMs"
MUX_FRAME_QUEUE_MS = "muxFrameQueueMs"
MUX_FLOW_CONTROL_MS = "muxFlowControlMs"
COLLECTIVE_MS = "collectiveMs"
DEVICE_SKEW_PCT = "deviceSkewPct"
HEDGED_REQUESTS = "hedgedRequests"
ADMISSION_DEFER_MS = "admissionDeferMs"
# per-kernel cost-profile attribution (XLA cost_analysis at compile time,
# folded with live launch counters): modeled flops / bytes the query's device
# launches accounted for, and the achieved-vs-roofline bandwidth percentage
DEVICE_FLOPS = "deviceFlops"
DEVICE_BYTES_ACCESSED = "deviceBytesAccessed"
ROOFLINE_PCT = "rooflinePct"
# tiered-storage lifecycle: segments the admission gate kept OFF the device
# (served by the host plan instead of OOMing), segments freshly promoted
# host→HBM this query, and cold-tier segments lazily downloaded from the
# deep store on first query (+ the wall time those downloads took)
SEGMENTS_SERVED_HOST_TIER = "segmentsServedHostTier"
TIER_PROMOTIONS = "tierPromotions"
SEGMENTS_COLD_LOADED = "segmentsColdLoaded"
COLD_LOAD_MS = "coldLoadMs"
# device hash-join fast path (PR 17): wall time in the build-side sort /
# scatter launches and the probe launches (summed across join partitions),
# bytes exchanged between join stages, probe segments skipped by the
# build-key derived filter, and joins the admission gate priced off the
# device (served by the host hash_join instead of OOMing HBM)
JOIN_BUILD_MS = "joinBuildMs"
JOIN_PROBE_MS = "joinProbeMs"
JOIN_SHUFFLE_BYTES = "joinShuffleBytes"
NUM_SEGMENTS_PRUNED_BY_JOIN_KEY = "numSegmentsPrunedByJoinKey"
JOIN_SERVED_HOST_TIER = "joinServedHostTier"
# worst probe-key skew any join partition saw (hot-bucket excess percentage
# from the probe-hash histogram); max-merged like deviceSkewPct
JOIN_SKEW_PCT = "joinSkewPct"

# merged-counter keys always present in a query response (0 when the path
# never ran); `*Ms` keys round to 3 decimals on export
COUNTER_KEYS = (
    NUM_SEGMENTS_QUERIED, NUM_SEGMENTS_PRUNED,
    NUM_SEGMENTS_PRUNED_BY_PARTITION, NUM_SEGMENTS_PRUNED_BY_TIME,
    NUM_SEGMENTS_PRUNED_BY_RANGE, NUM_SEGMENTS_PRUNED_BY_BLOOM,
    SCAN_ROWS_AVOIDED, NUM_SEGMENTS_MATCHED,
    DEVICE_LAUNCHES, COMPILE_CACHE_HITS, COMPILE_CACHE_MISSES,
    COMPILE_MS, DEVICE_EXEC_MS, DEVICE_FETCH_MS, BYTES_FETCHED,
    QUEUE_WAIT_MS, DEDUPED_LAUNCHES, STACKED_LAUNCHES,
    FUSED_LAUNCHES, STAGED_LAUNCHES,
    NUM_CONSUMING_SEGMENTS_QUERIED, MUX_FRAME_QUEUE_MS, MUX_FLOW_CONTROL_MS,
    COLLECTIVE_MS, HEDGED_REQUESTS, ADMISSION_DEFER_MS,
    DEVICE_FLOPS, DEVICE_BYTES_ACCESSED,
    SEGMENTS_SERVED_HOST_TIER, TIER_PROMOTIONS,
    SEGMENTS_COLD_LOADED, COLD_LOAD_MS,
    JOIN_BUILD_MS, JOIN_PROBE_MS, JOIN_SHUFFLE_BYTES,
    NUM_SEGMENTS_PRUNED_BY_JOIN_KEY, JOIN_SERVED_HOST_TIER,
)

# keys that merge by MINIMUM instead of sum (reference: the broker reduces
# minConsumingFreshnessTimeMs across servers with Math.min — the answer is
# only as fresh as the STALEST consuming segment it touched). Absent on
# responses that touched no consuming segment; never zero-filled, because a
# zero-fill would poison every min-merge round.
MIN_KEYS = (MIN_CONSUMING_FRESHNESS_TIME_MS,)

# keys that merge by MAXIMUM: deviceSkewPct reports the WORST per-device
# exec-time imbalance any mesh launch saw (summing percentages across
# launches/servers is meaningless; the slowest chip bounds the query).
# Absent on responses that never took a multi-device mesh path.
# rooflinePct likewise keeps the BEST achieved-vs-roofline fetch window the
# query saw (sums are meaningless for percentages).
MAX_KEYS = (DEVICE_SKEW_PCT, ROOFLINE_PCT, JOIN_SKEW_PCT)

# the query's 16-hex plan-shape fingerprint (sql/fingerprint.py): stamped by
# the broker so any response / slow-log line / trace resolves to its shape
# profile at GET /debug/workload?fp=
WORKLOAD_FINGERPRINT = "workloadFingerprint"

# broker-level keys that live beside the merged counters in QueryResult.stats
# (listed so the glossary drift guard covers the full emitted surface)
BROKER_KEYS = (
    "timeUsedMs", NUM_DOCS_SCANNED, "numGroupsTotal", "numServersQueried",
    "numServersResponded", "partialResult", "phaseTimesMs", "traceInfo",
    "traceId", "gapfilled", "explain", "analyze", "joinStrategy",
    WORKLOAD_FINGERPRINT,
)

#: routing pruner kind (cluster.routing.PRUNER_KINDS) -> its breakdown counter
PRUNED_BY_KIND = {
    "partition": NUM_SEGMENTS_PRUNED_BY_PARTITION,
    "time": NUM_SEGMENTS_PRUNED_BY_TIME,
    "range": NUM_SEGMENTS_PRUNED_BY_RANGE,
    "bloom": NUM_SEGMENTS_PRUNED_BY_BLOOM,
}

_OP_PREFIX = "op:"


def op_key(label: str, field: str) -> str:
    return f"{_OP_PREFIX}{label}:{field}"


class ExecutionStats:
    """One query's execution accounting: a flat dict of summable counters
    (plus flattened per-operator entries consumed by EXPLAIN ANALYZE)."""

    __slots__ = ("counters", "_lock")

    def __init__(self, counters: Optional[Dict[str, float]] = None):
        self.counters: Dict[str, float] = dict(counters or {})
        self._lock = threading.Lock()

    def add(self, key: str, n: float = 1) -> None:
        with self._lock:
            # graftcheck: ignore[unbounded-keyed-accumulation] -- per-query
            # stats object; key space is the drift-guarded stat-key constants
            self.counters[key] = self.counters.get(key, 0) + n

    def set_min(self, key: str, v: float) -> None:
        """Keep the minimum seen for a min-merged key (no-op when `v` loses)."""
        with self._lock:
            cur = self.counters.get(key)
            self.counters[key] = v if cur is None else min(cur, v)

    def set_max(self, key: str, v: float) -> None:
        """Keep the maximum seen for a max-merged key (no-op when `v` loses)."""
        with self._lock:
            cur = self.counters.get(key)
            self.counters[key] = v if cur is None else max(cur, v)

    def add_operator(self, label: str, rows: float = 0, ms: float = 0.0) -> None:
        with self._lock:
            rk, mk = op_key(label, "rows"), op_key(label, "ms")
            self.counters[rk] = self.counters.get(rk, 0) + rows
            self.counters[mk] = self.counters.get(mk, 0) + ms

    def merge(self, other) -> None:
        """Fold another record (ExecutionStats or its flat dict form) into
        this one: every numeric key sums, except MIN_KEYS (MAX_KEYS) which
        keep the minimum (maximum) of the sides that carry the key."""
        if other is None:
            return
        src = other.counters if isinstance(other, ExecutionStats) else other
        if isinstance(other, ExecutionStats):
            with other._lock:
                src = dict(src)
        with self._lock:
            for k, v in src.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    if k in MIN_KEYS:
                        cur = self.counters.get(k)
                        self.counters[k] = v if cur is None else min(cur, v)
                    elif k in MAX_KEYS:
                        cur = self.counters.get(k)
                        self.counters[k] = v if cur is None else max(cur, v)
                    else:
                        self.counters[k] = self.counters.get(k, 0) + v

    def operators(self) -> Dict[str, Dict[str, float]]:
        """Reassemble the per-operator breakdown: label -> {rows, ms}."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for k, v in self.counters.items():
                if not k.startswith(_OP_PREFIX):
                    continue
                label, _, fld = k[len(_OP_PREFIX):].rpartition(":")
                out.setdefault(label, {"rows": 0, "ms": 0.0})[fld] = v
        return out

    def to_wire(self) -> Dict[str, float]:
        """Flat dict for `SegmentResult.stats` (keeps op:* entries)."""
        with self._lock:
            return dict(self.counters)

    def to_public_dict(self) -> Dict[str, object]:
        """Export for `QueryResult.stats`: every well-known counter (0 when
        untouched), ints for counts, rounded floats for `*Ms`; internal op:*
        breakdowns stay off the response (EXPLAIN ANALYZE consumes them)."""
        with self._lock:
            out: Dict[str, object] = {}
            for k in COUNTER_KEYS:
                v = float(self.counters.get(k, 0))
                out[k] = round(v, 3) if k.endswith("Ms") else int(v)
            for k, v in self.counters.items():
                if k not in out and not k.startswith(_OP_PREFIX):
                    # MIN_KEYS are epoch-ms timestamps, not durations: whole ms
                    out[k] = (round(float(v), 3)
                              if (k.endswith("Ms") and k not in MIN_KEYS)
                              or k.endswith("Pct")
                              else int(v))
            return out


# -- thread-local current record (mirrors utils.trace's _local pattern) ------

_local = threading.local()


def current_stats() -> Optional[ExecutionStats]:
    return getattr(_local, "stats", None)


def record(key: str, n: float = 1) -> None:
    """Accounting hook for hot paths: add to the active record if any.
    Deliberately tolerant — kernel/fetch sites run on threads that may serve
    many queries (pipeline dispatcher) or none (warmup/calibration), where
    per-query attribution happens elsewhere or not at all."""
    st = getattr(_local, "stats", None)
    if st is not None:
        st.add(key, n)


def record_min(key: str, v: float) -> None:
    """Min-merge accounting hook (freshness timestamps): keep the smallest
    value seen by the active record, if any."""
    st = getattr(_local, "stats", None)
    if st is not None:
        st.set_min(key, v)


def record_max(key: str, v: float) -> None:
    """Max-merge accounting hook (per-launch device skew): keep the largest
    value seen by the active record, if any."""
    st = getattr(_local, "stats", None)
    if st is not None:
        st.set_max(key, v)


def record_operator(label: str, rows: float = 0, ms: float = 0.0) -> None:
    st = getattr(_local, "stats", None)
    if st is not None:
        st.add_operator(label, rows=rows, ms=ms)


@contextmanager
def collect_stats(st: Optional[ExecutionStats] = None
                  ) -> Iterator[ExecutionStats]:
    """Install a (fresh) record as this thread's active stats for the scope."""
    st = st if st is not None else ExecutionStats()
    prev = getattr(_local, "stats", None)
    _local.stats = st
    try:
        yield st
    finally:
        _local.stats = prev


@contextmanager
def activate(st: ExecutionStats) -> Iterator[ExecutionStats]:
    """Re-install an existing record on a worker thread (scheduler slots,
    scatter pool) — the stats analog of `Trace.activate`."""
    prev = getattr(_local, "stats", None)
    _local.stats = st
    try:
        yield st
    finally:
        _local.stats = prev
