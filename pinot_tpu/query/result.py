"""Query result model: ResultTable + execution stats.

Analog of the reference's broker response
(`pinot-common/.../response/broker/BrokerResponseNative.java` / `ResultTable`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class ResultTable:
    columns: List[str]
    rows: List[List[Any]]
    stats: Dict[str, Any] = field(default_factory=dict)  # numDocsScanned, segments, timings

    def to_json(self) -> Dict[str, Any]:
        return {
            "resultTable": {
                "dataSchema": {"columnNames": self.columns},
                "rows": [[_jsonify(v) for v in row] for row in self.rows],
            },
            **self.stats,
        }

    def __repr__(self) -> str:
        return f"ResultTable({self.columns}, {len(self.rows)} rows)"


def _jsonify(v: Any) -> Any:
    import numpy as np
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v
