"""Per-segment plan maker: choose execution strategy and build kernel specs/inputs.

Analog of the reference's `InstancePlanMakerImplV2.makeSegmentPlanNode`
(`pinot-core/.../plan/maker/InstancePlanMakerImplV2.java:153,243,288`) + segment pruners
(`core/query/pruner/`): decide per segment whether the query runs as

* `metadata` — answered from segment metadata alone, no scan (reference:
  `NonScanBasedAggregationOperator`): COUNT(*)/MIN/MAX with no filter;
* `empty`    — pruned: filter folds to constant-false (bloom / min-max / dictionary miss);
* `device`   — the fused TPU kernel (aggregation/group-by hot path);
* `host`     — numpy fallback for shapes the device path doesn't cover yet
  (group-by on expressions/raw columns, percentile/mode, huge key spaces);
* `selection`— mask on device, gather + order on host.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..segment.reader import ImmutableSegment
from ..sql.ast import Expr, Function, Identifier, Literal, identifiers_in
from .aggregates import AggContext, AggFunc, make_agg
from .context import QueryContext, QueryValidationError
from .predicate import CmpLeaf, FilterProgram, LutLeaf, NullLeaf, compile_filter

# dense-key cap (reference caps group-by at 100k groups). Raised to 2M now
# that the sort-based kernel regimes (engine/kernels.py) keep per-key cost
# sublinear past the chunked-matmul cap instead of falling off a scatter cliff.
MAX_DEVICE_GROUP_KEYS = 1 << 21
# grouped distinct presence matrix cap: (padded keys) x (dict-id lut) int32 cells
MAX_GROUPED_DISTINCT_CELLS = 1 << 22  # 16MB of presence counts per aggregation

# Below this row count a single numpy pass beats any device dispatch on the
# relay-attached backend (star-tree record tables, small dimension tables).
SMALL_SCAN_DOCS = 1 << 16


def _relay_backend() -> bool:
    """True on a real accelerator backend (device dispatches pay host round
    trips); False under CPU jax, where tests keep full kernel coverage."""
    global _RELAY_BACKEND
    if _RELAY_BACKEND is None:
        import jax
        _RELAY_BACKEND = jax.default_backend() != "cpu"
    return _RELAY_BACKEND


_RELAY_BACKEND: Optional[bool] = None

from ..engine.datetime_fns import DEVICE_DATETIME_FUNCS

_DEVICE_FUNCS = {"plus", "minus", "times", "divide", "mod", "case", "cast", "abs", "ceil",
                 "floor", "exp", "ln", "log10", "log2", "log", "sqrt", "power", "round",
                 "least", "greatest", "sign", "truncate", "eq", "neq", "gt", "gte", "lt",
                 "lte", "and", "or", "not", "in", "not_in", "between", "sin", "cos", "tan",
                 "asin", "acos", "atan", "sinh", "cosh", "tanh", "cot", "atan2", "degrees",
                 "radians"} | set(DEVICE_DATETIME_FUNCS)


@dataclass
class SegmentPlan:
    kind: str  # metadata | empty | device | host | selection
    segment: ImmutableSegment
    ctx: QueryContext
    aggs: List[AggFunc] = field(default_factory=list)
    group_exprs: List[Expr] = field(default_factory=list)
    filter_prog: Optional[FilterProgram] = None
    # device group-by geometry
    group_cols: Tuple[str, ...] = ()
    cards: Tuple[int, ...] = ()
    strides: Tuple[int, ...] = ()
    num_keys_real: int = 0
    num_keys_pad: int = 0
    # upper bound on OCCUPIED groups (dictionary key-space product capped by
    # scanned docs): drives merge/decode strategy — array-form dense partials
    # vs per-group state dicts — without waiting for exact device counts
    card_hint: int = 0
    fallback_reason: str = ""
    # upsert: only rows set in this mask are visible (None = all rows)
    valid_docs: Optional[np.ndarray] = None
    # LUT-leaf indices the executor routed to the packed-word bitmap index
    # (select_bitmap_leaves; () when the knob is off or nothing qualifies)
    bitmap_leaves: Tuple[int, ...] = ()


def plan_segment(ctx: QueryContext, segment: ImmutableSegment,
                 valid_docs: Optional[np.ndarray] = None,
                 scan_docs: Optional[int] = None) -> SegmentPlan:
    """`scan_docs` overrides the row count the small-scan heuristic sees: the
    mesh path plans a whole SET from one probe segment and amortizes ONE
    dispatch across all of it, so it passes the set's total."""
    aggs = [make_agg(f) for f in ctx.aggregations]
    # DISTINCT rewrites to a group-by over the select expressions with no aggregations
    # (reference: DistinctOperator is a specialized group-by).
    if ctx.distinct:
        group_exprs = [e for e, _ in ctx.select_items]
    else:
        group_exprs = list(ctx.group_by)

    plan = SegmentPlan("host", segment, ctx, aggs, group_exprs)
    plan.valid_docs = valid_docs
    _validate_mv_usage(ctx, aggs, segment)
    for agg in aggs:
        agg.validate_args(segment)

    # -- filter compilation + constant-fold pruning ------------------------
    try:
        prog = compile_filter(ctx.filter, segment)
    except QueryValidationError:
        raise
    plan.filter_prog = _fold_leaves(prog, segment)
    if plan.filter_prog.tree == ("const", False):
        plan.kind = "empty"
        return plan

    if not ctx.is_aggregation_query and not ctx.distinct:
        plan.kind = "selection"
        return plan

    # -- metadata-only answers (unavailable under an upsert mask) ----------
    if (not group_exprs and plan.filter_prog.is_match_all and valid_docs is None
            and aggs and all(_metadata_answerable(a, segment) for a in aggs)):
        plan.kind = "metadata"
        return plan

    # -- device path feasibility ------------------------------------------
    if getattr(segment, "is_mutable", False):
        # consuming segments stay host-side; the TPU path starts at commit
        plan.kind = "host"
        plan.fallback_reason = "mutable (consuming) segment"
        return plan
    if (scan_docs if scan_docs is not None
            else segment.num_docs) <= SMALL_SCAN_DOCS and _relay_backend():
        # tiny scans (star-tree record tables, mini dimension tables): one
        # numpy pass costs microseconds while a device dispatch on the relay
        # backend pays a ~100ms host round trip per sync — the kernel can
        # never win below this size. CPU-jax (tests) keeps the device path
        # so kernel coverage is unaffected.
        plan.kind = "host"
        plan.fallback_reason = "small scan (host beats device dispatch)"
        return plan
    reason = _device_feasible(plan, segment)
    if reason:
        plan.kind = "host"
        plan.fallback_reason = reason
        return plan
    plan.kind = "device"
    # a group needs at least one row, so occupied groups <= min(key space, docs
    # actually scanned — the SET total on the mesh path, not the probe segment)
    if plan.card_hint:
        plan.card_hint = min(plan.card_hint,
                             scan_docs if scan_docs is not None
                             else segment.num_docs)
    return plan


def select_bitmap_leaves(plan: SegmentPlan,
                         segment: ImmutableSegment) -> Tuple[int, ...]:
    """LUT leaves worth evaluating through the packed-word bitmap index.

    Per-leaf regime choice (reference: the broker/server pruners choose
    index-vs-scan per predicate): a leaf qualifies when its column can carry a
    bitmap index (single-value dict column within BITMAP_MAX_CARD) AND its
    estimated selectivity sits at or below the calibrated
    `KernelCaps.bitmap_sel_cap`. Selectivity comes from the inverted index's
    posting offsets when the segment has one (exact, O(ids) arithmetic),
    otherwise from matched-ids / cardinality (uniform-occupancy assumption).
    Dense predicates keep the interval-compare / one-hot LUT path, which beats
    streaming the whole word matrix when most rows match anyway."""
    from ..engine.calibrate import get_caps
    from ..engine.datablock import BITMAP_MAX_CARD
    if plan.filter_prog is None or plan.filter_prog.is_match_all \
            or getattr(segment, "is_mutable", False):
        return ()
    cap = get_caps().bitmap_sel_cap
    n = max(segment.num_docs, 1)
    out = []
    for i, leaf in enumerate(plan.filter_prog.leaves):
        if not isinstance(leaf, LutLeaf):
            continue
        reader = segment.column(leaf.col)
        if not reader.has_dictionary \
                or getattr(reader, "is_multi_value", False):
            continue
        card = reader.cardinality
        if card <= 0 or card > BITMAP_MAX_CARD:
            continue
        matched = leaf.lut[:card]
        inv = getattr(reader, "inverted_index", None)
        if inv is not None:
            sel = inv.match_count_for_ids(np.flatnonzero(matched)) / n
        else:
            sel = float(matched.sum()) / card
        if sel <= cap:
            out.append(i)
    return tuple(out)


def _validate_mv_usage(ctx: QueryContext, aggs: List[AggFunc],
                       segment: ImmutableSegment) -> None:
    """Reject shapes whose semantics need the *MV function family, with a clear
    error instead of a deep numpy crash (reference: AggregationFunctionFactory
    rejects SV functions over MV arguments)."""
    def is_mv(name: str) -> bool:
        try:
            return getattr(segment.column(name), "is_multi_value", False)
        except KeyError:
            return False

    for agg in aggs:
        if (isinstance(agg.arg, Identifier) and agg.arg.name != "*"
                and is_mv(agg.arg.name)
                and not agg.name.endswith("mv") and agg.name != "count"):
            raise QueryValidationError(
                f"{agg.name.upper()} over multi-value column {agg.arg.name!r}: "
                f"use {agg.name.upper()}MV")
    # selection ORDER BY on an MV cell compares ragged arrays — undefined. (In a
    # group-by, ORDER BY the MV *group key* is fine: keys are scalars after the
    # explode; ARRAYLENGTH/CARDINALITY order keys are scalars too.)
    if not ctx.is_aggregation_query and not ctx.distinct:
        for o in ctx.order_by:
            if any(is_mv(c) for c in identifiers_in(o.expr)) \
                    and not (isinstance(o.expr, Function)
                             and o.expr.name in ("arraylength", "cardinality")):
                raise QueryValidationError(
                    f"ORDER BY over multi-value column in {o.expr!r} is undefined")


def _fold_leaves(prog: FilterProgram, segment: ImmutableSegment) -> FilterProgram:
    """Fold decidable leaves to constants — this is segment pruning for free: an EQ
    literal absent from the dictionary, or a range disjoint from a raw column's
    [min, max] metadata, folds the whole tree to constant-false (reference:
    ColumnValueSegmentPruner + dictionary-miss shortcut; bloom filters serve the same
    role for EQ in the cluster-level pruner, see cluster/routing)."""
    from .predicate import _simplify  # shared with filter compilation

    def fold(node):
        if node[0] == "leaf":
            leaf = prog.leaves[node[1]]
            if isinstance(leaf, LutLeaf):
                card = segment.column(leaf.col).cardinality
                if not leaf.lut.any():
                    return ("const", False)
                if leaf.lut[:card].all():
                    return ("const", True)
            if isinstance(leaf, NullLeaf):
                has_nulls = segment.column(leaf.col).meta.get("hasNulls", False)
                if not has_nulls:
                    return ("const", leaf.negated)
            if isinstance(leaf, CmpLeaf) and isinstance(leaf.expr, Identifier):
                folded = _fold_cmp_minmax(leaf, segment)
                if folded is None:
                    folded = _fold_cmp_bloom(leaf, segment)
                if folded is not None:
                    return ("const", folded)
            return node
        if node[0] in ("and", "or"):
            return (node[0], tuple(fold(c) for c in node[1]))
        if node[0] == "not":
            return ("not", fold(node[1]))
        return node

    prog.tree = _simplify(fold(prog.tree))
    return prog


def _fold_cmp_minmax(leaf: CmpLeaf, segment: ImmutableSegment):
    """Decide a raw-column comparison from metadata min/max when possible.

    Returns True (matches everything), False (matches nothing), or None (must scan).
    """
    reader = segment.column(leaf.expr.name)
    mn, mx = reader.min_value, reader.max_value
    if mn is None or mx is None or not leaf.operands:
        return None
    ops = leaf.operands
    if leaf.op == "eq":
        return False if (ops[0] < mn or ops[0] > mx) else None
    if leaf.op == "in":
        return False if all(v < mn or v > mx for v in ops) else None
    if leaf.op in ("gte", "gt"):
        if ops[0] <= mn and leaf.op == "gte":
            return True
        if ops[0] < mn:
            return True
        if ops[0] > mx or (ops[0] == mx and leaf.op == "gt"):
            return False
        return None
    if leaf.op in ("lte", "lt"):
        if ops[0] >= mx and leaf.op == "lte":
            return True
        if ops[0] > mx:
            return True
        if ops[0] < mn or (ops[0] == mn and leaf.op == "lt"):
            return False
        return None
    if leaf.op == "between":
        lo, hi = ops
        if lo <= mn and hi >= mx:
            return True
        if hi < mn or lo > mx:
            return False
    return None


def _fold_cmp_bloom(leaf: CmpLeaf, segment: ImmutableSegment):
    """EQ/IN on a raw column with a bloom filter: definitely-absent values fold
    the leaf to constant false (reference: BloomFilterSegmentPruner runs this
    server-side per segment, not just at routing)."""
    if leaf.op not in ("eq", "in") or not leaf.operands:
        return None
    bloom = segment.column(leaf.expr.name).bloom_filter
    if bloom is None:
        return None
    if all(not bloom.might_contain(v) for v in leaf.operands):
        return False
    return None


def _metadata_answerable(agg: AggFunc, segment: ImmutableSegment) -> bool:
    if agg.name == "count" and (agg.arg is None or
                                (isinstance(agg.arg, Identifier) and agg.arg.name == "*")):
        return True
    if agg.name in ("min", "max", "minmaxrange") and isinstance(agg.arg, Identifier):
        reader = segment.column(agg.arg.name)
        return (reader.data_type.is_numeric and reader.min_value is not None
                and not getattr(reader, "is_multi_value", False))
    return False


def _device_feasible(plan: SegmentPlan, segment: ImmutableSegment) -> str:
    """Empty string if the fused device kernel can run this plan; else the reason."""
    # group-by columns must be plain dict-encoded columns with a bounded key space
    cards: List[int] = []
    cols: List[str] = []
    for e in plan.group_exprs:
        if not isinstance(e, Identifier):
            return f"group-by expression {e!r} (host transform)"
        reader = segment.column(e.name)
        if not reader.has_dictionary:
            return f"group-by on raw column {e.name}"
        if getattr(reader, "is_multi_value", False):
            # MV group-by explodes one row into one group per value — dense-key
            # matmul can't express that; host path explodes via mv offsets
            return f"group-by on multi-value column {e.name}"
        cols.append(e.name)
        cards.append(reader.cardinality)
    num_keys = 1
    for c in cards:
        num_keys *= max(c, 1)
    if num_keys > MAX_DEVICE_GROUP_KEYS:
        return f"group key space {num_keys} exceeds device cap"
    plan.group_cols = tuple(cols)
    plan.card_hint = num_keys if cols else 0  # clamped by scan docs in plan_segment

    group_by = bool(cols)
    for agg in plan.aggs:
        arg = agg.arg
        arg_is_dict = isinstance(arg, Identifier) and arg.name != "*" and \
            segment.column(arg.name).has_dictionary and \
            not getattr(segment.column(arg.name), "is_multi_value", False)
        arg_numeric = arg is None or not isinstance(arg, Identifier) or arg.name == "*" or \
            (segment.column(arg.name).data_type.is_numeric
             and not getattr(segment.column(arg.name), "is_multi_value", False))
        if not agg.device_ok(AggContext(group_by, arg_is_dict, arg_numeric)):
            return f"aggregation {agg.name} not device-supported here"
        err = _power_sum_f32_safe(agg, segment)
        if err:
            return err
        if arg_is_dict and "distinct" in agg.device_outputs:
            if group_by:
                # grouped distinct materializes a [keys, ids] presence matrix
                # on device; bound its memory (padded keys <= 2x real product)
                from ..engine.datablock import lut_size
                cells = 2 * num_keys * lut_size(
                    segment.column(arg.name).cardinality)
                if cells > MAX_GROUPED_DISTINCT_CELLS:
                    return (f"grouped {agg.name} presence matrix "
                            f"({cells} cells) exceeds device cap")
            continue  # distinct-family over a dict column works on ids; dtype irrelevant
        if arg is not None and not (isinstance(arg, Identifier) and arg.name == "*"):
            err = _expr_device_ok(arg, segment)
            if err:
                return err

    if plan.filter_prog:
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, CmpLeaf):
                err = _expr_device_ok(leaf.expr, segment)
                if err:
                    return err
    return ""


# Device power sums accumulate in f32 (~7 significant digits). Allow the device
# path only when max|x|^p stays within the f32 integer-exact-ish range, so the
# centered-moment subtraction at finalize is not pure cancellation noise; large
# columns (epoch timestamps, ids) take the f64 host path instead.
POWER_SUM_F32_LIMIT = float(1 << 20)


def _power_sum_f32_safe(agg, segment: ImmutableSegment) -> str:
    powers = [p for o, p in (("sum2", 2), ("sum3", 3), ("sum4", 4))
              if o in agg.device_outputs]
    if not powers:
        return ""
    if not isinstance(agg.arg, Identifier):
        return f"{agg.name} over an expression: unknown bounds for f32 power sums"
    reader = segment.column(agg.arg.name)
    mn, mx = reader.min_value, reader.max_value
    if mn is None or mx is None:
        return f"{agg.name}: no column bounds to prove f32 power sums safe"
    max_abs = max(abs(float(mn)), abs(float(mx)))
    if max_abs ** max(powers) > POWER_SUM_F32_LIMIT:
        return (f"{agg.name}: |{agg.arg.name}|^{max(powers)} exceeds f32 "
                f"precision budget (host f64 path)")
    return ""


def _expr_device_ok(e: Expr, segment: ImmutableSegment) -> str:
    """Device-evaluable: numeric identifiers representable in 32 bits, known functions."""
    for node_name in identifiers_in(e):
        reader = segment.column(node_name)
        if getattr(reader, "is_multi_value", False):
            return f"multi-value column {node_name} in expression (host path)"
        if not reader.data_type.is_numeric:
            return f"non-numeric column {node_name} in expression"
        mn, mx = reader.min_value, reader.max_value
        if (mn is not None and mx is not None and isinstance(mn, (int, np.integer))
                and (mn < -(2 ** 31) or mx >= 2 ** 31)):
            return f"column {node_name} exceeds int32 range (device is 32-bit)"
        if (mn is None or mx is None) and reader.data_type.numpy_dtype.itemsize > 4 \
                and np.dtype(reader.data_type.numpy_dtype).kind in "iu":
            # unknown bounds on a 64-bit integer column: cannot prove int32-safe
            return f"column {node_name} is 64-bit with unknown bounds"
    def check(node):
        if isinstance(node, Function):
            if node.name not in _DEVICE_FUNCS:
                return f"function {node.name} not device-supported"
            for a in node.args:
                err = check(a)
                if err:
                    return err
        return ""
    return check(e)


def build_device_geometry(plan: SegmentPlan) -> None:
    """Fill dense-key geometry: strides over real cardinalities, padded key count.

    Padding quantizes the kernel-cache key (tables with nearby cardinalities
    share a compiled program): pow2 up to 4096, then MULTIPLES of 4096 — the
    chunked group-by kernel's work is linear in padded keys with 4096-key
    chunk granularity, so pow2 past 4096 would waste up to 2x device work
    (e.g. 20k real keys -> 32768 pow2 = 9 chunks vs 24576 = 6)."""
    cards = [plan.segment.column(c).cardinality for c in plan.group_cols]
    strides = []
    s = 1
    for c in cards:
        strides.append(s)
        s *= max(c, 1)
    plan.cards = tuple(cards)
    plan.strides = tuple(strides)
    plan.num_keys_real = s
    if s <= 4096:
        plan.num_keys_pad = 1 << max(0, (s - 1)).bit_length()
    else:
        plan.num_keys_pad = -(-s // 4096) * 4096
