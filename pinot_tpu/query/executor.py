"""Per-server query executor: plan + run each segment, combine, reduce.

Analog of `ServerQueryExecutorV1Impl.processQuery`
(`pinot-core/.../query/executor/ServerQueryExecutorV1Impl.java:130`): acquire segments,
plan per segment (`planner.py`), execute (device kernel / host fallback / selection),
combine partials (`reduce.merge_segment_results`) and — when used standalone, as in the
single-process tests — run the broker reduce too (`reduce.reduce_to_result`).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..segment.reader import ImmutableSegment
from ..sql.ast import Expr, Function, Identifier, identifiers_in
from . import stats as qstats
from .aggregates import AggFunc, make_agg
from .context import QueryContext, compile_query
from .planner import SegmentPlan, build_device_geometry, plan_segment
from .predicate import CmpLeaf, DocSetLeaf, LutLeaf, NullLeaf
from .reduce import DensePartial, SegmentResult, merge_segment_results, reduce_to_result
from .result import ResultTable

#: per-segment plan kind -> the explain-plan label family it annotates in
#: EXPLAIN ANALYZE (prefix-matched against plan-node labels)
_PLAN_OP_LABELS = {"empty": "PRUNED", "metadata": "METADATA_ONLY_AGGREGATE",
                   "selection": "SELECT", "device": "DEVICE_FUSED",
                   "host": "HOST"}

#: below this dense-key-space size the classic dict partial is cheap enough
#: that the array form only adds wire weight (it ships full dictionaries)
DENSE_PARTIAL_MIN_GROUPS = 4096

#: functions allowed to materialize doc ids on the host (np.nonzero /
#: postings loops): declared fallbacks and decode paths. Everything else in
#: this module must stay in the vectorized/device regime — enforced by the
#: `filter-path-host-materialization` graftcheck rule
__graft_slow_paths__ = ("_decode_group_partials", "_decode_scalar_partials",
                        "_host_aggregate", "_selection", "host_filter_mask")


class ServerQueryExecutor:
    """Executes a QueryContext over a set of local segments."""

    def __init__(self, use_device: bool = True, bitmap_enabled: bool = True,
                 fused_enabled: Optional[bool] = None):
        self.use_device = use_device
        # packed-word bitmap filter indexes (clusterConfig/
        # server.index.bitmap.enabled): off -> every dict filter leaf keeps
        # the interval-compare / LUT path regardless of selectivity
        self.bitmap_enabled = bitmap_enabled
        # fused single-launch execution over compressed resident forms
        # (clusterConfig/server.fused.enabled): None defers to the calibrated
        # KernelCaps.fused_enabled regime; False forces the staged
        # two-launch ladder everywhere (decoded HBM columns, mask launch +
        # aggregate launch)
        self.fused_enabled = fused_enabled

    # -- public API --------------------------------------------------------
    def execute(self, segments: Sequence[ImmutableSegment],
                query: Union[str, QueryContext], schema=None) -> ResultTable:
        import time as _t
        t0 = _t.perf_counter()
        ctx = compile_query(query, schema or (segments[0].schema if segments else None)) \
            if isinstance(query, str) else query
        if ctx.analyze:
            return self._execute_analyze(segments, ctx)
        if ctx.explain:
            from .explain import explain_result
            return explain_result(ctx, segments)
        if qstats.current_stats() is None:
            # single-process entry (no server wrapper installed a record):
            # collect here so the engine API surfaces the same stats block
            # as the broker path does
            with qstats.collect_stats():
                return self.execute(segments, ctx)
        aggs = [make_agg(f) for f in ctx.aggregations]
        group_exprs = ([e for e, _ in ctx.select_items] if ctx.distinct
                       else list(ctx.group_by))
        t_compile = _t.perf_counter()
        results = [self.execute_segment(ctx, seg) for seg in segments]
        t_scan = _t.perf_counter()
        merged = merge_segment_results(results, aggs)
        if not results:
            merged.kind = ("groups" if (group_exprs or ctx.distinct) else
                           "scalar" if aggs else "selection")
        result = reduce_to_result(ctx, merged, aggs, group_exprs)
        # per-phase wall times (reference: ServerQueryPhase SCHEDULER_WAIT /
        # QUERY_PLANNING / QUERY_PROCESSING), surfaced in the response stats
        result.stats["phaseTimesMs"] = {
            "compile": round((t_compile - t0) * 1000, 3),
            "scan": round((t_scan - t_compile) * 1000, 3),
            "reduce": round((_t.perf_counter() - t_scan) * 1000, 3),
        }
        # per-operator rollups for EXPLAIN ANALYZE (no-op without a record)
        qstats.record_operator("COMBINE", rows=merged.num_docs_scanned,
                               ms=(t_scan - t_compile) * 1000)
        qstats.record_operator("BROKER_REDUCE", rows=len(result.rows),
                               ms=(_t.perf_counter() - t_scan) * 1000)
        st = qstats.current_stats()
        if st is not None:
            result.stats.update(st.to_public_dict())
        return result

    def _execute_analyze(self, segments: Sequence[ImmutableSegment],
                         ctx: QueryContext) -> ResultTable:
        """EXPLAIN ANALYZE (single-process path): run the real query with a
        fresh stats record, then render the plan tree annotated with each
        node's rows/ms (reference: postgres-style EXPLAIN ANALYZE; the
        reference engine has no direct analog)."""
        from .explain import analyze_result
        run_ctx = dataclasses.replace(ctx, explain=False, analyze=False)
        t0 = time.perf_counter()
        with qstats.collect_stats() as st:
            inner = self.execute(segments, run_ctx)
        total_ms = (time.perf_counter() - t0) * 1000
        return analyze_result(ctx, segments, st, inner, total_ms)

    # -- per-segment execution --------------------------------------------
    def execute_segment(self, ctx: QueryContext, segment: ImmutableSegment,
                        valid_docs: Optional[np.ndarray] = None) -> SegmentResult:
        # star-tree rewrite (not under an upsert valid-doc mask: pre-aggregated
        # records cannot honor per-doc visibility, same restriction as the reference)
        if valid_docs is None and not getattr(segment, "is_mutable", False):
            from .startree_exec import reassemble, try_star_tree
            stp = try_star_tree(ctx, segment)
            if stp is not None:
                sub = self.execute_segment(stp.ctx2, stp.tree.view,
                                           valid_docs=stp.record_mask)
                reassemble(stp, sub)
                return sub
        from ..utils.trace import span
        with span("plan"):
            plan = plan_segment(ctx, segment, valid_docs)
        if not self.use_device and plan.kind == "device":
            plan.kind = "host"
            plan.fallback_reason = "device disabled"
        t0 = time.perf_counter()
        with span(f"exec:{plan.kind}"):
            if plan.kind == "empty":
                r = self._empty_result(plan)
            elif plan.kind == "metadata":
                r = self._metadata_result(plan)
            elif plan.kind == "selection":
                r = self._selection(plan)
            elif plan.kind == "device":
                r = self._device_aggregate(plan)
            else:
                r = self._host_aggregate(plan)
        st = qstats.current_stats()
        if st is not None:
            ms = (time.perf_counter() - t0) * 1000
            if plan.kind == "empty":
                st.add(qstats.NUM_SEGMENTS_PRUNED)
                st.add(qstats.SCAN_ROWS_AVOIDED, segment.num_docs)
            else:
                st.add(qstats.NUM_SEGMENTS_QUERIED)
                if (r.num_docs_scanned > 0 or r.groups or r.rows
                        or r.dense is not None or plan.kind == "metadata"):
                    st.add(qstats.NUM_SEGMENTS_MATCHED)
            st.add_operator("SEGMENT_PLAN", rows=r.num_docs_scanned, ms=ms)
            label = _PLAN_OP_LABELS[plan.kind]
            if plan.kind == "device" and \
                    getattr(plan, "exec_mode", "fused") == "staged":
                label = "DEVICE_STAGED"  # two-launch fallback rung
            st.add_operator(label, rows=r.num_docs_scanned, ms=ms)
        return r

    # ------------------------------------------------------------------
    def _result_kind(self, plan: SegmentPlan) -> str:
        return "groups" if plan.group_exprs else "scalar"

    def _empty_result(self, plan: SegmentPlan) -> SegmentResult:
        if plan.group_exprs:
            return SegmentResult("groups")
        if not plan.ctx.is_aggregation_query and not plan.ctx.distinct:
            # a pruned SELECTION segment contributes zero rows — NOT a scalar
            # block, which would route the broker reduce down the aggregation
            # path and crash resolving bare columns
            return SegmentResult("selection")
        empty = np.empty(0, dtype=np.float64)
        return SegmentResult("scalar",
                             scalar=[a.host_state(empty) for a in plan.aggs] or None)

    def _metadata_result(self, plan: SegmentPlan) -> SegmentResult:
        """Answer from metadata without scanning (NonScanBasedAggregationOperator)."""
        seg = plan.segment
        states: List[Any] = []
        for agg in plan.aggs:
            if agg.name == "count":
                states.append(seg.num_docs)
            else:
                reader = seg.column(agg.arg.name)
                mn, mx = float(reader.min_value), float(reader.max_value)
                if agg.name == "min":
                    states.append(mn)
                elif agg.name == "max":
                    states.append(mx)
                else:  # minmaxrange
                    states.append((mn, mx))
        return SegmentResult("scalar", scalar=states, num_docs_scanned=0)

    # -- device aggregation path ----------------------------------------
    def _device_aggregate(self, plan: SegmentPlan) -> SegmentResult:
        from ..engine import kernels
        from ..engine.datablock import block_for, lut_size

        seg = plan.segment
        build_device_geometry(plan)
        agg_specs: List[Tuple[AggFunc, Tuple[str, ...]]] = []
        distinct_lut_sizes: Dict[int, int] = {}
        for i, agg in enumerate(plan.aggs):
            agg_specs.append((agg, agg.device_outputs))
            if "distinct" in agg.device_outputs:
                distinct_lut_sizes[i] = lut_size(seg.column(agg.arg.name).cardinality)

        block = block_for(seg)
        plan.bitmap_leaves = self._bitmap_leaves(plan, seg)
        fused_cols = self._fused_cols(plan, seg, block)
        plan.exec_mode = "staged" if fused_cols is None else "fused"
        spec = kernels.KernelSpec(plan.filter_prog, plan.group_cols, plan.num_keys_pad,
                                  tuple(agg_specs), distinct_lut_sizes, block.padded,
                                  mv_cols=_mv_lut_cols(plan, seg),
                                  bitmap_leaves=plan.bitmap_leaves,
                                  fused_cols=fused_cols or ())
        inputs = self._kernel_inputs(plan, spec, block)
        if fused_cols is None:
            outs = kernels.run_kernel_staged(spec, inputs)
        else:
            outs = kernels.run_kernel(spec, inputs)

        if plan.group_cols:
            return self._decode_group_partials(plan, outs)
        return self._decode_scalar_partials(plan, outs)

    def _fused_cols(self, plan: SegmentPlan, seg,
                    block) -> Optional[Tuple[Tuple[str, str], ...]]:
        """(col, form) routing for a fused single-launch plan, or None when
        the regime ladder sends this shape down the staged two-launch rung.

        Fused iff every value column (filter compare expressions + aggregate
        arguments) stays in a compressed resident form the kernel can decode
        in-register: a single-value dict column whose padded decode table
        fits `KernelCaps.fused_lut_cap` routes as ("dict"), a raw int column
        with a profitable frame-of-reference form as ("for"), and plain raw
        columns pass through unrouted (their resident form IS the value
        form). A multi-value or over-cap dict value column means the decoded
        HBM cache would be built anyway — the plan stages instead."""
        from ..engine.calibrate import get_caps
        from ..engine.datablock import lut_size
        caps = get_caps()
        enabled = (caps.fused_enabled if self.fused_enabled is None
                   else self.fused_enabled)
        if not enabled or getattr(seg, "is_mutable", False):
            return None
        fused: List[Tuple[str, str]] = []
        for c in sorted(_plan_vals_cols(plan)):
            reader = seg.column(c)
            if getattr(reader, "is_multi_value", False):
                return None
            if reader.has_dictionary:
                if lut_size(reader.cardinality) > caps.fused_lut_cap:
                    return None
                fused.append((c, "dict"))
            elif block.for_form(c) is not None:
                fused.append((c, "for"))
        return tuple(fused)

    def _bitmap_leaves(self, plan: SegmentPlan, seg) -> Tuple[int, ...]:
        if not self.bitmap_enabled:
            return ()
        from .planner import select_bitmap_leaves
        return select_bitmap_leaves(plan, seg)

    def _kernel_inputs(self, plan: SegmentPlan, spec, block):
        import jax.numpy as jnp
        from ..engine.kernels import KernelInputs

        ids_cols = set(plan.group_cols)
        vals_cols = set()
        nulls_cols = set()
        luts = []
        iscal: List[int] = []
        fscal: List[float] = []
        docsets = []
        bitmaps = []
        for li, leaf in enumerate(plan.filter_prog.leaves):
            if isinstance(leaf, LutLeaf):
                if li in spec.bitmap_index:
                    # packed-word path: gather only the LUT-selected dict-id
                    # rows from the HBM word matrix, padded to pow2 by
                    # repeating a selected row (OR-idempotent, bounds
                    # retraces); this leaf never reads the forward id column
                    # and its word traffic scales with selectivity, not card
                    words = block.bitmap_words(leaf.col)
                    assert words is not None, (
                        f"leaf {li} ({leaf.col}) marked bitmap but the block "
                        "declined to build words — planner/block gating drifted")
                    luts.append(jnp.asarray(leaf.lut))
                    sel = np.asarray(leaf.lut)[:words.shape[0]].astype(bool)
                    rows = np.where(sel)[0]
                    if rows.size == 0:
                        bitmaps.append(jnp.zeros((1, words.shape[1]),
                                                 dtype=jnp.uint32))
                    else:
                        k = 1 << int(rows.size - 1).bit_length()
                        idx = np.concatenate(
                            [rows, np.full(k - rows.size, rows[0])])
                        bitmaps.append(jnp.take(
                            words, jnp.asarray(idx.astype(np.int32)), axis=0))
                    continue
                ids_cols.add(leaf.col)
                if leaf.intervals is not None:
                    # interval bounds ride the int scalar stream, in leaf order —
                    # must mirror KernelSpec.__post_init__ routing exactly
                    for lo, hi in leaf.intervals:
                        iscal.extend((lo, hi))
                else:
                    luts.append(jnp.asarray(leaf.lut))
            elif isinstance(leaf, CmpLeaf):
                vals_cols.update(identifiers_in(leaf.expr))
                (iscal if leaf.is_int else fscal).extend(leaf.operands)
            elif isinstance(leaf, NullLeaf):
                nulls_cols.add(leaf.col)
            elif isinstance(leaf, DocSetLeaf):
                padded = np.zeros(block.padded, dtype=bool)
                padded[:len(leaf.mask)] = leaf.mask
                docsets.append(jnp.asarray(padded))
        agg_luts: Dict[str, "jnp.ndarray"] = {}
        for i, agg in enumerate(plan.aggs):
            if "distinct" in agg.device_outputs:
                ids_cols.add(agg.arg.name)
            elif agg.arg is not None and not (isinstance(agg.arg, Identifier)
                                              and agg.arg.name == "*"):
                vals_cols.update(identifiers_in(agg.arg))

        valid = block.valid
        valid_words = block.valid_words
        if plan.valid_docs is not None:
            padded = np.zeros(block.padded, dtype=bool)
            padded[:len(plan.valid_docs)] = plan.valid_docs
            valid = valid & jnp.asarray(padded)  # upsert valid-doc intersection
            valid_words = None                   # packed form is now stale

        # fused plans keep value columns in compressed resident form: a
        # "dict" column ships its padded decode table via vals plus the id
        # column via ids (gathered in-register by _fused_env), a "for"
        # column ships narrow deltas via vals with its base appended to
        # iscal AFTER every filter scalar, in fused_cols order — must
        # mirror KernelSpec.__post_init__'s for_offset routing exactly
        fused = dict(spec.fused_cols)
        vals = {}
        for c in vals_cols:
            form = fused.get(c)
            if form == "dict":
                ids_cols.add(c)
                vals[c] = block.dict_values(c)
            elif form == "for":
                vals[c] = block.for_form(c)[1]
            else:
                vals[c] = block.values(c)
        for c, form in spec.fused_cols:
            if form == "for":
                iscal.append(block.for_form(c)[0])

        return KernelInputs(
            ids={c: block.ids(c) for c in ids_cols},
            vals=vals,
            luts=tuple(luts),
            iscal=jnp.asarray(np.asarray(iscal, dtype=np.int32)),
            fscal=jnp.asarray(np.asarray(fscal, dtype=np.float32)),
            nulls={c: block.null_mask(c) for c in nulls_cols},
            valid=valid,
            strides=jnp.asarray(np.asarray(plan.strides, dtype=np.int32)),
            agg_luts=agg_luts,
            docsets=tuple(docsets),
            bitmaps=tuple(bitmaps),
            valid_words=valid_words,
        )

    def _decode_group_partials(self, plan: SegmentPlan, outs,
                               trim_global: bool = False) -> SegmentResult:
        seg = plan.segment
        counts = outs["count"][:plan.num_keys_real]
        occupied = np.nonzero(counts > 0)[0]
        if trim_global:
            # outs are GLOBAL (post-collective) partials, so an order-by trim here is
            # exact — the TableResizer analog, but vectorized over the dense key space
            # instead of a heap, bounding the decode loop to k groups
            occupied = _trim_occupied(plan, outs, occupied)
        # decode dense keys -> per-column dict ids -> values (vectorized per column)
        value_cols = []
        for j, col in enumerate(plan.group_cols):
            ids_j = (occupied // plan.strides[j]) % max(plan.cards[j], 1)
            value_cols.append(seg.column(col).dictionary.take(ids_j.astype(np.int64)))
        keys = list(zip(*[c.tolist() for c in value_cols])) if len(occupied) else []

        result = SegmentResult("groups")
        result.num_docs_scanned = int(counts.sum())
        # per-agg distinct decode inputs (grouped presence matrices)
        distinct_readers = {
            i: seg.column(agg.arg.name)
            for i, agg in enumerate(plan.aggs)
            if "distinct" in agg.device_outputs}
        for row, k in enumerate(occupied):
            states = []
            for i, agg in enumerate(plan.aggs):
                if i in distinct_readers:
                    reader = distinct_readers[i]
                    presence = outs[f"{i}.distinct"][k][:reader.cardinality]
                    if getattr(agg, "wants_id_counts", False):
                        states.append(agg.state_from_id_counts(
                            reader.dictionary, np.asarray(presence)))
                    else:
                        states.append(agg.state_from_present_ids(
                            reader.dictionary, np.nonzero(presence > 0)[0]))
                    continue
                o = {"count": int(counts[k])}
                for out_name in agg.device_outputs:
                    if out_name != "count":
                        o[out_name] = outs[f"{i}.{out_name}"][k]
                states.append(agg.state_from_device(o))
            result.groups[tuple(keys[row])] = states
        return result

    def _decode_dense_partial(self, plan: SegmentPlan, outs) -> Optional[SegmentResult]:
        """Array-form partial decode (see `reduce.DensePartial`): skip the
        per-group Python state loop entirely at high cardinality. Returns None
        when the plan can't prove cross-server key alignment (missing dict
        hashes) or the dense form wouldn't pay for itself."""
        from .dense_reduce import _dense_capable
        if plan.num_keys_real < DENSE_PARTIAL_MIN_GROUPS:
            return None
        if not all(_dense_capable(a) for a in plan.aggs):
            return None
        if any("distinct" in a.device_outputs for a in plan.aggs):
            return None
        seg = plan.segment
        dict_hashes = []
        for col in plan.group_cols:
            h = seg.column(col).meta.get("dictHash")
            if h is None:
                return None  # can't prove dictionaries align across servers
            dict_hashes.append(h)
        counts = np.asarray(outs["count"][:plan.num_keys_real]).astype(np.int64)
        dp_outs = {}
        for i, agg in enumerate(plan.aggs):
            for out_name in agg.device_outputs:
                if out_name != "count":
                    dp_outs[f"{i}.{out_name}"] = np.asarray(
                        outs[f"{i}.{out_name}"][:plan.num_keys_real])
        group_values = [
            seg.column(col).dictionary.take(
                np.arange(plan.cards[j], dtype=np.int64))
            for j, col in enumerate(plan.group_cols)]
        token = (tuple(plan.group_cols), tuple(plan.cards),
                 tuple(dict_hashes), plan.num_keys_real)
        dp = DensePartial(token, tuple(plan.cards), tuple(plan.strides),
                          plan.num_keys_real, counts, dp_outs, group_values,
                          aggs=plan.aggs)
        return SegmentResult("groups", dense=dp,
                             num_docs_scanned=int(counts.sum()))

    def _decode_scalar_partials(self, plan: SegmentPlan, outs) -> SegmentResult:
        seg = plan.segment
        count = int(outs["count"])
        states: List[Any] = []
        for i, agg in enumerate(plan.aggs):
            if "distinct" in agg.device_outputs:
                presence = outs[f"{i}.distinct"]
                reader = seg.column(agg.arg.name)
                if getattr(agg, "wants_id_counts", False):
                    states.append(agg.state_from_id_counts(
                        reader.dictionary,
                        np.asarray(presence[:reader.cardinality])))
                    continue
                present_ids = np.nonzero(presence[:reader.cardinality] > 0)[0]
                states.append(agg.state_from_present_ids(reader.dictionary,
                                                         present_ids))
                continue
            o = {"count": count}
            for out_name in agg.device_outputs:
                if out_name != "count":
                    o[out_name] = outs[f"{i}.{out_name}"]
            states.append(agg.state_from_device(o))
        return SegmentResult("scalar", scalar=states, num_docs_scanned=count)

    # -- host fallback aggregation ---------------------------------------
    def _host_aggregate(self, plan: SegmentPlan) -> SegmentResult:
        seg = plan.segment
        mask = host_filter_mask(plan, seg)
        if plan.valid_docs is not None:
            mask = mask & plan.valid_docs[:len(mask)]
        idx = np.nonzero(mask)[0]
        env = _host_env(plan, seg)

        def arg_values(agg: AggFunc) -> np.ndarray:
            if agg.arg is None or (isinstance(agg.arg, Identifier) and agg.arg.name == "*"):
                return np.zeros(len(idx))
            from ..engine.expr import eval_expr
            return np.asarray(eval_expr(agg.arg, env, np))[idx]

        if not plan.group_exprs:
            states = [a.host_state(arg_values(a)) for a in plan.aggs]
            return SegmentResult("scalar", scalar=states, num_docs_scanned=len(idx))

        from ..engine.expr import eval_expr
        key_arrays = [np.asarray(eval_expr(g, env, np))[idx] for g in plan.group_exprs]
        arg_arrays = [arg_values(a) for a in plan.aggs]

        # multi-value group-by: explode each row into one group row per value
        # (reference: MV group key generators emit one key per value combination).
        # Detected on the EVALUATED key arrays so MV->MV transforms (VALUEIN)
        # explode the same way bare MV identifiers do.
        def _is_mv_keys(arr: np.ndarray) -> bool:
            return (arr.dtype == object and len(arr)
                    and isinstance(arr[0], np.ndarray))
        mv_pos = [j for j, arr in enumerate(key_arrays) if _is_mv_keys(arr)]
        if mv_pos:
            from .context import QueryValidationError
            if len(mv_pos) > 1:
                raise QueryValidationError(
                    "GROUP BY supports at most one multi-value expression")
            j = mv_pos[0]
            rows = key_arrays[j]
            counts = np.fromiter((len(r) for r in rows), dtype=np.int64,
                                 count=len(rows))
            rep = np.repeat(np.arange(len(rows)), counts)
            flat = (np.concatenate(list(rows)) if len(rows)
                    else np.empty(0, dtype=object))
            key_arrays = [flat if k == j else arr[rep]
                          for k, arr in enumerate(key_arrays)]
            arg_arrays = [a[rep] for a in arg_arrays]

        # vectorized grouping: factorize each key column, combine into one dense int
        # key, then split row indices per group — the host-side mirror of the device's
        # DictionaryBasedGroupKeyGenerator dense keys (no pandas: its arrow string
        # backend is not thread-safe for object arrays).
        value_dicts = []
        n_rows = len(key_arrays[0]) if key_arrays else len(idx)  # post-explode size
        combined = np.zeros(n_rows, dtype=np.int64)
        stride = 1
        for arr in key_arrays:
            codes, values = _factorize_keys(arr)
            combined += codes * stride
            value_dicts.append(values)
            stride *= max(len(values), 1)
        uniq_keys, inverse = np.unique(combined, return_inverse=True)
        order = np.argsort(inverse, kind="stable")
        bounds = np.zeros(len(uniq_keys) + 1, dtype=np.int64)
        np.cumsum(np.bincount(inverse, minlength=len(uniq_keys)), out=bounds[1:])

        result = SegmentResult("groups", num_docs_scanned=len(idx))
        for g, dense in enumerate(uniq_keys):
            gidx = order[bounds[g]:bounds[g + 1]]
            key = []
            rem = dense
            for j, values in enumerate(value_dicts):
                card = max(len(values), 1)
                v = values[rem % card]
                key.append(v.item() if isinstance(v, np.generic) else v)
                rem //= card
            result.groups[tuple(key)] = [a.host_state(arg_arrays[i][gidx])
                                         for i, a in enumerate(plan.aggs)]
        return result

    # -- selection --------------------------------------------------------
    MAX_DEVICE_TOPK = 65536

    def _selection(self, plan: SegmentPlan) -> SegmentResult:
        ctx, seg = plan.ctx, plan.segment
        topk = self._topk_candidates(plan)
        if topk is not None:
            idx, scanned = topk
        else:
            mask = self._selection_mask(plan)
            if plan.valid_docs is not None:
                mask = mask & plan.valid_docs[:len(mask)]
            idx = np.nonzero(mask)[0]
            if not ctx.order_by:
                idx = idx[:ctx.offset + ctx.limit]  # early terminate (SelectionOnlyOperator)
            scanned = len(idx)

        needed = set()
        for e, _ in ctx.select_items:
            needed.update(identifiers_in(e))
        for o in ctx.order_by:
            needed.update(identifiers_in(o.expr))
        env = {c: seg.column(c).values()[idx] for c in needed}

        from ..engine.expr import eval_expr
        out_cols = [np.asarray(eval_expr(e, env, np)) if not _is_const(e)
                    else np.full(len(idx), eval_expr(e, env, np), dtype=object)
                    for e, _ in ctx.select_items]

        def _cell(v):
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, np.ndarray):  # multi-value cell -> python list
                return v.tolist()
            return v
        rows = [tuple(_cell(c[i]) for c in out_cols) for i in range(len(idx))]
        sort_keys = []
        if ctx.order_by:
            sort_cols = [np.asarray(eval_expr(o.expr, env, np)) for o in ctx.order_by]
            sort_keys = [tuple(c[i].item() if isinstance(c[i], np.generic) else c[i]
                               for c in sort_cols) for i in range(len(idx))]
        return SegmentResult("selection", rows=rows, sort_keys=sort_keys,
                             num_docs_scanned=scanned)

    # slack candidates beyond k so f32 ties at the k-boundary cannot evict a true
    # top-k row (final ordering is exact: candidates re-sort on host in f64)
    TOPK_SLACK = 256

    def _topk_candidates(self, plan: SegmentPlan) -> Optional[Tuple[np.ndarray, int]]:
        """(candidate doc ids, match count) from a DEVICE order-by trim, or None.

        Eligible: single plain-column numeric ORDER BY key, bounded LIMIT, immutable
        segment. Integer keys require known bounds within 2^24 (f32-exact); float
        keys ride with TOPK_SLACK overfetch, since only the candidate set — never the
        final order — is decided in f32. Expression keys (e.g. a*b) can overflow f32
        precision without column bounds revealing it, so they stay on the host."""
        ctx, seg = plan.ctx, plan.segment
        k = ctx.offset + ctx.limit
        if (len(ctx.order_by) != 1 or not self.use_device or k <= 0
                or k > self.MAX_DEVICE_TOPK or getattr(seg, "is_mutable", False)):
            return None
        order = ctx.order_by[0]
        if not topk_order_key_device_ok(seg, order.expr):
            return None
        from .planner import _expr_device_ok
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, CmpLeaf) and _expr_device_ok(leaf.expr, seg):
                return None  # mask itself needs the host path
        from ..engine import kernels
        from ..engine.datablock import block_for
        block = block_for(seg)
        spec = kernels.KernelSpec(plan.filter_prog, (), 1, (), {}, block.padded,
                                  mv_cols=_mv_lut_cols(plan, seg))
        inputs = self._kernel_inputs(plan, spec, block)
        for c in identifiers_in(order.expr):
            if c not in inputs.vals:
                inputs.vals[c] = block.values(c)
        idx, count, ok = kernels.compute_topk(spec, inputs, order.expr, order.desc,
                                              k + self.TOPK_SLACK)
        keep = min(k + self.TOPK_SLACK, count)
        idx, ok = idx[:keep], ok[:keep]
        idx = idx[ok & (idx < seg.num_docs)]
        if len(idx) < min(k, count):
            return None  # -inf/NaN keys displaced matches; exact host path decides
        return idx, count

    def _selection_mask(self, plan: SegmentPlan) -> np.ndarray:
        seg = plan.segment
        if plan.filter_prog.is_match_all:
            return np.ones(seg.num_docs, dtype=bool)
        use_device = self.use_device and not getattr(seg, "is_mutable", False)
        if use_device:
            from .planner import _expr_device_ok
            for leaf in plan.filter_prog.leaves:
                if isinstance(leaf, CmpLeaf) and _expr_device_ok(leaf.expr, seg):
                    use_device = False
                    break
        if use_device:
            from ..engine import kernels
            from ..engine.datablock import block_for
            block = block_for(seg)
            plan.bitmap_leaves = self._bitmap_leaves(plan, seg)
            spec = kernels.KernelSpec(plan.filter_prog, (), 1, (), {}, block.padded,
                                      mv_cols=_mv_lut_cols(plan, seg),
                                      bitmap_leaves=plan.bitmap_leaves)
            inputs = self._kernel_inputs(plan, spec, block)
            return kernels.compute_mask(spec, inputs)[:seg.num_docs]
        return host_filter_mask(plan, seg)


def _plan_vals_cols(plan: SegmentPlan) -> set:
    """Columns the kernel reads as *values* (not dict ids): filter compare
    expressions plus non-distinct aggregate arguments. Mirrors the
    vals_cols set `_kernel_inputs` builds — fused eligibility is decided
    over exactly these columns."""
    cols = set()
    for leaf in plan.filter_prog.leaves:
        if isinstance(leaf, CmpLeaf):
            cols.update(identifiers_in(leaf.expr))
    for agg in plan.aggs:
        if "distinct" in agg.device_outputs:
            continue
        if agg.arg is not None and not (isinstance(agg.arg, Identifier)
                                        and agg.arg.name == "*"):
            cols.update(identifiers_in(agg.arg))
    return cols


def _mv_lut_cols(plan: SegmentPlan, seg: ImmutableSegment) -> Tuple[str, ...]:
    """LUT-leaf columns that are multi-value in this segment (KernelSpec.mv_cols)."""
    cols = set()
    for leaf in plan.filter_prog.leaves:
        if isinstance(leaf, LutLeaf) and \
                getattr(seg.column(leaf.col), "is_multi_value", False):
            cols.add(leaf.col)
    return tuple(sorted(cols))


def host_filter_mask(plan: SegmentPlan, seg: ImmutableSegment) -> np.ndarray:
    """Evaluate the compiled filter program with numpy on the host — same LUT semantics as
    the device path, so host and device paths agree by construction."""
    from ..engine.expr import eval_expr

    prog = plan.filter_prog
    n = seg.num_docs
    if prog is None or prog.is_match_all:
        return np.ones(n, dtype=bool)
    env = _host_env(plan, seg)

    def leaf_mask(i: int) -> np.ndarray:
        leaf = prog.leaves[i]
        if isinstance(leaf, LutLeaf):
            reader = seg.column(leaf.col)
            # Mutable (consuming) readers: take ONE dict_snapshot and bind the
            # LUT, the inverted-index view, AND the forward ids to it. Dict
            # ids REMAP as the sorted dictionary grows, so the compile-time
            # LUT paired with a fresh index/fwd read (or vice versa) evaluates
            # the predicate in two different id spaces — the same
            # mixed-growth hazard the immutable reader never has. The LUT is
            # rebuilt from the leaf's source predicate against the snapshot
            # dictionary (LutLeaf.rebuild_lut).
            snap_fn = getattr(reader, "dict_snapshot", None)
            snap = snap_fn() if snap_fn is not None else None
            if snap is not None and snap[1] is None:  # no-dict reader sentinel
                snap = None
            lut = leaf.lut
            if snap is not None and snap[1] is not None and leaf.op is not None:
                lut = leaf.rebuild_lut(snap[1], len(snap[1]))
            if snap is not None:
                iv = getattr(reader, "inverted_view", None)
                inv = iv(snap) if iv is not None else None
            else:
                inv = getattr(reader, "inverted_index", None)
            if inv is not None:
                # index-aware path (reference: BitmapBasedFilterOperator;
                # realtime segments serve it from the incrementally-maintained
                # RealtimeInvertedIndex view): selective predicates
                # materialize the doc set from postings — O(matches) instead
                # of the O(docs) forward gather; dense predicates keep the
                # gather, which is cheaper than concatenating huge postings
                card = min(inv.cardinality, len(lut))
                match_ids = np.nonzero(lut[:card])[0]
                if inv.match_count_for_ids(match_ids) * 8 <= n:
                    mask = np.zeros(n, dtype=bool)
                    docs = inv.doc_ids_for_ids(match_ids)
                    mask[docs[docs < n]] = True
                    return mask
            if getattr(reader, "is_multi_value", False):
                # ANY-value-matches per row (MVScanDocIdIterator semantics); every
                # row has >= 1 value (writer stores [null] for empty), so reduceat
                # over the CSR offsets is well-defined
                if snap is not None:
                    _, _, flat, off = snap
                else:
                    flat = np.asarray(reader.fwd).astype(np.int64)
                    off = np.asarray(reader.mv_offsets)
                if not len(flat):
                    return np.zeros(n, dtype=bool)
                hits = lut[np.asarray(flat).astype(np.int64)].astype(np.int32)
                m = np.add.reduceat(hits, np.asarray(off)[:-1]) > 0
                if len(m) < n:  # snapshot older than the captured row count
                    m = np.pad(m, (0, n - len(m)), constant_values=False)
                return m[:n]
            if snap is not None:
                ids = np.asarray(snap[2]).astype(np.int64)
                m = lut[ids]
                if len(m) < n:
                    m = np.pad(m, (0, n - len(m)), constant_values=False)
                return m[:n]
            ids = np.asarray(reader.fwd).astype(np.int64)
            return lut[ids]
        if isinstance(leaf, NullLeaf):
            nb = seg.column(leaf.col).null_bitmap
            m = nb if nb is not None else np.zeros(n, dtype=bool)
            return ~m if leaf.negated else m
        if isinstance(leaf, DocSetLeaf):
            return leaf.mask[:n]
        assert isinstance(leaf, CmpLeaf)
        v = np.asarray(eval_expr(leaf.expr, env, np))
        ops = leaf.operands
        if leaf.op == "eq":
            return v == ops[0]
        if leaf.op == "gte":
            return v >= ops[0]
        if leaf.op == "lte":
            return v <= ops[0]
        if leaf.op == "gt":
            return v > ops[0]
        if leaf.op == "lt":
            return v < ops[0]
        if leaf.op == "between":
            return (v >= ops[0]) & (v <= ops[1])
        m = v == ops[0]
        for o in ops[1:]:
            m = m | (v == o)
        return m

    def walk_tree(node) -> np.ndarray:
        kind = node[0]
        if kind == "const":
            return np.full(n, node[1], dtype=bool)
        if kind == "leaf":
            return leaf_mask(node[1])
        if kind == "not":
            return ~walk_tree(node[1])
        masks = [walk_tree(c) for c in node[1]]
        out = masks[0]
        for m in masks[1:]:
            out = (out & m) if kind == "and" else (out | m)
        return out

    return walk_tree(prog.tree)


def _host_env(plan: SegmentPlan, seg: ImmutableSegment) -> Dict[str, np.ndarray]:
    """Decoded column environment for host-side expression evaluation."""
    needed = set()
    for g in plan.group_exprs:
        needed.update(identifiers_in(g))
    for a in plan.aggs:
        if a.arg is not None:
            needed.update(identifiers_in(a.arg))
    if plan.filter_prog:
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, CmpLeaf):
                needed.update(identifiers_in(leaf.expr))
    return {c: seg.column(c).values() for c in needed}


def topk_order_key_device_ok(seg, order_expr) -> bool:
    """True when `order_expr` is a device-sortable ORDER BY key on `seg`.

    Requires a plain single-value column (expression keys like a*b can
    overflow f32 precision without column bounds revealing it) that the
    device can evaluate; integer keys additionally need known min/max within
    2^24 so the f32 candidate pass cannot misorder them. Shared by the
    per-segment `_topk_candidates` and the served mesh top-k
    (`parallel.combine._prepare_topk`), so serving and library paths agree
    on eligibility."""
    if not isinstance(order_expr, Identifier):
        return False
    from .planner import _expr_device_ok
    if _expr_device_ok(order_expr, seg):
        return False
    reader = seg.column(order_expr.name)
    if getattr(reader, "is_multi_value", False):
        return False
    if reader.data_type.numpy_dtype.kind in "iu":
        mn, mx = reader.min_value, reader.max_value
        if mn is None or mx is None or \
                max(abs(float(mn)), abs(float(mx))) >= (1 << 24):
            return False  # f32 would misorder wide integers
    return True


def group_trim_spec(ctx: QueryContext, plan: SegmentPlan):
    """(agg index or None-for-count, desc, k) when a group-by ORDER BY can be trimmed
    to its top-k groups from device outputs alone; None otherwise.

    Safe only against GLOBAL (fully combined) partials: per-segment partial sums can
    rank groups differently than their cross-segment totals. Requires: single ORDER BY
    key that IS one of the query's aggregations, no HAVING (it could resurrect
    trimmed groups), no DISTINCT rewrite."""
    if (ctx.having is not None or ctx.distinct or len(ctx.order_by) != 1
            or ctx.gapfill is not None):  # gapfill fabricates rows for trimmed groups
        return None
    k = ctx.offset + ctx.limit
    if k <= 0 or k > ServerQueryExecutor.MAX_DEVICE_TOPK:
        return None
    o = ctx.order_by[0]
    for i, fn_expr in enumerate(ctx.aggregations):
        if repr(o.expr) == repr(fn_expr):
            outs = plan.aggs[i].device_outputs
            if outs in (("count",), ("sum",), ("min",), ("max",), ("sum", "count")):
                return (i, o.desc, k)
    return None


def _trim_occupied(plan: SegmentPlan, outs, occupied: np.ndarray) -> np.ndarray:
    """Exact top-k subset of occupied dense keys by the ORDER BY aggregation."""
    trim = group_trim_spec(plan.ctx, plan)
    if trim is None or len(occupied) <= trim[2]:
        return occupied
    i, desc, k = trim
    outs_names = plan.aggs[i].device_outputs
    if outs_names == ("count",):
        score = outs["count"][:plan.num_keys_real][occupied].astype(np.float64)
    elif outs_names == ("sum", "count"):  # AVG
        s = outs[f"{i}.sum"][:plan.num_keys_real][occupied].astype(np.float64)
        c = outs["count"][:plan.num_keys_real][occupied].astype(np.float64)
        score = s / np.maximum(c, 1)
    else:
        score = np.asarray(outs[f"{i}.{outs_names[0]}"][:plan.num_keys_real][occupied],
                           dtype=np.float64)
    top = np.argpartition(-score if desc else score, k - 1)[:k]
    return occupied[top]


def _factorize_keys(arr: np.ndarray):
    """Null-aware dense codes for host group-by keys.

    SQL groups all nulls (None in object arrays, NaN in float arrays — e.g. a
    LOOKUP miss, `LookupTransformFunction.java:65` semantics) into ONE group whose
    key surfaces as None; np.unique alone cannot sort None against str. Returns
    (codes, values) where nulls get the trailing code len(values)-1 -> None."""
    n = len(arr)
    if arr.dtype == object:
        isnull = np.fromiter((v is None for v in arr), dtype=bool, count=n)
        if isnull.any():
            fill = next((v for v in arr if v is not None), "")
            tmp = arr.copy()
            tmp[isnull] = fill
        else:
            tmp = arr
        uniq, inv = np.unique(tmp, return_inverse=True)
    elif arr.dtype.kind == "f":
        isnull = np.isnan(arr)
        uniq, inv = np.unique(np.where(isnull, 0.0, arr), return_inverse=True)
    else:
        isnull = np.zeros(n, dtype=bool)
        uniq, inv = np.unique(arr, return_inverse=True)
    codes = inv.astype(np.int64).reshape(n)
    values = list(uniq)
    if isnull.any():
        codes[isnull] = len(values)
        values.append(None)
    return codes, values


def _is_const(e: Expr) -> bool:
    return not identifiers_in(e)


def execute_query(segments: Sequence[ImmutableSegment], sql: str,
                  schema=None, use_device: bool = True) -> ResultTable:
    """One-call convenience: SQL over loaded segments (the BaseQueriesTest harness shape)."""
    return ServerQueryExecutor(use_device).execute(segments, sql, schema)
