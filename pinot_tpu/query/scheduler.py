"""Query scheduling and admission control.

Analog of the reference's `QueryScheduler` hierarchy
(`pinot-core/src/main/java/org/apache/pinot/core/query/scheduler/QueryScheduler.java:56`,
`FCFSQueryScheduler`, `BoundedFCFSScheduler`, `TokenPriorityScheduler` with its
`ResourceManager` semaphores) and the broker's `QueryQuotaManager` (per-table QPS
quotas). TPU framing: a server fronts ONE chip, so admission control is what keeps a
single runaway query from occupying the device while everything else queues — the
scheduler bounds concurrency (device dispatch is serialized by XLA anyway; host-side
decode/plan work does parallelize), bounds the wait queue, enforces wall-clock
timeouts, and accounts per-table usage so one table cannot starve the rest.

Dispatch order is weighted-fair across tables (start-time fair queueing on a
per-tenant virtual clock, the TokenPriorityScheduler analog): each tenant's
virtual time advances by `cost / weight` per dispatched query, and the tenant
with the smallest virtual time runs next, so a hot tenant that floods the queue
only delays itself. Admission additionally enforces a per-tenant in-flight byte
budget fed by the per-table accounting upstream (callers pass `cost_bytes`).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor, TimeoutError as FutureTimeout
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Optional


class QueryRejectedError(Exception):
    """Admission denied (queue full / quota exceeded / scheduler stopped).

    Reference: QueryScheduler returning an error DataTable with
    SERVER_SCHEDULER_DOWN/SERVER_OUT_OF_CAPACITY. Carries an optional
    `retry_after_ms` drain-rate hint that the HTTP layer surfaces on 429s."""

    def __init__(self, message: str, retry_after_ms: Optional[float] = None):
        super().__init__(message)
        self.retry_after_ms = retry_after_ms


class QueryTimeoutError(Exception):
    """Query exceeded its wall-clock budget (reference: per-query timeoutMs).

    `deadline_epoch_ms` is set when the rejection came from an absolute
    `deadlineEpochMs` so the 408 body can echo the deadline back."""

    def __init__(self, message: str, deadline_epoch_ms: Optional[float] = None):
        super().__init__(message)
        self.deadline_epoch_ms = deadline_epoch_ms


@dataclass
class SchedulerStats:
    submitted: int = 0
    completed: int = 0
    rejected: int = 0
    timed_out: int = 0
    failed: int = 0
    # live gauges
    running: int = 0
    queued: int = 0
    per_table_running: Dict[str, int] = field(default_factory=dict)
    per_table_queued: Dict[str, int] = field(default_factory=dict)
    per_table_bytes: Dict[str, float] = field(default_factory=dict)

    def snapshot(self) -> Dict[str, Any]:
        return {k: (dict(v) if isinstance(v, dict) else v)
                for k, v in self.__dict__.items()}


@dataclass
class _QueuedItem:
    table: str
    fn: Callable[[], Any]
    future: Future
    cost: float
    cost_bytes: float


# one cost unit per query plus one per MiB of predicted in-flight bytes, so a
# tenant of heavy scans burns virtual time faster than a tenant of cheap aggs
_BYTES_PER_COST = float(1 << 20)


class QueryScheduler:
    """Weighted-fair bounded scheduler with per-table accounting.

    Queries run on a fixed worker pool (`max_concurrent`); at most `max_pending`
    more may wait; beyond that, submission is rejected immediately — backpressure
    instead of unbounded queue growth, exactly the BoundedFCFS behavior. A
    `per_table_share` < 1 caps how many workers a single table may hold
    concurrently (the ResourceManager's per-query-group semaphore analog).
    Waiting queries dispatch in weighted-fair order across tables rather than
    FIFO; `tenant_weights` biases the split and `max_table_bytes` bounds one
    tenant's predicted in-flight bytes (0 disables the byte budget).
    """

    def __init__(self, max_concurrent: int = 4, max_pending: int = 32,
                 default_timeout_s: float = 60.0, per_table_share: float = 1.0,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 max_table_bytes: float = 0.0):
        self.max_concurrent = max_concurrent
        self.max_pending = max_pending
        self.default_timeout_s = default_timeout_s
        # share < 1 caps one table's in-flight (running+queued) queries; 1.0 means
        # no per-table cap — admission is then bounded by max_pending alone
        self.table_cap = (None if per_table_share >= 1.0
                          else max(1, int(max_concurrent * per_table_share)))
        self.tenant_weights = dict(tenant_weights or {})
        self.max_table_bytes = float(max_table_bytes)
        self._pool = ThreadPoolExecutor(max_workers=max_concurrent,
                                        thread_name_prefix="query-sched")
        self._lock = threading.Condition()
        self.stats = SchedulerStats()
        self._stopped = False
        # weighted-fair state: per-tenant wait queues and virtual clocks
        self._queues: Dict[str, Deque[_QueuedItem]] = {}
        self._vtimes: Dict[str, float] = {}
        self._vclock = 0.0
        # EWMA of observed service time, feeding the Retry-After drain hint
        self._service_ms_ewma = 25.0

    # -- fair-queue internals (call with self._lock held) -------------------
    def _weight(self, table: str) -> float:
        return max(0.1, float(self.tenant_weights.get(table, 1.0)))

    def _enqueue(self, item: _QueuedItem) -> None:
        q = self._queues.get(item.table)
        if q is None:
            q = self._queues[item.table] = deque()
        if not q:
            # a tenant going from idle to busy starts at the global clock so it
            # cannot bank credit while idle (start-time fair queueing)
            self._vtimes[item.table] = max(
                self._vtimes.get(item.table, 0.0), self._vclock)
        q.append(item)

    def _pop_next(self) -> Optional[_QueuedItem]:
        best: Optional[str] = None
        best_vt = 0.0
        for table, q in self._queues.items():
            if not q:
                continue
            vt = self._vtimes.get(table, 0.0)
            if best is None or vt < best_vt:
                best, best_vt = table, vt
        if best is None:
            return None
        q = self._queues[best]
        item = q.popleft()
        if not q:
            del self._queues[best]
        self._vclock = max(self._vclock, best_vt)
        self._vtimes[best] = best_vt + item.cost / self._weight(best)
        return item

    def _dec(self, counts: Dict[str, Any], table: str, n: float = 1) -> None:
        v = counts.get(table, 0) - n
        if v <= 0 or (isinstance(v, float) and v < 1e-6):
            counts.pop(table, None)
        else:
            counts[table] = v

    def _release_table(self, table: str, cost_bytes: float) -> None:
        self._dec(self.stats.per_table_running, table)
        if cost_bytes:
            self._dec(self.stats.per_table_bytes, table, cost_bytes)
        if table not in self.stats.per_table_running \
                and table not in self._queues:
            # tenant fully idle: drop its virtual clock so the map stays
            # bounded across hundreds of transient tenants
            self._vtimes.pop(table, None)

    def retry_after_ms(self) -> float:
        """Drain-rate hint for 429 Retry-After: how long until a freed slot,
        estimated from the queue depth and the observed service-time EWMA."""
        with self._lock:
            depth = self.stats.queued + self.stats.running
            return max(1.0, (depth + 1) * self._service_ms_ewma
                       / max(1, self.max_concurrent))

    # ------------------------------------------------------------------
    def submit(self, table: str, fn: Callable[[], Any],
               timeout_s: Optional[float] = None,
               cost_bytes: float = 0.0) -> Any:
        """Run fn under admission control; blocks the caller until done.

        Raises QueryRejectedError when the server is out of capacity and
        QueryTimeoutError when fn exceeds its budget (the worker is abandoned to
        finish in the background — same as the reference reaping the response
        future; the slot frees when it completes)."""
        from ..utils.metrics import get_registry
        timeout_s = timeout_s if timeout_s is not None else self.default_timeout_s
        cost_bytes = max(0.0, float(cost_bytes))
        with self._lock:
            if self._stopped:
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError("scheduler is shut down")
            if self.stats.queued >= self.max_pending:
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError(
                    f"server out of capacity: {self.stats.queued} queries pending",
                    retry_after_ms=(self.stats.queued + self.stats.running + 1)
                    * self._service_ms_ewma / max(1, self.max_concurrent))
            if self.table_cap is not None \
                    and self.stats.per_table_running.get(table, 0) >= self.table_cap:
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError(
                    f"table {table!r} is at its concurrency share ({self.table_cap})",
                    retry_after_ms=self._service_ms_ewma)
            if self.max_table_bytes > 0 and cost_bytes > 0 \
                    and self.stats.per_table_bytes.get(table, 0.0) > 0 \
                    and self.stats.per_table_bytes[table] + cost_bytes \
                    > self.max_table_bytes:
                # an idle tenant may always run one oversized query — the budget
                # bounds concurrent bytes, it must not wedge a table forever
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError(
                    f"table {table!r} exceeded its in-flight byte budget "
                    f"({int(self.max_table_bytes)}B)",
                    retry_after_ms=self._service_ms_ewma)
            self.stats.submitted += 1
            self.stats.queued += 1
            self.stats.per_table_running[table] = \
                self.stats.per_table_running.get(table, 0) + 1
            self.stats.per_table_queued[table] = \
                self.stats.per_table_queued.get(table, 0) + 1
            if cost_bytes:
                self.stats.per_table_bytes[table] = \
                    self.stats.per_table_bytes.get(table, 0.0) + cost_bytes
            fut: Future = Future()
            self._enqueue(_QueuedItem(
                table=table, fn=fn, future=fut,
                cost=1.0 + cost_bytes / _BYTES_PER_COST, cost_bytes=cost_bytes))

        try:
            # one ticket per queued item: each worker invocation dispatches
            # exactly the fair-queue head, so pool order no longer implies
            # execution order and a hot tenant cannot monopolize the pool
            self._pool.submit(self._run_ticket)
        except RuntimeError:
            with self._lock:
                self.stats.rejected += 1
                self.stats.queued -= 1
                self._dec(self.stats.per_table_queued, table)
                self._release_table(table, cost_bytes)
                fut.cancel()
            get_registry().counter("pinot_server_queries_rejected").inc()
            raise QueryRejectedError("scheduler is shut down") from None
        try:
            result = fut.result(timeout=timeout_s)
            with self._lock:
                self.stats.completed += 1
            return result
        except FutureTimeout:
            cancelled = fut.cancel()  # a still-queued query never needs to run
            get_registry().counter("pinot_server_queries_timed_out").inc()
            with self._lock:
                self.stats.timed_out += 1
                if cancelled:
                    # the ticket will skip it: undo the queue accounting here
                    self.stats.queued -= 1
                    self._dec(self.stats.per_table_queued, table)
                    self._release_table(table, cost_bytes)
            raise QueryTimeoutError(f"query exceeded {timeout_s}s") from None
        except Exception:
            with self._lock:
                self.stats.failed += 1
            raise

    def _run_ticket(self) -> None:
        while True:
            with self._lock:
                item = self._pop_next()
                if item is None:
                    return
                if item.future.set_running_or_notify_cancel():
                    self.stats.queued -= 1
                    self._dec(self.stats.per_table_queued, item.table)
                    self.stats.running += 1
                    break
                # cancelled while queued (caller timed out and already undid
                # the accounting): discard and dispatch the next fair head
        t0 = time.monotonic()
        try:
            item.future.set_result(item.fn())
        except BaseException as e:  # route into the caller's future, never lose it
            item.future.set_exception(e)
        finally:
            elapsed_ms = (time.monotonic() - t0) * 1000.0
            # the table slot frees when the work ACTUALLY finishes — a timed-out
            # caller abandons the worker, but the table stays at its cap until
            # the abandoned query completes (else the cap could be exceeded)
            with self._lock:
                self.stats.running -= 1
                self._release_table(item.table, item.cost_bytes)
                self._service_ms_ewma += 0.2 * (elapsed_ms - self._service_ms_ewma)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        self._pool.shutdown(wait=False)


def scheduler_from_config(cfg) -> Optional["QueryScheduler"]:
    """Build a QueryScheduler from a Configuration's `server.scheduler.*` keys
    (reference: pinot.query.scheduler.* configs consumed by QuerySchedulerFactory);
    returns None when admission control is disabled (the default).

    Fair-scheduling knobs: `server.scheduler.fair.weights` is a JSON object of
    table -> weight (default 1.0 each); `server.scheduler.fair.tenant.bytes`
    bounds one table's in-flight bytes (0 = unlimited)."""
    if not cfg.get_bool("server.scheduler.enabled", False):
        return None
    weights: Dict[str, float] = {}
    raw = cfg.get_str("server.scheduler.fair.weights", "") or ""
    if raw.strip():
        try:
            weights = {str(k): float(v) for k, v in json.loads(raw).items()}
        except (ValueError, TypeError, AttributeError):
            weights = {}
    return QueryScheduler(
        max_concurrent=cfg.get_int("server.scheduler.max.concurrent", 4),
        max_pending=cfg.get_int("server.scheduler.max.pending", 32),
        default_timeout_s=cfg.get_float("server.scheduler.timeout.seconds", 60.0),
        per_table_share=cfg.get_float("server.scheduler.table.share", 1.0),
        tenant_weights=weights,
        max_table_bytes=cfg.get_float("server.scheduler.fair.tenant.bytes", 0.0),
    )


class TokenBucket:
    """Classic token bucket (reference: HitCounter-based QPS tracking in
    QueryQuotaManager; a bucket gives the same steady rate + burst semantics)."""

    def __init__(self, rate_per_s: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_per_s)
        self.capacity = float(burst if burst is not None else max(1.0, rate_per_s))
        self._tokens = self.capacity
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.capacity, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refund(self, n: float = 1.0) -> None:
        """Return tokens taken for an admission that was then aborted."""
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + n)


class QueryQuotaManager:
    """Broker-side per-table QPS quota (reference:
    `pinot-broker/.../queryquota/HelixExternalViewBasedQueryQuotaManager.java`).

    Quotas come from `TableConfig.quota.max_qps`; a table without a quota is
    unlimited. The per-broker rate is the table quota divided by the live broker
    count, like the reference splits quota across brokers."""

    def __init__(self, catalog, broker_count_fn: Optional[Callable[[], int]] = None):
        self.catalog = catalog
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._broker_count_fn = broker_count_fn or (lambda: max(1, sum(
            1 for i in catalog.instances.values()
            if i.role == "broker" and i.alive)))
        self._last_broker_count = self._broker_count_fn()
        catalog.subscribe(self._on_event)

    def _on_event(self, event: str, key: str) -> None:
        if event == "table":
            with self._lock:
                self._buckets.pop(key, None)  # config changed: rebuild lazily
        elif event == "instance":
            # rebuild only when BROKER membership actually changed — server churn
            # must not refill every table's burst allowance
            count = self._broker_count_fn()
            with self._lock:
                if count != self._last_broker_count:
                    self._last_broker_count = count
                    self._buckets.clear()

    def _bucket(self, table: str) -> Optional[TokenBucket]:
        with self._lock:
            if table in self._buckets:
                return self._buckets[table]
        cfg = self.catalog.table_configs.get(table)
        max_qps = getattr(getattr(cfg, "quota", None), "max_qps", None) if cfg else None
        bucket = None
        if max_qps:
            bucket = TokenBucket(float(max_qps) / self._broker_count_fn())
        with self._lock:
            self._buckets[table] = bucket
        return bucket

    def try_acquire(self, table: str) -> bool:
        bucket = self._bucket(table)
        return bucket.try_acquire() if bucket is not None else True

    def refund(self, table: str) -> None:
        bucket = self._bucket(table)
        if bucket is not None:
            bucket.refund()

    def try_acquire_all(self, tables) -> bool:
        """All-or-nothing admission over several physical tables (hybrid split):
        a rejection refunds tokens already taken so no table's quota leaks."""
        taken = []
        for t in tables:
            if self.try_acquire(t):
                taken.append(t)
            else:
                for u in taken:
                    self.refund(u)
                return False
        return True
