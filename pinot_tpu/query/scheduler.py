"""Query scheduling and admission control.

Analog of the reference's `QueryScheduler` hierarchy
(`pinot-core/src/main/java/org/apache/pinot/core/query/scheduler/QueryScheduler.java:56`,
`FCFSQueryScheduler`, `BoundedFCFSScheduler`, `TokenPriorityScheduler` with its
`ResourceManager` semaphores) and the broker's `QueryQuotaManager` (per-table QPS
quotas). TPU framing: a server fronts ONE chip, so admission control is what keeps a
single runaway query from occupying the device while everything else queues — the
scheduler bounds concurrency (device dispatch is serialized by XLA anyway; host-side
decode/plan work does parallelize), bounds the wait queue, enforces wall-clock
timeouts, and accounts per-table usage so one table cannot starve the rest.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor, TimeoutError as FutureTimeout
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


class QueryRejectedError(Exception):
    """Admission denied (queue full / quota exceeded / scheduler stopped).

    Reference: QueryScheduler returning an error DataTable with
    SERVER_SCHEDULER_DOWN/SERVER_OUT_OF_CAPACITY."""


class QueryTimeoutError(Exception):
    """Query exceeded its wall-clock budget (reference: per-query timeoutMs)."""


@dataclass
class SchedulerStats:
    submitted: int = 0
    completed: int = 0
    rejected: int = 0
    timed_out: int = 0
    failed: int = 0
    # live gauges
    running: int = 0
    queued: int = 0
    per_table_running: Dict[str, int] = field(default_factory=dict)

    def snapshot(self) -> Dict[str, Any]:
        return {k: (dict(v) if isinstance(v, dict) else v)
                for k, v in self.__dict__.items()}


class QueryScheduler:
    """Bounded-FCFS scheduler with per-table accounting.

    Queries run on a fixed worker pool (`max_concurrent`); at most `max_pending`
    more may wait; beyond that, submission is rejected immediately — backpressure
    instead of unbounded queue growth, exactly the BoundedFCFS behavior. A
    `per_table_share` < 1 caps how many workers a single table may hold
    concurrently (the ResourceManager's per-query-group semaphore analog).
    """

    def __init__(self, max_concurrent: int = 4, max_pending: int = 32,
                 default_timeout_s: float = 60.0, per_table_share: float = 1.0):
        self.max_concurrent = max_concurrent
        self.max_pending = max_pending
        self.default_timeout_s = default_timeout_s
        # share < 1 caps one table's in-flight (running+queued) queries; 1.0 means
        # no per-table cap — admission is then bounded by max_pending alone
        self.table_cap = (None if per_table_share >= 1.0
                          else max(1, int(max_concurrent * per_table_share)))
        self._pool = ThreadPoolExecutor(max_workers=max_concurrent,
                                        thread_name_prefix="query-sched")
        self._lock = threading.Condition()
        self.stats = SchedulerStats()
        self._stopped = False

    # ------------------------------------------------------------------
    def submit(self, table: str, fn: Callable[[], Any],
               timeout_s: Optional[float] = None) -> Any:
        """Run fn under admission control; blocks the caller until done.

        Raises QueryRejectedError when the server is out of capacity and
        QueryTimeoutError when fn exceeds its budget (the worker is abandoned to
        finish in the background — same as the reference reaping the response
        future; the slot frees when it completes)."""
        from ..utils.metrics import get_registry
        timeout_s = timeout_s if timeout_s is not None else self.default_timeout_s
        with self._lock:
            if self._stopped:
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError("scheduler is shut down")
            if self.stats.queued >= self.max_pending:
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError(
                    f"server out of capacity: {self.stats.queued} queries pending")
            if self.table_cap is not None \
                    and self.stats.per_table_running.get(table, 0) >= self.table_cap:
                self.stats.rejected += 1
                get_registry().counter("pinot_server_queries_rejected").inc()
                raise QueryRejectedError(
                    f"table {table!r} is at its concurrency share ({self.table_cap})")
            self.stats.submitted += 1
            self.stats.queued += 1
            self.stats.per_table_running[table] = \
                self.stats.per_table_running.get(table, 0) + 1

        def release_table_slot():
            n = self.stats.per_table_running.get(table, 1) - 1
            if n <= 0:
                self.stats.per_table_running.pop(table, None)
            else:
                self.stats.per_table_running[table] = n

        def run():
            with self._lock:
                self.stats.queued -= 1
                self.stats.running += 1
            try:
                return fn()
            finally:
                # the table slot frees when the work ACTUALLY finishes — a timed-out
                # caller abandons the worker, but the table stays at its cap until
                # the abandoned query completes (else the cap could be exceeded)
                with self._lock:
                    self.stats.running -= 1
                    release_table_slot()

        try:
            fut: Future = self._pool.submit(run)
        except RuntimeError:
            with self._lock:
                self.stats.rejected += 1
                self.stats.queued -= 1
                release_table_slot()
            get_registry().counter("pinot_server_queries_rejected").inc()
            raise QueryRejectedError("scheduler is shut down") from None
        try:
            result = fut.result(timeout=timeout_s)
            with self._lock:
                self.stats.completed += 1
            return result
        except FutureTimeout:
            cancelled = fut.cancel()  # a still-queued query never needs to run
            get_registry().counter("pinot_server_queries_timed_out").inc()
            with self._lock:
                self.stats.timed_out += 1
                if cancelled:
                    # run() will never execute: undo its accounting here
                    self.stats.queued -= 1
                    release_table_slot()
            raise QueryTimeoutError(f"query exceeded {timeout_s}s") from None
        except Exception:
            with self._lock:
                self.stats.failed += 1
            raise

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        self._pool.shutdown(wait=False)


def scheduler_from_config(cfg) -> Optional["QueryScheduler"]:
    """Build a QueryScheduler from a Configuration's `server.scheduler.*` keys
    (reference: pinot.query.scheduler.* configs consumed by QuerySchedulerFactory);
    returns None when admission control is disabled (the default)."""
    if not cfg.get_bool("server.scheduler.enabled", False):
        return None
    return QueryScheduler(
        max_concurrent=cfg.get_int("server.scheduler.max.concurrent", 4),
        max_pending=cfg.get_int("server.scheduler.max.pending", 32),
        default_timeout_s=cfg.get_float("server.scheduler.timeout.seconds", 60.0),
        per_table_share=cfg.get_float("server.scheduler.table.share", 1.0),
    )


class TokenBucket:
    """Classic token bucket (reference: HitCounter-based QPS tracking in
    QueryQuotaManager; a bucket gives the same steady rate + burst semantics)."""

    def __init__(self, rate_per_s: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_per_s)
        self.capacity = float(burst if burst is not None else max(1.0, rate_per_s))
        self._tokens = self.capacity
        self._last = clock()
        self._clock = clock
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.capacity, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refund(self, n: float = 1.0) -> None:
        """Return tokens taken for an admission that was then aborted."""
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + n)


class QueryQuotaManager:
    """Broker-side per-table QPS quota (reference:
    `pinot-broker/.../queryquota/HelixExternalViewBasedQueryQuotaManager.java`).

    Quotas come from `TableConfig.quota.max_qps`; a table without a quota is
    unlimited. The per-broker rate is the table quota divided by the live broker
    count, like the reference splits quota across brokers."""

    def __init__(self, catalog, broker_count_fn: Optional[Callable[[], int]] = None):
        self.catalog = catalog
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._broker_count_fn = broker_count_fn or (lambda: max(1, sum(
            1 for i in catalog.instances.values()
            if i.role == "broker" and i.alive)))
        self._last_broker_count = self._broker_count_fn()
        catalog.subscribe(self._on_event)

    def _on_event(self, event: str, key: str) -> None:
        if event == "table":
            with self._lock:
                self._buckets.pop(key, None)  # config changed: rebuild lazily
        elif event == "instance":
            # rebuild only when BROKER membership actually changed — server churn
            # must not refill every table's burst allowance
            count = self._broker_count_fn()
            with self._lock:
                if count != self._last_broker_count:
                    self._last_broker_count = count
                    self._buckets.clear()

    def _bucket(self, table: str) -> Optional[TokenBucket]:
        with self._lock:
            if table in self._buckets:
                return self._buckets[table]
        cfg = self.catalog.table_configs.get(table)
        max_qps = getattr(getattr(cfg, "quota", None), "max_qps", None) if cfg else None
        bucket = None
        if max_qps:
            bucket = TokenBucket(float(max_qps) / self._broker_count_fn())
        with self._lock:
            self._buckets[table] = bucket
        return bucket

    def try_acquire(self, table: str) -> bool:
        bucket = self._bucket(table)
        return bucket.try_acquire() if bucket is not None else True

    def refund(self, table: str) -> None:
        bucket = self._bucket(table)
        if bucket is not None:
            bucket.refund()

    def try_acquire_all(self, tables) -> bool:
        """All-or-nothing admission over several physical tables (hybrid split):
        a rejection refunds tokens already taken so no table's quota leaks."""
        taken = []
        for t in tables:
            if self.try_acquire(t):
                taken.append(t)
            else:
                for u in taken:
                    self.refund(u)
                return False
        return True
