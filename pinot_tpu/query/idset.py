"""IdSet: a serializable value-set filter that travels between queries.

Analog of the reference's id-set subsystem: the `IDSET(col)` aggregation builds a
compact set of a column's values (`pinot-core/.../query/utils/idset/IdSets.java`,
`IdSetAggregationFunction`), `IN_ID_SET(col, 'base64')` filters against one
(`InIdSetTransformFunction`), and the broker rewrites `IN_SUBQUERY(col, 'sql')` by
running the inner query first and splicing its serialized id-set into the outer
filter (`BaseBrokerRequestHandler.java:782` subquery recursion).

TPU-first departure: the reference keys RoaringBitmap/Roaring64 sets on *values*
because dict ids are segment-local — the same is true here, so the set's domain is
values (int64 / float64 / strings). On a dictionary-encoded column membership is
resolved host-side once against the *sorted dictionary* (O(card), not O(docs)),
producing the same boolean-LUT filter leaf as IN/EQ — the device work is identical
to any other dictionary predicate (id-interval compares or one gather), so an
id-set filter rides the fused kernel with zero extra dispatches.
"""

from __future__ import annotations

import base64
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Any, List, Union

import numpy as np

# Exact sets only: beyond this the serialized form stops being a sane query literal.
# (The reference switches to a Bloom filter past a threshold; exact-with-cap keeps
# differential correctness — revisit if a workload needs approximate id-sets.)
MAX_IDSET_VALUES = 4_000_000

_MAGIC = b"PIDS"


class IdSetError(ValueError):
    pass


class IdSet:
    """Sorted-unique value set. `kind` is "i8" (int64), "f8" (float64) or "str"."""

    def __init__(self, kind: str, values: np.ndarray):
        assert kind in ("i8", "f8", "str"), kind
        self.kind = kind
        self.values = values  # sorted unique; dtype int64/float64/object(str)
        self._str_set = None  # lazy python set for string membership
        self._i8_view = None  # lazy int64 view of an f8 set (int-probe path)
        self._u8_view = None  # lazy uint64 view (probes >= 2**63)

    def _int_view(self) -> np.ndarray:
        """Sorted int64 view of an f8 set: the integral, exactly-representable
        members (cached — contains() runs once per segment)."""
        if self._i8_view is None:
            sv = self.values
            ok = (np.isfinite(sv) & (np.floor(sv) == sv)
                  & (sv >= -9.223372036854776e18) & (sv < 9.223372036854776e18))
            vi = sv[ok].astype(np.int64)
            self._i8_view = np.unique(vi[vi.astype(np.float64) == sv[ok]])
        return self._i8_view

    def _uint_view(self) -> np.ndarray:
        """Sorted uint64 view of an f8 set for the [2**63, 2**64) range —
        unsigned probes up there would WRAP in an int64 cast."""
        if self._u8_view is None:
            sv = self.values
            ok = (np.isfinite(sv) & (np.floor(sv) == sv)
                  & (sv >= 9.223372036854776e18) & (sv < 1.8446744073709552e19))
            vu = sv[ok].astype(np.uint64)
            self._u8_view = np.unique(vu[vu.astype(np.float64) == sv[ok]])
        return self._u8_view

    def __len__(self) -> int:
        return len(self.values)

    def __eq__(self, other) -> bool:
        return (isinstance(other, IdSet) and self.kind == other.kind
                and len(self.values) == len(other.values)
                and bool(np.all(self.values == other.values)))

    # -- construction ------------------------------------------------------

    @classmethod
    def empty(cls) -> "IdSet":
        return cls("i8", np.empty(0, dtype=np.int64))

    @classmethod
    def from_values(cls, values: Union[np.ndarray, List[Any]]) -> "IdSet":
        arr = np.asarray(values)
        if arr.size > MAX_IDSET_VALUES:
            raise IdSetError(
                f"id-set over {arr.size} values exceeds the {MAX_IDSET_VALUES} cap")
        if arr.size == 0:
            return cls.empty()
        if arr.dtype == object or arr.dtype.kind in ("U", "S"):
            vals = np.array(sorted({str(x) for x in arr.reshape(-1)
                                    if x is not None}), dtype=object)
            return cls("str", vals)
        if arr.dtype.kind in ("i", "u", "b"):
            return cls("i8", np.unique(arr.astype(np.int64)))
        if arr.dtype.kind == "f":
            vals = arr.astype(np.float64)
            vals = vals[~np.isnan(vals)]
            return cls("f8", np.unique(vals))
        raise IdSetError(f"unsupported id-set value dtype {arr.dtype}")

    # -- set algebra -------------------------------------------------------

    def union(self, other: "IdSet") -> "IdSet":
        if len(other) == 0:
            return self
        if len(self) == 0:
            return other
        if self.kind != other.kind:
            # int/float mixes promote to float (same value-equality the engine uses
            # for numeric compares); anything-with-str is a type error
            if {self.kind, other.kind} == {"i8", "f8"}:
                a = self.values.astype(np.float64)
                b = other.values.astype(np.float64)
                out = np.unique(np.concatenate((a, b)))
                if out.size > MAX_IDSET_VALUES:
                    raise IdSetError("id-set union exceeds value cap")
                return IdSet("f8", out)
            raise IdSetError(f"cannot union id-sets of kind {self.kind}/{other.kind}")
        if self.kind == "str":
            merged = np.array(sorted(set(self.values) | set(other.values)),
                              dtype=object)
        else:
            merged = np.unique(np.concatenate((self.values, other.values)))
        if merged.size > MAX_IDSET_VALUES:
            raise IdSetError("id-set union exceeds value cap")
        return IdSet(self.kind, merged)

    # -- membership --------------------------------------------------------

    def contains(self, probe: np.ndarray) -> np.ndarray:
        """Vectorized membership: bool mask over `probe` (any shape, flattened)."""
        probe = np.asarray(probe)
        flat = probe.reshape(-1)
        if len(self.values) == 0:
            return np.zeros(flat.shape, dtype=bool)
        if self.kind == "str":
            if self._str_set is None:
                self._str_set = set(self.values)
            s = self._str_set
            return np.fromiter((x is not None and str(x) in s for x in flat),
                               dtype=bool, count=len(flat))
        if probe.dtype == object or probe.dtype.kind in ("U", "S"):
            return np.zeros(flat.shape, dtype=bool)  # numeric set vs string column
        vals = self.values
        # cross-kind numeric probes compare in the INT64 domain when both
        # sides are integral-valued: casting int64<->float64 loses precision
        # above 2^53 (the same hazard the theta path's _canonical guards
        # against) and would produce false membership matches/misses
        if self.kind == "i8" and flat.dtype.kind == "f":
            out = np.zeros(flat.shape, dtype=bool)
            f = flat.astype(np.float64)
            ok = (np.isfinite(f) & (np.floor(f) == f)
                  & (f >= -9.223372036854776e18) & (f < 9.223372036854776e18))
            probe_i = f[ok].astype(np.int64)
            # above 2^53 one float spans many ints — require the exact
            # round-trip so only truly representable members match
            exact = probe_i.astype(np.float64) == f[ok]
            idx_c = np.minimum(np.searchsorted(vals, probe_i), len(vals) - 1)
            out[np.flatnonzero(ok)] = exact & (vals[idx_c] == probe_i)
            return out
        if self.kind == "f8" and flat.dtype.kind in ("i", "u", "b"):
            out = np.zeros(flat.shape, dtype=bool)
            lo = np.ones(flat.shape, dtype=bool)
            if flat.dtype.kind == "u" and flat.dtype.itemsize == 8:
                # uint64 probes >= 2**63 would WRAP in the int64 cast —
                # compare that range in the uint64 domain instead
                hi = flat >= np.uint64(1) << np.uint64(63)
                lo = ~hi
                vu = self._uint_view()
                if vu.size and hi.any():
                    fh = flat[hi]
                    idx_c = np.minimum(np.searchsorted(vu, fh), len(vu) - 1)
                    out[np.flatnonzero(hi)] = vu[idx_c] == fh
            vi = self._int_view()
            if vi.size and lo.any():
                fl = flat[lo].astype(np.int64)
                idx_c = np.minimum(np.searchsorted(vi, fl), len(vi) - 1)
                out[np.flatnonzero(lo)] = vi[idx_c] == fl
            return out
        # sorted-set membership via searchsorted: O(n log card), no hash build
        idx = np.searchsorted(vals, flat)
        idx_c = np.minimum(idx, len(vals) - 1)
        return vals[idx_c] == flat

    # -- serialization -----------------------------------------------------

    def to_bytes(self) -> bytes:
        if self.kind == "str":
            parts = []
            for v in self.values:
                raw = str(v).encode("utf-8")
                parts.append(struct.pack("<I", len(raw)))
                parts.append(raw)
            body = b"".join(parts)
        else:
            body = self.values.tobytes()
        return (_MAGIC + self.kind.ljust(3).encode()
                + struct.pack("<I", len(self.values)) + body)

    @classmethod
    def from_bytes(cls, data: bytes) -> "IdSet":
        if data[:4] != _MAGIC:
            raise IdSetError("bad id-set header")
        kind = data[4:7].decode().strip()
        (n,) = struct.unpack("<I", data[7:11])
        body = data[11:]
        if n == 0:
            return cls.empty()
        if kind == "str":
            out: List[str] = []
            pos = 0
            for _ in range(n):
                if pos + 4 > len(body):
                    raise IdSetError("truncated id-set string body")
                (ln,) = struct.unpack_from("<I", body, pos)
                pos += 4
                out.append(body[pos:pos + ln].decode("utf-8"))
                pos += ln
            vals = np.array(out, dtype=object)
        else:
            vals = np.frombuffer(body, dtype=np.int64 if kind == "i8" else np.float64)
        if len(vals) != n:
            raise IdSetError("id-set length mismatch")
        return cls(kind, vals)

    def serialize(self) -> str:
        return base64.b64encode(zlib.compress(self.to_bytes())).decode("ascii")

    @classmethod
    def deserialize(cls, s: str) -> "IdSet":
        # memoized: filter compilation runs per segment, and the same (often large)
        # literal is decoded by every segment of every query using it
        with _CACHE_LOCK:
            hit = _CACHE.get(s)
            if hit is not None:
                _CACHE.move_to_end(s)
                return hit
        out = _deserialize_uncached(s)
        with _CACHE_LOCK:
            _CACHE[s] = out
            _CACHE.move_to_end(s)
            # size-weighted eviction: bound resident decoded values, not entry
            # count — 64 near-cap sets would otherwise pin GBs forever
            total = sum(len(v) for v in _CACHE.values())
            while total > _CACHE_MAX_TOTAL_VALUES and len(_CACHE) > 1:
                _, evicted = _CACHE.popitem(last=False)
                total -= len(evicted)
        return out


# literal-string -> decoded IdSet, LRU by total decoded values
_CACHE: "OrderedDict[str, IdSet]" = OrderedDict()
_CACHE_LOCK = threading.Lock()
_CACHE_MAX_TOTAL_VALUES = 8_000_000


def _deserialize_uncached(s: str) -> IdSet:
    try:
        return IdSet.from_bytes(zlib.decompress(base64.b64decode(s.encode("ascii"))))
    except (ValueError, zlib.error, struct.error) as exc:
        raise IdSetError(f"malformed id-set literal: {exc}") from exc
