"""Aggregation functions: device partial computation spec + host merge/finalize.

Analog of the reference's `AggregationFunction` interface
(`pinot-core/.../query/aggregation/function/`, 58 classes): each function defines
(1) which fused-kernel outputs it needs on device (`device_outputs`),
(2) how per-segment partial states merge across segments/servers (`merge` — the reference's
    `merge(intermediate, intermediate)`), and
(3) how a final value is extracted (`finalize` — `extractFinalResult`).

Functions whose exact semantics need raw values (percentile, mode, exact distinct-count on
expressions) run on the host path; the planner asks `device_ok()`.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..sql.ast import Expr, Function, Identifier
from .context import QueryValidationError


@dataclass
class AggContext:
    """Static facts the planner knows when choosing the device/host path."""
    group_by: bool
    arg_is_dict_column: bool  # argument is a plain dictionary-encoded column
    arg_is_numeric: bool


class AggFunc:
    name: str = ""
    device_outputs: Tuple[str, ...] = ()  # subset of {count,sum,min,max,distinct}

    def __init__(self, call: Function):
        self.call = call
        self.arg: Optional[Expr] = call.args[0] if call.args else None

    # -- capability --------------------------------------------------------
    def device_ok(self, ctx: AggContext) -> bool:
        return True

    # -- host path ---------------------------------------------------------
    def host_state(self, values: np.ndarray) -> Any:
        """Build a partial state from the filtered argument values of one segment."""
        raise NotImplementedError

    def state_from_device(self, outs: Dict[str, float]) -> Any:
        """Build the same state from the fused kernel's per-key outputs."""
        raise NotImplementedError

    def state_from_value_set(self, values: set) -> Any:
        """State from the device `distinct` output's surviving value set.
        Sketch aggregations override to convert to their bounded state HERE —
        a single-segment server ships this state over the wire without any
        merge call, and an exact value set would defeat the sketch's
        bounded-size purpose."""
        return values

    def state_from_present_ids(self, dictionary, present_ids: np.ndarray) -> Any:
        """State straight from the device presence vector's surviving DICT IDS.
        Default decodes the values and defers to `state_from_value_set`;
        aggregations whose state depends only on per-value derived data (HLL's
        bucket/rank) override to skip the per-query value materialization."""
        values = dictionary.take(present_ids)
        return self.state_from_value_set(set(values.tolist()))

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, state: Any) -> Any:
        raise NotImplementedError

    # -- vectorized decode (the dense group fast path) ---------------------
    #: dense_values emits NaN where the scalar finalize would return None
    #: (e.g. VAR_SAMP of a single row); the dense reducer converts
    dense_nan_is_null = False

    def dense_values(self, get, counts: np.ndarray) -> Optional[np.ndarray]:
        """Finalized values over ALL occupied groups at once, or None when
        this aggregation has no dense path (sketches/value-set states).

        `get(name)` returns this agg's kernel output column sliced to the
        occupied dense keys; `counts` is the per-group matched row count
        (> 0 for every occupied key, so the None-state cases of the scalar
        `finalize` cannot occur except where `dense_nan_is_null` says so).
        High-cardinality GROUP BY decodes through this instead of a
        per-group Python state loop — the loop costs more than the fused
        kernel once groups reach the tens of thousands."""
        return None

    def empty_result(self) -> Any:
        """Result over zero rows (no group-by), mirroring reference defaults."""
        return None

    def validate_args(self, segment) -> None:
        """Plan-time argument validation against one segment's column types;
        raise QueryValidationError for shapes whose host path would crash deep
        in numpy (reference: AggregationFunctionFactory type checks)."""


class CountAgg(AggFunc):
    name = "count"
    device_outputs = ("count",)

    def host_state(self, values):
        return int(len(values))

    def state_from_device(self, outs):
        return int(outs["count"])

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return int(state)

    def dense_values(self, get, counts):
        return counts.astype(np.int64)

    def empty_result(self):
        return 0


class SumAgg(AggFunc):
    name = "sum"
    device_outputs = ("sum",)

    def host_state(self, values):
        return float(np.sum(values)) if len(values) else None

    def state_from_device(self, outs):
        return float(outs["sum"]) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a + b

    def finalize(self, state):
        return None if state is None else float(state)

    def dense_values(self, get, counts):
        return get("sum").astype(np.float64)


class MinAgg(AggFunc):
    name = "min"
    device_outputs = ("min",)

    def host_state(self, values):
        return float(np.min(values)) if len(values) else None

    def state_from_device(self, outs):
        return float(outs["min"]) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return min(a, b)

    def finalize(self, state):
        return None if state is None else float(state)

    def dense_values(self, get, counts):
        return get(self.device_outputs[0]).astype(np.float64)


class MaxAgg(MinAgg):
    name = "max"
    device_outputs = ("max",)

    def host_state(self, values):
        return float(np.max(values)) if len(values) else None

    def state_from_device(self, outs):
        return float(outs["max"]) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return max(a, b)


class AvgAgg(AggFunc):
    name = "avg"
    device_outputs = ("sum", "count")

    def host_state(self, values):
        return (float(np.sum(values)), len(values))

    def state_from_device(self, outs):
        return (float(outs["sum"]), int(outs["count"]))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, state):
        s, c = state
        return None if c == 0 else s / c

    def dense_values(self, get, counts):
        return get("sum").astype(np.float64) / counts


class MinMaxRangeAgg(AggFunc):
    name = "minmaxrange"
    device_outputs = ("min", "max")

    def host_state(self, values):
        if not len(values):
            return None
        return (float(np.min(values)), float(np.max(values)))

    def state_from_device(self, outs):
        if outs["count"] == 0:
            return None
        return (float(outs["min"]), float(outs["max"]))

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return (min(a[0], b[0]), max(a[1], b[1]))

    def finalize(self, state):
        return None if state is None else state[1] - state[0]

    def dense_values(self, get, counts):
        return (get("max").astype(np.float64)
                - get("min").astype(np.float64))


class DistinctCountAgg(AggFunc):
    """Exact distinct count. Device path: per-dict-id presence vector (no group-by);
    states merge as value sets across segments since dictionaries differ per segment."""
    name = "distinctcount"
    device_outputs = ("distinct",)

    def device_ok(self, ctx: AggContext) -> bool:
        # grouped path: the kernel emits a per-group presence matrix (the
        # planner bounds its size via MAX_GROUPED_DISTINCT_CELLS)
        return ctx.arg_is_dict_column

    def host_state(self, values):
        return set(np.unique(values).tolist())

    def merge(self, a, b):
        return a | b

    def finalize(self, state):
        return len(state)

    def empty_result(self):
        return 0


HLL_DEFAULT_P = 12  # 4096 registers, ~1.6% relative error


def hll_hash(value) -> int:
    """64-bit stable hash for HLL bucketing."""
    import hashlib
    if isinstance(value, bytes):
        data = value
    elif isinstance(value, (float, np.floating)) and float(value).is_integer():
        data = str(int(value)).encode()
    else:
        data = str(value).encode()
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def hll_bucket_rank(value, p: int) -> Tuple[int, int]:
    h = hll_hash(value)
    bucket = h >> (64 - p)
    # remaining bits shifted to the top of the word; low p bits are zero-filled, so
    # leading zeros of the 64-bit word count within the (64-p)-bit window
    w = (h << p) & ((1 << 64) - 1)
    rank = (64 - p) + 1 if w == 0 else min(64 - w.bit_length() + 1, (64 - p) + 1)
    return bucket, rank


def hll_estimate(registers: np.ndarray) -> float:
    """Standard HyperLogLog estimator with small-range correction."""
    m = len(registers)
    alpha = 0.7213 / (1 + 1.079 / m) if m >= 128 else {16: 0.673, 32: 0.697,
                                                       64: 0.709}.get(m, 0.7213)
    est = alpha * m * m / np.sum(np.exp2(-registers.astype(np.float64)))
    zeros = int(np.sum(registers == 0))
    if est <= 2.5 * m and zeros > 0:
        est = m * np.log(m / zeros)
    return float(est)


class DistinctCountHLLAgg(AggFunc):
    """Approximate distinct count via HyperLogLog (reference:
    DistinctCountHLLAggregationFunction, default log2m in
    `CommonConstants.Helix.DEFAULT_HYPERLOGLOG_LOG2M`).

    TPU path (dict-column arg, no group-by): the fused kernel's per-dict-id
    PRESENCE vector (the same one-hot-matmul `distinct` output DISTINCTCOUNT
    and the theta sketch use — MXU work, no scatter) comes back, and the
    registers are built host-side from the surviving dictionary values —
    O(cardinality), not O(rows). An earlier design updated registers on device
    via `segment_max(rank_lut[ids], bucket_lut[ids])`; the scatter serialized
    badly on this backend (~15x slower than the matmul presence path measured
    on the SSB HLL config). States merge by elementwise register max; device
    states stay as value sets until first merge/finalize, like theta.
    """

    name = "distinctcounthll"
    device_outputs = ("distinct",)

    def __init__(self, call: Function):
        super().__init__(call)
        self.p = HLL_DEFAULT_P
        if len(call.args) >= 2:
            from ..sql.ast import Literal
            if isinstance(call.args[1], Literal):
                self.p = int(call.args[1].value)

    def device_ok(self, ctx: AggContext) -> bool:
        # grouped HLL rides the per-group presence matrix (BASELINE config 5)
        return ctx.arg_is_dict_column

    def host_state(self, values) -> np.ndarray:
        regs = np.zeros(1 << self.p, dtype=np.int8)
        for v in np.unique(np.asarray(values, dtype=object)):
            b, r = hll_bucket_rank(v, self.p)
            regs[b] = max(regs[b], r)
        return regs

    def state_from_present_ids(self, dictionary, present_ids: np.ndarray):
        """Registers straight from a presence vector, via a (bucket, rank)
        table cached ON the dictionary object (lifetime-correct: a dictionary
        lives exactly as long as its segment). Hashing every dictionary value
        is paid once per dictionary instead of once per query — the per-query
        cost drops to one vectorized maximum.at over the surviving ids."""
        cache = getattr(dictionary, "_hll_br", None)
        if cache is None:
            cache = {}
            try:
                dictionary._hll_br = cache
            except AttributeError:
                return super().state_from_present_ids(dictionary, present_ids)
        br = cache.get(self.p)
        if br is None:
            vals = np.asarray(dictionary.take(np.arange(len(dictionary))),
                              dtype=object)
            buckets = np.empty(len(vals), dtype=np.int32)
            ranks = np.empty(len(vals), dtype=np.int8)
            for i, v in enumerate(vals):
                buckets[i], ranks[i] = hll_bucket_rank(v, self.p)
            br = cache[self.p] = (buckets, ranks)
        regs = np.zeros(1 << self.p, dtype=np.int8)
        np.maximum.at(regs, br[0][present_ids], br[1][present_ids])
        return regs

    def _normalize(self, state) -> np.ndarray:
        if isinstance(state, set):  # device path returns the exact value set
            return self.host_state(np.asarray(list(state), dtype=object))
        return state

    def state_from_value_set(self, values: set) -> np.ndarray:
        return self._normalize(values)

    def merge(self, a, b):
        return np.maximum(self._normalize(a), self._normalize(b))

    def finalize(self, state) -> int:
        return int(round(hll_estimate(self._normalize(state))))

    def empty_result(self):
        return 0


class PercentileAgg(AggFunc):
    """Exact percentile — keeps filtered values per state (host-path only).
    `percentile(col, p)` or legacy `percentileNN(col)`."""
    name = "percentile"

    def __init__(self, call: Function):
        super().__init__(call)
        self.pct = _parse_percentile(call, "percentile")

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def host_state(self, values):
        return np.asarray(values, dtype=np.float64)

    def merge(self, a, b):
        return np.concatenate([a, b])

    def finalize(self, state):
        return None if len(state) == 0 else float(np.percentile(state, self.pct))


def _parse_percentile(call: Function, base: str) -> float:
    """`<base>NN(col)` suffix form or `<base>(col, NN)` argument form."""
    if call.name.startswith(base) and call.name[len(base):].isdigit():
        return float(call.name[len(base):])
    if len(call.args) >= 2:
        from ..sql.ast import Literal
        assert isinstance(call.args[1], Literal)
        return float(call.args[1].value)
    raise QueryValidationError(f"{call.name} needs a percentile argument")


class DistinctCountThetaAgg(AggFunc):
    """DISTINCTCOUNTTHETASKETCH — KMV theta sketch state (`sketches.ThetaSketch`).

    Reference: DistinctCountThetaSketchAggregationFunction (DataSketches theta). On the
    device path over a dict column the exact present-id set comes back from the kernel
    (same output as DISTINCTCOUNT); the sketch is built from the surviving dictionary
    values host-side — cardinality-sized work, not row-sized.
    """
    name = "distinctcountthetasketch"
    device_outputs = ("distinct",)

    def __init__(self, call: Function):
        super().__init__(call)
        from ..sql.ast import Literal
        self.k = 4096
        if len(call.args) >= 2 and isinstance(call.args[1], Literal):
            # reference accepts 'nominalEntries=NNNN' parameter strings
            s = str(call.args[1].value)
            if "=" in s:
                self.k = int(s.split("=", 1)[1])
            elif s.isdigit():
                self.k = int(s)
        # filtered set-op form (reference signature:
        # DISTINCTCOUNTTHETASKETCH(col, 'params', 'pred1', ..., 'SET_OP($1,$2)')):
        # one sketch per predicate over the rows surviving the MAIN filter,
        # combined by the post-op expression at finalize
        self.filter_exprs: List[Expr] = []
        self.post_op: Optional[str] = None
        if len(call.args) == 3:
            raise QueryValidationError(
                f"{self.name}: the filtered form needs at least one predicate "
                "AND a set-op expression — (col, 'params', 'pred1', ..., "
                "'SET_OP($1, ...)'); a lone third argument would be silently "
                "ignored")
        if len(call.args) >= 4:
            from ..sql.parser import Parser
            preds = call.args[2:-1]
            post = call.args[-1]
            if not all(isinstance(p, Literal) for p in (*preds, post)):
                raise QueryValidationError(
                    f"{self.name}: predicate/set-op arguments must be string literals")
            for p in preds:
                stmt = Parser(f"SELECT 1 FROM t WHERE {p.value}").parse()
                self.filter_exprs.append(stmt.where)
            self.post_op = str(post.value)
            # evaluate the key column AND each predicate as one packed argument
            # (the executor's agg surface is single-expression; same trick as
            # COVAR's __pack, object-typed so string keys survive)
            self.arg = Function("__packobj",
                                (call.args[0], *self.filter_exprs))

    def device_ok(self, ctx: AggContext) -> bool:
        return ctx.arg_is_dict_column and not self.filter_exprs

    @staticmethod
    def _canonical(values) -> np.ndarray:
        """One hash domain per logical type across segments AND device/host paths (the
        device path yields python ints where the host path sees the column dtype).
        Integers stay integral — float64 would collapse distinct int64s above 2^53."""
        arr = np.asarray(list(values) if isinstance(values, set) else values)
        if arr.dtype == object and arr.size:
            # the filtered path's __packobj matrix is object-typed; restore the
            # numeric hash domain or identical ids would hash differently from
            # the unfiltered/device path (raw-sketch clients intersect across
            # queries)
            if all(isinstance(v, (int, np.integer)) for v in arr.reshape(-1)):
                arr = arr.astype(np.int64)
            elif all(isinstance(v, (float, np.floating)) for v in arr.reshape(-1)):
                arr = arr.astype(np.float64)
        if arr.dtype.kind in "iub":
            return arr.astype(np.int64)
        if arr.dtype.kind == "f":
            return arr.astype(np.float64)
        return arr

    def _normalize(self, state):
        from .sketches import ThetaSketch
        if isinstance(state, set):  # device path returns the exact value set
            return ThetaSketch.from_values(self._canonical(state), self.k)
        return state

    def state_from_value_set(self, values: set):
        return self._normalize(values)

    def state_from_present_ids(self, dictionary, present_ids: np.ndarray):
        """KMV sketch straight from the device presence vector, via a 64-bit
        hash table cached ON the dictionary (HLL's bucket/rank trick, same
        lifetime argument): hashing every dictionary value is paid once per
        dictionary, and the per-query cost is one vectorized k-min over the
        surviving ids — no per-query python-loop hashing of string values."""
        from .sketches import ThetaSketch, hash64
        cache = getattr(dictionary, "_theta_h64", None)
        if cache is None:
            vals = np.asarray(dictionary.take(np.arange(len(dictionary))),
                              dtype=object)
            cache = hash64(self._canonical(vals))
            try:
                dictionary._theta_h64 = cache
            except AttributeError:
                return super().state_from_present_ids(dictionary, present_ids)
        sk = ThetaSketch(self.k)
        sk._absorb(np.unique(cache[present_ids]))
        return sk

    def host_state(self, values):
        from .sketches import ThetaSketch
        if self.filter_exprs:
            arr = np.asarray(values)  # [n, 1+m] object matrix from __packobj
            keys = arr[:, 0] if arr.ndim == 2 else np.empty(0, dtype=object)
            out = []
            for j in range(len(self.filter_exprs)):
                mask = arr[:, 1 + j].astype(bool) if arr.ndim == 2 \
                    else np.empty(0, dtype=bool)
                out.append(ThetaSketch.from_values(
                    self._canonical(keys[mask]), self.k))
            return tuple(out)
        return ThetaSketch.from_values(self._canonical(values), self.k)

    def merge(self, a, b):
        if self.filter_exprs:
            return tuple(x.union(y) for x, y in zip(a, b))
        return self._normalize(a).union(self._normalize(b))

    def _combined(self, state):
        from .sketches import ThetaSketch
        if not self.filter_exprs:
            return self._normalize(state)
        if state is None:
            return ThetaSketch(self.k)
        return _eval_theta_setop(self.post_op, list(state))

    def finalize(self, state):
        return int(round(self._combined(state).estimate()))

    def empty_result(self):
        return 0


def _eval_theta_setop(expr: str, sketches: List) -> "object":
    """Parse + evaluate the reference's theta post-aggregation expression:
    `$N` (1-based sketch refs), SET_UNION(...), SET_INTERSECT(...),
    SET_DIFF(a, b) (reference: DistinctCountThetaSketchAggregationFunction's
    postAggregationExpression)."""
    import re as _re
    src = expr or "$1"
    toks = []
    i = 0
    while i < len(src):  # position-tracking lexer: unknown chars ERROR, never vanish
        if src[i].isspace():
            i += 1
            continue
        m = _re.match(r"\$\d+|[A-Za-z_]+|[(),]", src[i:])
        if m is None:
            raise QueryValidationError(
                f"theta set-op: unexpected character {src[i]!r} in {expr!r}")
        toks.append(m.group(0))
        i += len(m.group(0))
    pos = [0]

    def peek():
        return toks[pos[0]] if pos[0] < len(toks) else None

    def take():
        t = peek()
        pos[0] += 1
        return t

    def parse_node():
        t = take()
        if t is None:
            raise QueryValidationError(f"theta set-op: unexpected end in {expr!r}")
        if t.startswith("$"):
            i = int(t[1:]) - 1
            if not 0 <= i < len(sketches):
                raise QueryValidationError(
                    f"theta set-op references ${i + 1} but only "
                    f"{len(sketches)} filter sketches exist")
            return sketches[i]
        op = t.upper()
        if op not in ("SET_UNION", "SET_INTERSECT", "SET_DIFF"):
            raise QueryValidationError(f"unknown theta set-op {t!r}")
        if take() != "(":
            raise QueryValidationError(f"theta set-op: expected ( after {t}")
        args = [parse_node()]
        while peek() == ",":
            take()
            args.append(parse_node())
        if take() != ")":
            raise QueryValidationError(f"theta set-op: expected ) in {expr!r}")
        if op == "SET_DIFF":
            if len(args) != 2:
                raise QueryValidationError("SET_DIFF takes exactly two arguments")
            return args[0].a_not_b(args[1])
        out = args[0]
        for a in args[1:]:
            out = out.union(a) if op == "SET_UNION" else out.intersect(a)
        return out

    node = parse_node()
    if peek() is not None:
        raise QueryValidationError(f"theta set-op: trailing tokens in {expr!r}")
    return node


class DistinctCountRawThetaAgg(DistinctCountThetaAgg):
    """DISTINCTCOUNTRAWTHETASKETCH — returns the serialized sketch (hex) instead of the
    estimate, for client-side set operations (reference: ...RawThetaSketchAggregationFunction)."""
    name = "distinctcountrawthetasketch"

    def finalize(self, state):
        return self._combined(state).to_bytes().hex()

    def empty_result(self):
        from .sketches import ThetaSketch
        return ThetaSketch(self.k).to_bytes().hex()


class PercentileTDigestAgg(AggFunc):
    """PERCENTILETDIGEST / PERCENTILETDIGESTNN — merging t-digest state.

    Reference: PercentileTDigestAggregationFunction (com.tdunning TDigest). Bounded-size
    mergeable state — unlike PercentileAgg's exact value buffer, this flows through
    multi-host reduce without shipping raw rows.
    """
    name = "percentiletdigest"
    pct_base = "percentiletdigest"  # suffix parsing base — MV subclasses keep
    # the parent's base because their call name was already 'mv'-stripped
    COMPRESSION = 100.0
    # device path: ride the per-dict-id COUNT vector (not mere presence) —
    # a dictionary's sorted values + masked multiplicities build the digest
    # at O(cardinality) host cost after the row-sized work ran on device
    device_outputs = ("distinct",)
    wants_id_counts = True

    def __init__(self, call: Function):
        super().__init__(call)
        self.pct = _parse_percentile(call, self.pct_base)

    def device_ok(self, ctx: AggContext) -> bool:
        return ctx.arg_is_dict_column and ctx.arg_is_numeric

    def host_state(self, values):
        from .sketches import TDigest
        return TDigest.from_values(values, self.COMPRESSION)

    def state_from_id_counts(self, dictionary, counts: np.ndarray):
        """Counts per dict id -> weighted digest over the SORTED dictionary
        values. The float64 value array caches ON the dictionary (lifetime =
        the segment's, same as HLL's bucket/rank table): a grouped decode
        calls this once per group, and re-materializing the dictionary per
        group would cost O(groups x cardinality)."""
        from .sketches import TDigest
        vals = getattr(dictionary, "_td_vals", None)
        if vals is None or len(vals) < len(counts):
            vals = np.asarray(dictionary.take(np.arange(len(counts))),
                              dtype=np.float64)
            try:
                dictionary._td_vals = vals
            except AttributeError:
                pass
        return TDigest.from_weighted(vals[:len(counts)], counts,
                                     self.COMPRESSION)

    def merge(self, a, b):
        return a.merge(b)

    def finalize(self, state):
        q = state.quantile(self.pct / 100.0)
        return None if q is None else float(q)


class PercentileEstAgg(PercentileTDigestAgg):
    """PERCENTILEEST — approximate long-valued percentile (reference uses QuantileDigest;
    here the same t-digest state with integer extraction)."""
    name = "percentileest"
    pct_base = "percentileest"

    def finalize(self, state):
        q = state.quantile(self.pct / 100.0)
        return None if q is None else int(round(q))


class ModeAgg(AggFunc):
    name = "mode"

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def host_state(self, values):
        return Counter(values.tolist())

    def merge(self, a, b):
        a.update(b)
        return a

    def finalize(self, state):
        if not state:
            return None
        # ties broken by smallest value, matching reference MODE default
        best = max(state.items(), key=lambda kv: (kv[1], -kv[0] if isinstance(kv[0], (int, float)) else 0))
        return float(best[0]) if isinstance(best[0], (int, float)) else best[0]


# -- moment-based aggregations (reference: VarianceAggregationFunction,
# SkewnessAggregationFunction / FourthMomentAggregationFunction) -------------
# States are tuples of raw power sums (n, Σx, Σx², ...): exactly mergeable
# across segments/servers and computable on device as stacked masked-sum rows
# (kernels._POWER_SUMS) — the TPU analog of the reference's PinotFourthMoment
# combine. Central moments are derived only at finalize.

class MomentAgg(AggFunc):
    """Base for power-sum states (n, Σx, Σx², ...): element-wise mergeable, and
    decodable generically from the kernel's per-power outputs."""

    def state_from_device(self, outs):
        return (int(outs["count"]),) + tuple(
            float(outs.get(o, 0.0)) for o in self.device_outputs if o != "count")

    def merge(self, a, b):
        return tuple(x + y for x, y in zip(a, b))

    def validate_args(self, segment) -> None:
        _require_numeric_arg(self, segment)


class VarianceAgg(MomentAgg):
    """VAR_POP / VAR_SAMP / STDDEV_POP / STDDEV_SAMP.

    State is the CENTERED (n, Σx, m2=Σ(x-mean)²) tuple with the pairwise
    Chan/Welford merge (reference: VarianceTuple.apply) — raw Σx² would cancel
    catastrophically for large-magnitude columns (epoch seconds) even in f64.
    The device path still ships raw f32 power sums, but only for columns the
    planner proved small enough (`_power_sum_f32_safe`)."""
    name = "varpop"
    device_outputs = ("sum", "sum2", "count")
    sample = False
    sqrt = False

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        if len(v) == 0:
            return (0, 0.0, 0.0)
        mean = v.mean()
        return (len(v), float(v.sum()), float(((v - mean) ** 2).sum()))

    def state_from_device(self, outs):
        n = int(outs["count"])
        s1 = float(outs.get("sum", 0.0))
        s2 = float(outs.get("sum2", 0.0))
        m2 = max(0.0, s2 - s1 * s1 / n) if n else 0.0
        return (n, s1, m2)

    def merge(self, a, b):
        na, sa, m2a = a
        nb, sb, m2b = b
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        delta = sb / nb - sa / na
        return (n, sa + sb, m2a + m2b + delta * delta * na * nb / n)

    def finalize(self, state):
        n, _s1, m2 = state
        d = n - 1 if self.sample else n
        if n == 0 or d <= 0:
            return None
        var = max(0.0, m2 / d)
        return float(np.sqrt(var)) if self.sqrt else var

    dense_nan_is_null = True  # VAR_SAMP/STDDEV_SAMP of a 1-row group is null

    def dense_values(self, get, counts):
        n = counts.astype(np.float64)
        s1 = get("sum").astype(np.float64)
        s2 = get("sum2").astype(np.float64)
        m2 = np.maximum(0.0, s2 - s1 * s1 / n)
        d = n - 1 if self.sample else n
        var = np.where(d > 0, m2 / np.maximum(d, 1), np.nan)
        return np.sqrt(var) if self.sqrt else var


class VarSampAgg(VarianceAgg):
    name = "varsamp"
    sample = True


class StdDevPopAgg(VarianceAgg):
    name = "stddevpop"
    sqrt = True


class StdDevSampAgg(VarianceAgg):
    name = "stddevsamp"
    sample = True
    sqrt = True


class SkewnessAgg(MomentAgg):
    """SKEWNESS — population skewness from the first three raw moments."""
    name = "skewness"
    device_outputs = ("sum", "sum2", "sum3", "count")

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        return (len(v), float(v.sum()), float((v ** 2).sum()), float((v ** 3).sum()))

    def finalize(self, state):
        n, s1, s2, s3 = state
        if n == 0:
            return None
        mean = s1 / n
        m2 = s2 / n - mean * mean
        if m2 <= 0:
            return 0.0
        m3 = s3 / n - 3 * mean * s2 / n + 2 * mean ** 3
        return float(m3 / m2 ** 1.5)

    def dense_values(self, get, counts):
        n = counts.astype(np.float64)
        s1, s2, s3 = (get(o).astype(np.float64)
                      for o in ("sum", "sum2", "sum3"))
        mean = s1 / n
        m2 = s2 / n - mean * mean
        m3 = s3 / n - 3 * mean * s2 / n + 2 * mean ** 3
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(m2 > 0, m3 / np.maximum(m2, 1e-300) ** 1.5, 0.0)
        return out


class KurtosisAgg(MomentAgg):
    """KURTOSIS — excess kurtosis from the first four raw moments."""
    name = "kurtosis"
    device_outputs = ("sum", "sum2", "sum3", "sum4", "count")

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        return (len(v), float(v.sum()), float((v ** 2).sum()),
                float((v ** 3).sum()), float((v ** 4).sum()))

    def finalize(self, state):
        n, s1, s2, s3, s4 = state
        if n == 0:
            return None
        mean = s1 / n
        m2 = s2 / n - mean * mean
        if m2 <= 0:
            return 0.0
        m4 = (s4 / n - 4 * mean * s3 / n + 6 * mean ** 2 * s2 / n - 3 * mean ** 4)
        return float(m4 / (m2 * m2) - 3.0)

    def dense_values(self, get, counts):
        n = counts.astype(np.float64)
        s1, s2, s3, s4 = (get(o).astype(np.float64)
                          for o in ("sum", "sum2", "sum3", "sum4"))
        mean = s1 / n
        m2 = s2 / n - mean * mean
        m4 = (s4 / n - 4 * mean * s3 / n
              + 6 * mean ** 2 * s2 / n - 3 * mean ** 4)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(m2 > 0, m4 / np.maximum(m2 * m2, 1e-300) - 3.0, 0.0)
        return out


# -- two-argument aggregations ------------------------------------------------
# The executor evaluates ONE argument expression per aggregation, so multi-arg
# functions pack their columns into an [n, k] matrix via the internal __pack
# transform (engine/expr.py); host_state then unpacks columns. Host-path only
# (__pack is not a device function), like the reference's covariance family.

def _pack_args(args) -> Function:
    return Function("__pack", tuple(args))


def _require_numeric_arg(agg: AggFunc, segment, arg: Optional[Expr] = None) -> None:
    """Every column referenced by the argument must be numeric."""
    from ..sql.ast import identifiers_in
    arg = arg if arg is not None else agg.arg
    if arg is None:
        return
    for name in identifiers_in(arg):
        if name == "*":
            continue
        try:
            reader = segment.column(name)
        except KeyError:
            continue
        if not reader.data_type.is_numeric:
            raise QueryValidationError(
                f"{agg.call.name.upper()} requires numeric arguments; "
                f"column {name!r} is {reader.data_type.value}")


class CovarPopAgg(MomentAgg):
    """COVAR_POP / COVAR_SAMP (reference: CovarianceAggregationFunction)."""
    name = "covarpop"
    device_outputs = ()
    sample = False

    def __init__(self, call: Function):
        super().__init__(call)
        if len(call.args) < 2:
            raise QueryValidationError(f"{self.name} needs two arguments")
        self._arg_cols = call.args[:2]
        self.arg = _pack_args(self._arg_cols)

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def validate_args(self, segment) -> None:
        for a in self._arg_cols:
            _require_numeric_arg(self, segment, a)

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        if v.size == 0:
            return (0, 0.0, 0.0, 0.0)
        x, y = v[:, 0], v[:, 1]
        # centered co-moment, merged pairwise like VarianceAgg (stable at any
        # magnitude; raw Σxy cancels catastrophically for epoch-sized columns)
        cxy = float(((x - x.mean()) * (y - y.mean())).sum())
        return (len(x), float(x.sum()), float(y.sum()), cxy)

    def merge(self, a, b):
        na, sxa, sya, ca = a
        nb, sxb, syb, cb = b
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        dx = sxb / nb - sxa / na
        dy = syb / nb - sya / na
        return (n, sxa + sxb, sya + syb, ca + cb + dx * dy * na * nb / n)

    def finalize(self, state):
        n, _sx, _sy, cxy = state
        d = n - 1 if self.sample else n
        if n == 0 or d <= 0:
            return None
        return float(cxy / d)


class CovarSampAgg(CovarPopAgg):
    name = "covarsamp"
    sample = True


class CorrAgg(CovarPopAgg):
    """CORR — Pearson correlation; centered co-moments like CovarPopAgg."""
    name = "corr"

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        if v.size == 0:
            return (0, 0.0, 0.0, 0.0, 0.0, 0.0)
        x, y = v[:, 0], v[:, 1]
        dx, dy = x - x.mean(), y - y.mean()
        return (len(x), float(x.sum()), float(y.sum()), float((dx * dx).sum()),
                float((dy * dy).sum()), float((dx * dy).sum()))

    def merge(self, a, b):
        na, sxa, sya, cxxa, cyya, cxya = a
        nb, sxb, syb, cxxb, cyyb, cxyb = b
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        dx = sxb / nb - sxa / na
        dy = syb / nb - sya / na
        w = na * nb / n
        return (n, sxa + sxb, sya + syb,
                cxxa + cxxb + dx * dx * w,
                cyya + cyyb + dy * dy * w,
                cxya + cxyb + dx * dy * w)

    def finalize(self, state):
        n, _sx, _sy, cxx, cyy, cxy = state
        if n == 0 or cxx <= 0 or cyy <= 0:
            return None
        return float(cxy / np.sqrt(cxx * cyy))


class LastWithTimeAgg(AggFunc):
    """LASTWITHTIME(col, timeCol, 'dataType') — value at the max time
    (reference: LastWithTimeAggregationFunction). State: (time, value)."""
    name = "lastwithtime"
    pick_last = True

    def __init__(self, call: Function):
        super().__init__(call)
        if len(call.args) < 2:
            raise QueryValidationError(f"{self.name} needs (value, time) arguments")
        self._arg_cols = call.args[:2]
        self.arg = _pack_args(self._arg_cols)

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def validate_args(self, segment) -> None:
        # string value columns need a typed state the __pack matrix can't carry;
        # fail at plan time instead of deep in np.asarray(dtype=float64)
        for a in self._arg_cols:
            _require_numeric_arg(self, segment, a)

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        if v.size == 0:
            return None
        x, t = v[:, 0], v[:, 1]
        i = int(np.argmax(t) if self.pick_last else np.argmin(t))
        return (float(t[i]), float(x[i]))

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if self.pick_last:
            return a if a[0] >= b[0] else b
        return a if a[0] <= b[0] else b

    def finalize(self, state):
        return None if state is None else state[1]


class FirstWithTimeAgg(LastWithTimeAgg):
    name = "firstwithtime"
    pick_last = False


class HistogramAgg(AggFunc):
    """HISTOGRAM(col, lower, upper, numBins) — equal-width bin counts
    (reference: HistogramAggregationFunction). State: int64[numBins]; values
    outside [lower, upper) are clamped into the edge bins like the reference."""
    name = "histogram"

    def __init__(self, call: Function):
        super().__init__(call)
        from ..sql.ast import Literal
        if len(call.args) != 4 or not all(isinstance(a, Literal)
                                          for a in call.args[1:]):
            raise QueryValidationError(
                "HISTOGRAM needs (column, lower, upper, numBins) literals")
        self.lower = float(call.args[1].value)
        self.upper = float(call.args[2].value)
        self.bins = int(call.args[3].value)
        if self.bins <= 0 or self.upper <= self.lower:
            raise QueryValidationError("HISTOGRAM needs upper > lower, bins > 0")
        self.arg = call.args[0]

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def host_state(self, values):
        v = np.asarray(values, dtype=np.float64)
        idx = np.floor((v - self.lower) / (self.upper - self.lower) * self.bins)
        idx = np.clip(idx, 0, self.bins - 1).astype(np.int64)
        return np.bincount(idx, minlength=self.bins).astype(np.int64)

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return [int(c) for c in state]

    def empty_result(self):
        return [0] * self.bins


class DistinctSumAgg(DistinctCountAgg):
    """DISTINCTSUM — sum over the distinct value set (reference:
    DistinctSumAggregationFunction); device path reuses the presence vector."""
    name = "distinctsum"

    def validate_args(self, segment) -> None:
        _require_numeric_arg(self, segment)

    def finalize(self, state):
        return float(sum(state)) if state else None

    def empty_result(self):
        return None


class DistinctAvgAgg(DistinctCountAgg):
    name = "distinctavg"

    def finalize(self, state):
        return float(sum(state) / len(state)) if state else None

    def empty_result(self):
        return None


class DistinctSumMVAgg(DistinctSumAgg):
    name = "distinctsummv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return DistinctCountAgg.host_state(self, _mv_flat(values))


class DistinctAvgMVAgg(DistinctAvgAgg):
    name = "distinctavgmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return DistinctCountAgg.host_state(self, _mv_flat(values))


class BoolAndAgg(AggFunc):
    """BOOL_AND — true iff every (boolean 0/1) value is true; rides the device
    min output (reference: BooleanAndAggregationFunction, which likewise
    requires a BOOLEAN argument — enforced in validate_args so the device
    min>=1 decode and the host truthiness path can never disagree)."""
    name = "booland"
    device_outputs = ("min",)

    def validate_args(self, segment) -> None:
        from ..sql.ast import Identifier as _Id
        if isinstance(self.arg, _Id) and self.arg.name != "*":
            try:
                dt = segment.column(self.arg.name).data_type
            except KeyError:
                return
            from ..schema import DataType as _DT
            if dt is not _DT.BOOLEAN:
                raise QueryValidationError(
                    f"{self.call.name.upper()} requires a BOOLEAN column; "
                    f"{self.arg.name!r} is {dt.value}")

    def host_state(self, values):
        return bool(np.all(np.asarray(values) != 0)) if len(values) else None

    def state_from_device(self, outs):
        return bool(outs["min"] >= 1) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a and b

    def finalize(self, state):
        return state


class BoolOrAgg(BoolAndAgg):
    name = "boolor"
    device_outputs = ("max",)

    def host_state(self, values):
        return bool(np.any(np.asarray(values) != 0)) if len(values) else None

    def state_from_device(self, outs):
        return bool(outs["max"] >= 1) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a or b


class SumPrecisionAgg(AggFunc):
    """SUMPRECISION — exact decimal sum, returned as a string (reference:
    SumPrecisionAggregationFunction over BigDecimal)."""
    name = "sumprecision"

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def validate_args(self, segment) -> None:
        _require_numeric_arg(self, segment)

    def host_state(self, values):
        from decimal import Decimal
        if not len(values):
            return None  # empty -> null, like SUM (and like empty_result)
        total = Decimal(0)
        for v in np.asarray(values).tolist():
            total += Decimal(str(v))
        return total

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a + b

    def finalize(self, state):
        return str(state.normalize()) if state is not None else None


class PercentileRawTDigestAgg(PercentileTDigestAgg):
    """PERCENTILERAWTDIGEST — serialized t-digest (hex) for client-side merging.

    Host path ONLY: the device counts path builds one centroid per distinct
    value, so the serialized bytes would differ between execution paths for
    identical data — clients that store/diff raw digests need stability."""
    name = "percentilerawtdigest"
    pct_base = "percentilerawtdigest"
    device_outputs = ()
    wants_id_counts = False

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def finalize(self, state):
        return state.to_bytes().hex()


# -- multi-value aggregations (reference: CountMVAggregationFunction etc.) ----
# `values` on the host path is an object array of per-row numpy arrays (the MV
# cells); every *MV function flattens rows to their values first. Host-only:
# the device kernel has no ragged-row reduction (the planner's AggContext marks
# MV args non-dict / non-numeric, and device_ok returns False anyway).

def _mv_flat(values) -> np.ndarray:
    rows = [np.asarray(v) for v in values]
    if not rows:
        return np.empty(0, dtype=np.float64)
    return np.concatenate(rows)


class CountMVAgg(CountAgg):
    name = "countmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return int(sum(len(v) for v in values))


class SumMVAgg(SumAgg):
    name = "summv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class MinMVAgg(MinAgg):
    name = "minmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class MaxMVAgg(MaxAgg):
    name = "maxmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class AvgMVAgg(AvgAgg):
    name = "avgmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class DistinctCountMVAgg(DistinctCountAgg):
    name = "distinctcountmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class DistinctCountRawHLLAgg(DistinctCountHLLAgg):
    """DISTINCTCOUNTRAWHLL — serialized registers (hex) for client-side merging
    (reference: DistinctCountRawHLLAggregationFunction). Register-max merge of
    two hex payloads of equal p reproduces the server-side union."""
    name = "distinctcountrawhll"

    def finalize(self, state):
        return self._normalize(state).astype(np.int8).tobytes().hex()

    def empty_result(self):
        return np.zeros(1 << self.p, dtype=np.int8).tobytes().hex()


class DistinctCountRawHLLMVAgg(DistinctCountRawHLLAgg):
    name = "distinctcountrawhllmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class PercentileRawEstAgg(PercentileEstAgg):
    """PERCENTILERAWEST — serialized digest (hex); the reference serializes a
    QuantileDigest, here the same t-digest state as PERCENTILERAWTDIGEST.
    Host path only, like the other RAW variant: serialized bytes must not
    depend on the execution path."""
    name = "percentilerawest"
    pct_base = "percentilerawest"
    device_outputs = ()
    wants_id_counts = False

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def finalize(self, state):
        return state.to_bytes().hex()


class PercentileSmartTDigestAgg(PercentileTDigestAgg):
    """PERCENTILESMARTTDIGEST — exact value buffer until `threshold` values,
    then degrade to a t-digest (reference: PercentileSmartTDigestAggregationFunction,
    threshold via a 'threshold=N' third argument)."""
    name = "percentilesmarttdigest"
    pct_base = "percentilesmarttdigest"
    DEFAULT_THRESHOLD = 100_000
    # NOT the inherited device counts path: smart's state is ("exact"|
    # "digest", v) tuples and its exact-below-threshold contract needs raw
    # values, which the per-id count vector cannot restore
    device_outputs = ()
    wants_id_counts = False

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def __init__(self, call: Function):
        super().__init__(call)
        self.threshold = self.DEFAULT_THRESHOLD
        from ..sql.ast import Literal
        # args[1:]: in the digit-suffix form (PERCENTILESMARTTDIGEST90(x, ...))
        # the threshold literal is args[1]; a pct literal never contains
        # "threshold=" so the guard excludes it either way
        for a in call.args[1:]:
            if isinstance(a, Literal) and "threshold=" in str(a.value):
                self.threshold = int(str(a.value).split("=", 1)[1])

    def _digest(self, values: np.ndarray):
        from .sketches import TDigest
        return TDigest.from_values(values, self.COMPRESSION)

    def host_state(self, values):
        arr = np.asarray(values, dtype=np.float64)
        if len(arr) > self.threshold:
            return ("digest", self._digest(arr))
        return ("exact", arr)

    def merge(self, a, b):
        ka, va = a
        kb, vb = b
        if ka == "exact" and kb == "exact":
            u = np.concatenate([va, vb])
            if len(u) > self.threshold:
                return ("digest", self._digest(u))
            return ("exact", u)
        da = va if ka == "digest" else self._digest(va)
        db = vb if kb == "digest" else self._digest(vb)
        return ("digest", da.merge(db))

    def finalize(self, state):
        kind, v = state
        if kind == "exact":
            return None if len(v) == 0 else float(np.percentile(v, self.pct))
        q = v.quantile(self.pct / 100.0)
        return None if q is None else float(q)


class MinMaxRangeMVAgg(MinMaxRangeAgg):
    name = "minmaxrangemv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


def _strip_mv(call: Function) -> Function:
    return Function(call.name[:-2], call.args, call.distinct)


class PercentileMVAgg(PercentileAgg):
    """PERCENTILEMV / PERCENTILE<NN>MV — exact percentile over flattened
    multi-value cells (reference: PercentileMVAggregationFunction)."""
    name = "percentilemv"

    def __init__(self, call: Function):
        super().__init__(_strip_mv(call))

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class PercentileEstMVAgg(PercentileEstAgg):
    name = "percentileestmv"

    def __init__(self, call: Function):
        super().__init__(_strip_mv(call))

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class PercentileTDigestMVAgg(PercentileTDigestAgg):
    name = "percentiletdigestmv"

    def __init__(self, call: Function):
        super().__init__(_strip_mv(call))

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class PercentileRawEstMVAgg(PercentileRawEstAgg):
    name = "percentilerawestmv"

    def __init__(self, call: Function):
        super().__init__(_strip_mv(call))

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class PercentileRawTDigestMVAgg(PercentileRawTDigestAgg):
    name = "percentilerawtdigestmv"

    def __init__(self, call: Function):
        super().__init__(_strip_mv(call))

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class DistinctCountHLLMVAgg(DistinctCountHLLAgg):
    """Reference: DistinctCountHLLMVAggregationFunction."""
    name = "distinctcounthllmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class SegmentPartitionedDistinctCountAgg(AggFunc):
    """Exact distinct count under the promise that the column is partitioned by
    segment (each value appears in only one segment): per-segment exact unique
    count, merged by SUM — O(1) merge state instead of shipping value sets
    (reference: SegmentPartitionedDistinctCountAggregationFunction; returns
    overcounts if the promise is violated, same as the reference)."""
    name = "segmentpartitioneddistinctcount"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        arr = np.asarray(values)
        if arr.dtype == object:
            return len({v for v in arr if v is not None})
        return len(np.unique(arr))

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return int(state)

    # NOTE: no dense_values here — `counts` is the matched-ROW count, not a
    # distinct count; inheriting CountAgg's shape would silently miscount if
    # this agg ever grows a device plan (today device_ok is False).

    def empty_result(self):
        return 0


class DistinctCountSmartHLLAgg(AggFunc):
    """Exact distinct set until `threshold` distinct values, then degrade to
    HLL (reference: DistinctCountSmartHLLAggregationFunction). Second literal
    argument overrides the threshold."""
    name = "distinctcountsmarthll"
    DEFAULT_THRESHOLD = 100_000

    def __init__(self, call: Function):
        super().__init__(call)
        self.threshold = self.DEFAULT_THRESHOLD
        if len(call.args) >= 2:
            from ..sql.ast import Literal
            if isinstance(call.args[1], Literal):
                self.threshold = int(call.args[1].value)
        self._hll = DistinctCountHLLAgg(Function("distinctcounthll",
                                                 call.args[:1]))

    def device_ok(self, ctx):
        return False

    def _to_hll(self, values_set):
        return self._hll.host_state(np.asarray(list(values_set), dtype=object))

    def host_state(self, values):
        s = {v for v in np.asarray(values, dtype=object).reshape(-1)
             if v is not None}
        if len(s) > self.threshold:
            return ("hll", self._to_hll(s))
        return ("set", s)

    def merge(self, a, b):
        ka, va = a
        kb, vb = b
        if ka == "set" and kb == "set":
            u = va | vb
            if len(u) > self.threshold:
                return ("hll", self._to_hll(u))
            return ("set", u)
        ha = va if ka == "hll" else self._to_hll(va)
        hb = vb if kb == "hll" else self._to_hll(vb)
        return ("hll", np.maximum(ha, hb))

    def finalize(self, state):
        kind, v = state
        return len(v) if kind == "set" else self._hll.finalize(v)

    def empty_result(self):
        return 0


class StUnionAgg(AggFunc):
    """STUNION — union of point geometries into one MULTIPOINT WKT (reference:
    StUnionAggregationFunction unions theta-sketch-free geometries; our geo
    model is lng/lat points — see engine/geo_fns.py — so the union is the
    distinct point set, serialized as WKT)."""
    name = "stunion"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        from ..engine.geo_fns import _as_complex
        pts = _as_complex(values)
        return {(float(p.real), float(p.imag))
                for p in np.atleast_1d(np.asarray(pts, dtype=complex))}

    def merge(self, a, b):
        return a | b

    def finalize(self, state):
        if not state:
            return "MULTIPOINT EMPTY"
        # 12 significant digits: ~1e-7 deg (cm-scale) lng/lat stays distinct,
        # %g's 6-digit default would collapse nearby real-world points
        body = ", ".join(f"{x:.12g} {y:.12g}" for x, y in sorted(state))
        return f"MULTIPOINT ({body})"

    def empty_result(self):
        return "MULTIPOINT EMPTY"


class IdSetAgg(AggFunc):
    """IDSET(col): build a serialized value-set usable as an `IN_ID_SET` filter
    literal in a later query (reference: IdSetAggregationFunction; the broker's
    IN_SUBQUERY rewrite consumes this). State is an `IdSet`; finalize emits the
    base64 string."""

    name = "idset"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        from .idset import IdSet
        return IdSet.from_values(values)

    def merge(self, a, b):
        return a.union(b)

    def finalize(self, state):
        return state.serialize()

    def empty_result(self):
        from .idset import IdSet
        return IdSet.empty().serialize()


class IdSetMVAgg(IdSetAgg):
    name = "idsetmv"

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


_REGISTRY = {
    "idset": IdSetAgg,
    "idsetmv": IdSetMVAgg,
    # (percentile*mv names dispatch through make_agg's MV-percentile branch,
    # which also handles the digit-suffix forms — not via this registry)
    "distinctcounthllmv": DistinctCountHLLMVAgg,
    "stunion": StUnionAgg,
    "percentilesmarttdigest": PercentileSmartTDigestAgg,
    "percentilerawest": PercentileRawEstAgg,
    "distinctcountrawhll": DistinctCountRawHLLAgg,
    "distinctcountrawhllmv": DistinctCountRawHLLMVAgg,
    "fasthll": DistinctCountHLLAgg,  # legacy alias (reference: FASTHLL)
    "distinctcountbitmapmv": DistinctCountMVAgg,  # exact, same state
    "minmaxrangemv": MinMaxRangeMVAgg,
    "segmentpartitioneddistinctcount": SegmentPartitionedDistinctCountAgg,
    "distinctcountsmarthll": DistinctCountSmartHLLAgg,
    "count": CountAgg,
    "countmv": CountMVAgg,
    "summv": SumMVAgg,
    "minmv": MinMVAgg,
    "maxmv": MaxMVAgg,
    "avgmv": AvgMVAgg,
    "distinctcountmv": DistinctCountMVAgg,
    "sum": SumAgg,
    "min": MinAgg,
    "max": MaxAgg,
    "avg": AvgAgg,
    "minmaxrange": MinMaxRangeAgg,
    "distinctcount": DistinctCountAgg,
    "distinctcountbitmap": DistinctCountAgg,  # exact; same state here
    "distinctcounthll": DistinctCountHLLAgg,
    "mode": ModeAgg,
    "percentile": PercentileAgg,
    "percentileest": PercentileEstAgg,
    "percentiletdigest": PercentileTDigestAgg,
    "percentilerawtdigest": PercentileRawTDigestAgg,
    "distinctcountthetasketch": DistinctCountThetaAgg,
    "distinctcountrawthetasketch": DistinctCountRawThetaAgg,
    # moments (both reference camelCase-derived and SQL-standard spellings)
    "varpop": VarianceAgg, "var_pop": VarianceAgg,
    "varsamp": VarSampAgg, "var_samp": VarSampAgg,
    "stddevpop": StdDevPopAgg, "stddev_pop": StdDevPopAgg,
    "stddevsamp": StdDevSampAgg, "stddev_samp": StdDevSampAgg,
    "skewness": SkewnessAgg,
    "kurtosis": KurtosisAgg,
    "covarpop": CovarPopAgg, "covar_pop": CovarPopAgg,
    "covarsamp": CovarSampAgg, "covar_samp": CovarSampAgg,
    "corr": CorrAgg,
    "firstwithtime": FirstWithTimeAgg,
    "lastwithtime": LastWithTimeAgg,
    "histogram": HistogramAgg,
    "distinctsum": DistinctSumAgg,
    "distinctavg": DistinctAvgAgg,
    "distinctsummv": DistinctSumMVAgg,
    "distinctavgmv": DistinctAvgMVAgg,
    "booland": BoolAndAgg, "bool_and": BoolAndAgg,
    "boolor": BoolOrAgg, "bool_or": BoolOrAgg,
    "sumprecision": SumPrecisionAgg,
}


def make_agg(call: Function) -> AggFunc:
    name = call.name
    if call.name == "count" and call.distinct:
        # COUNT(DISTINCT x) -> DISTINCTCOUNT(x), reference does the same rewrite
        return DistinctCountAgg(Function("distinctcount", call.args))
    if name.endswith("mv") and name.startswith("percentile"):
        stem = name[:-2]
        for prefix, cls in (("percentilerawtdigest", PercentileRawTDigestMVAgg),
                            ("percentilerawest", PercentileRawEstMVAgg),
                            ("percentiletdigest", PercentileTDigestMVAgg),
                            ("percentileest", PercentileEstMVAgg),
                            ("percentile", PercentileMVAgg)):
            if stem == prefix or (stem.startswith(prefix)
                                  and stem[len(prefix):].isdigit()):
                return cls(call)
    for prefix, cls in (("percentilesmarttdigest", PercentileSmartTDigestAgg),
                        ("percentilerawtdigest", PercentileRawTDigestAgg),
                        ("percentilerawest", PercentileRawEstAgg),
                        ("percentiletdigest", PercentileTDigestAgg),
                        ("percentileest", PercentileEstAgg),
                        ("percentile", PercentileAgg)):
        if name.startswith(prefix) and name[len(prefix):].isdigit():
            return cls(call)
    cls = _REGISTRY.get(name)
    if cls is None:
        raise QueryValidationError(f"unsupported aggregation function {name!r}")
    return cls(call)
