"""Aggregation functions: device partial computation spec + host merge/finalize.

Analog of the reference's `AggregationFunction` interface
(`pinot-core/.../query/aggregation/function/`, 58 classes): each function defines
(1) which fused-kernel outputs it needs on device (`device_outputs`),
(2) how per-segment partial states merge across segments/servers (`merge` — the reference's
    `merge(intermediate, intermediate)`), and
(3) how a final value is extracted (`finalize` — `extractFinalResult`).

Functions whose exact semantics need raw values (percentile, mode, exact distinct-count on
expressions) run on the host path; the planner asks `device_ok()`.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..sql.ast import Expr, Function, Identifier
from .context import QueryValidationError


@dataclass
class AggContext:
    """Static facts the planner knows when choosing the device/host path."""
    group_by: bool
    arg_is_dict_column: bool  # argument is a plain dictionary-encoded column
    arg_is_numeric: bool


class AggFunc:
    name: str = ""
    device_outputs: Tuple[str, ...] = ()  # subset of {count,sum,min,max,distinct}

    def __init__(self, call: Function):
        self.call = call
        self.arg: Optional[Expr] = call.args[0] if call.args else None

    # -- capability --------------------------------------------------------
    def device_ok(self, ctx: AggContext) -> bool:
        return True

    # -- host path ---------------------------------------------------------
    def host_state(self, values: np.ndarray) -> Any:
        """Build a partial state from the filtered argument values of one segment."""
        raise NotImplementedError

    def state_from_device(self, outs: Dict[str, float]) -> Any:
        """Build the same state from the fused kernel's per-key outputs."""
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, state: Any) -> Any:
        raise NotImplementedError

    def empty_result(self) -> Any:
        """Result over zero rows (no group-by), mirroring reference defaults."""
        return None


class CountAgg(AggFunc):
    name = "count"
    device_outputs = ("count",)

    def host_state(self, values):
        return int(len(values))

    def state_from_device(self, outs):
        return int(outs["count"])

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return int(state)

    def empty_result(self):
        return 0


class SumAgg(AggFunc):
    name = "sum"
    device_outputs = ("sum",)

    def host_state(self, values):
        return float(np.sum(values)) if len(values) else None

    def state_from_device(self, outs):
        return float(outs["sum"]) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return a + b

    def finalize(self, state):
        return None if state is None else float(state)


class MinAgg(AggFunc):
    name = "min"
    device_outputs = ("min",)

    def host_state(self, values):
        return float(np.min(values)) if len(values) else None

    def state_from_device(self, outs):
        return float(outs["min"]) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return min(a, b)

    def finalize(self, state):
        return None if state is None else float(state)


class MaxAgg(MinAgg):
    name = "max"
    device_outputs = ("max",)

    def host_state(self, values):
        return float(np.max(values)) if len(values) else None

    def state_from_device(self, outs):
        return float(outs["max"]) if outs["count"] > 0 else None

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return max(a, b)


class AvgAgg(AggFunc):
    name = "avg"
    device_outputs = ("sum", "count")

    def host_state(self, values):
        return (float(np.sum(values)), len(values))

    def state_from_device(self, outs):
        return (float(outs["sum"]), int(outs["count"]))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, state):
        s, c = state
        return None if c == 0 else s / c


class MinMaxRangeAgg(AggFunc):
    name = "minmaxrange"
    device_outputs = ("min", "max")

    def host_state(self, values):
        if not len(values):
            return None
        return (float(np.min(values)), float(np.max(values)))

    def state_from_device(self, outs):
        if outs["count"] == 0:
            return None
        return (float(outs["min"]), float(outs["max"]))

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return (min(a[0], b[0]), max(a[1], b[1]))

    def finalize(self, state):
        return None if state is None else state[1] - state[0]


class DistinctCountAgg(AggFunc):
    """Exact distinct count. Device path: per-dict-id presence vector (no group-by);
    states merge as value sets across segments since dictionaries differ per segment."""
    name = "distinctcount"
    device_outputs = ("distinct",)

    def device_ok(self, ctx: AggContext) -> bool:
        return ctx.arg_is_dict_column and not ctx.group_by

    def host_state(self, values):
        return set(np.unique(values).tolist())

    def merge(self, a, b):
        return a | b

    def finalize(self, state):
        return len(state)

    def empty_result(self):
        return 0


HLL_DEFAULT_P = 12  # 4096 registers, ~1.6% relative error


def hll_hash(value) -> int:
    """64-bit stable hash for HLL bucketing."""
    import hashlib
    if isinstance(value, bytes):
        data = value
    elif isinstance(value, (float, np.floating)) and float(value).is_integer():
        data = str(int(value)).encode()
    else:
        data = str(value).encode()
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


def hll_bucket_rank(value, p: int) -> Tuple[int, int]:
    h = hll_hash(value)
    bucket = h >> (64 - p)
    # remaining bits shifted to the top of the word; low p bits are zero-filled, so
    # leading zeros of the 64-bit word count within the (64-p)-bit window
    w = (h << p) & ((1 << 64) - 1)
    rank = (64 - p) + 1 if w == 0 else min(64 - w.bit_length() + 1, (64 - p) + 1)
    return bucket, rank


def hll_estimate(registers: np.ndarray) -> float:
    """Standard HyperLogLog estimator with small-range correction."""
    m = len(registers)
    alpha = 0.7213 / (1 + 1.079 / m) if m >= 128 else {16: 0.673, 32: 0.697,
                                                       64: 0.709}.get(m, 0.7213)
    est = alpha * m * m / np.sum(np.exp2(-registers.astype(np.float64)))
    zeros = int(np.sum(registers == 0))
    if est <= 2.5 * m and zeros > 0:
        est = m * np.log(m / zeros)
    return float(est)


class DistinctCountHLLAgg(AggFunc):
    """Approximate distinct count via HyperLogLog (reference:
    DistinctCountHLLAggregationFunction, default log2m in
    `CommonConstants.Helix.DEFAULT_HYPERLOGLOG_LOG2M`).

    TPU path (dict-column arg, no group-by): per-dict-id (bucket, rank) LUTs are
    precomputed host-side from the dictionary; on device the registers are one
    `segment_max(rank_lut[ids], bucket_lut[ids])` — the sketch update is a gather+scatter
    with no hashing on device. States merge by elementwise register max.
    """

    name = "distinctcounthll"
    device_outputs = ("hll",)

    def __init__(self, call: Function):
        super().__init__(call)
        self.p = HLL_DEFAULT_P
        if len(call.args) >= 2:
            from ..sql.ast import Literal
            if isinstance(call.args[1], Literal):
                self.p = int(call.args[1].value)

    def device_ok(self, ctx: AggContext) -> bool:
        return ctx.arg_is_dict_column and not ctx.group_by

    def host_state(self, values) -> np.ndarray:
        regs = np.zeros(1 << self.p, dtype=np.int8)
        for v in np.unique(np.asarray(values, dtype=object)):
            b, r = hll_bucket_rank(v, self.p)
            regs[b] = max(regs[b], r)
        return regs

    def state_from_device(self, outs) -> np.ndarray:
        return np.asarray(outs["hll"], dtype=np.int8)

    def merge(self, a, b):
        return np.maximum(a, b)

    def finalize(self, state) -> int:
        return int(round(hll_estimate(state)))

    def empty_result(self):
        return 0


class PercentileAgg(AggFunc):
    """Exact percentile — keeps filtered values per state (host-path only).
    `percentile(col, p)` or legacy `percentileNN(col)`."""
    name = "percentile"

    def __init__(self, call: Function):
        super().__init__(call)
        self.pct = _parse_percentile(call, "percentile")

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def host_state(self, values):
        return np.asarray(values, dtype=np.float64)

    def merge(self, a, b):
        return np.concatenate([a, b])

    def finalize(self, state):
        return None if len(state) == 0 else float(np.percentile(state, self.pct))


def _parse_percentile(call: Function, base: str) -> float:
    """`<base>NN(col)` suffix form or `<base>(col, NN)` argument form."""
    if call.name.startswith(base) and call.name[len(base):].isdigit():
        return float(call.name[len(base):])
    if len(call.args) >= 2:
        from ..sql.ast import Literal
        assert isinstance(call.args[1], Literal)
        return float(call.args[1].value)
    raise QueryValidationError(f"{call.name} needs a percentile argument")


class DistinctCountThetaAgg(AggFunc):
    """DISTINCTCOUNTTHETASKETCH — KMV theta sketch state (`sketches.ThetaSketch`).

    Reference: DistinctCountThetaSketchAggregationFunction (DataSketches theta). On the
    device path over a dict column the exact present-id set comes back from the kernel
    (same output as DISTINCTCOUNT); the sketch is built from the surviving dictionary
    values host-side — cardinality-sized work, not row-sized.
    """
    name = "distinctcountthetasketch"
    device_outputs = ("distinct",)

    def __init__(self, call: Function):
        super().__init__(call)
        from ..sql.ast import Literal
        self.k = 4096
        if len(call.args) >= 2 and isinstance(call.args[1], Literal):
            # reference accepts 'nominalEntries=NNNN' parameter strings
            s = str(call.args[1].value)
            if "=" in s:
                self.k = int(s.split("=", 1)[1])
            elif s.isdigit():
                self.k = int(s)

    def device_ok(self, ctx: AggContext) -> bool:
        return not ctx.group_by and ctx.arg_is_dict_column

    @staticmethod
    def _canonical(values) -> np.ndarray:
        """One hash domain per logical type across segments AND device/host paths (the
        device path yields python ints where the host path sees the column dtype).
        Integers stay integral — float64 would collapse distinct int64s above 2^53."""
        arr = np.asarray(list(values) if isinstance(values, set) else values)
        if arr.dtype.kind in "iub":
            return arr.astype(np.int64)
        if arr.dtype.kind == "f":
            return arr.astype(np.float64)
        return arr

    def _normalize(self, state):
        from .sketches import ThetaSketch
        if isinstance(state, set):  # device path returns the exact value set
            return ThetaSketch.from_values(self._canonical(state), self.k)
        return state

    def host_state(self, values):
        from .sketches import ThetaSketch
        return ThetaSketch.from_values(self._canonical(values), self.k)

    def merge(self, a, b):
        return self._normalize(a).union(self._normalize(b))

    def finalize(self, state):
        return int(round(self._normalize(state).estimate()))

    def empty_result(self):
        return 0


class DistinctCountRawThetaAgg(DistinctCountThetaAgg):
    """DISTINCTCOUNTRAWTHETASKETCH — returns the serialized sketch (hex) instead of the
    estimate, for client-side set operations (reference: ...RawThetaSketchAggregationFunction)."""
    name = "distinctcountrawthetasketch"

    def finalize(self, state):
        return self._normalize(state).to_bytes().hex()

    def empty_result(self):
        from .sketches import ThetaSketch
        return ThetaSketch(self.k).to_bytes().hex()


class PercentileTDigestAgg(AggFunc):
    """PERCENTILETDIGEST / PERCENTILETDIGESTNN — merging t-digest state.

    Reference: PercentileTDigestAggregationFunction (com.tdunning TDigest). Bounded-size
    mergeable state — unlike PercentileAgg's exact value buffer, this flows through
    multi-host reduce without shipping raw rows.
    """
    name = "percentiletdigest"
    COMPRESSION = 100.0

    def __init__(self, call: Function):
        super().__init__(call)
        self.pct = _parse_percentile(call, self.name)

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def host_state(self, values):
        from .sketches import TDigest
        return TDigest.from_values(values, self.COMPRESSION)

    def merge(self, a, b):
        return a.merge(b)

    def finalize(self, state):
        q = state.quantile(self.pct / 100.0)
        return None if q is None else float(q)


class PercentileEstAgg(PercentileTDigestAgg):
    """PERCENTILEEST — approximate long-valued percentile (reference uses QuantileDigest;
    here the same t-digest state with integer extraction)."""
    name = "percentileest"

    def finalize(self, state):
        q = state.quantile(self.pct / 100.0)
        return None if q is None else int(round(q))


class ModeAgg(AggFunc):
    name = "mode"

    def device_ok(self, ctx: AggContext) -> bool:
        return False

    def host_state(self, values):
        return Counter(values.tolist())

    def merge(self, a, b):
        a.update(b)
        return a

    def finalize(self, state):
        if not state:
            return None
        # ties broken by smallest value, matching reference MODE default
        best = max(state.items(), key=lambda kv: (kv[1], -kv[0] if isinstance(kv[0], (int, float)) else 0))
        return float(best[0]) if isinstance(best[0], (int, float)) else best[0]


# -- multi-value aggregations (reference: CountMVAggregationFunction etc.) ----
# `values` on the host path is an object array of per-row numpy arrays (the MV
# cells); every *MV function flattens rows to their values first. Host-only:
# the device kernel has no ragged-row reduction (the planner's AggContext marks
# MV args non-dict / non-numeric, and device_ok returns False anyway).

def _mv_flat(values) -> np.ndarray:
    rows = [np.asarray(v) for v in values]
    if not rows:
        return np.empty(0, dtype=np.float64)
    return np.concatenate(rows)


class CountMVAgg(CountAgg):
    name = "countmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return int(sum(len(v) for v in values))


class SumMVAgg(SumAgg):
    name = "summv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class MinMVAgg(MinAgg):
    name = "minmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class MaxMVAgg(MaxAgg):
    name = "maxmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class AvgMVAgg(AvgAgg):
    name = "avgmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


class DistinctCountMVAgg(DistinctCountAgg):
    name = "distinctcountmv"

    def device_ok(self, ctx):
        return False

    def host_state(self, values):
        return super().host_state(_mv_flat(values))


_REGISTRY = {
    "count": CountAgg,
    "countmv": CountMVAgg,
    "summv": SumMVAgg,
    "minmv": MinMVAgg,
    "maxmv": MaxMVAgg,
    "avgmv": AvgMVAgg,
    "distinctcountmv": DistinctCountMVAgg,
    "sum": SumAgg,
    "min": MinAgg,
    "max": MaxAgg,
    "avg": AvgAgg,
    "minmaxrange": MinMaxRangeAgg,
    "distinctcount": DistinctCountAgg,
    "distinctcountbitmap": DistinctCountAgg,  # exact; same state here
    "distinctcounthll": DistinctCountHLLAgg,
    "mode": ModeAgg,
    "percentile": PercentileAgg,
    "percentileest": PercentileEstAgg,
    "percentiletdigest": PercentileTDigestAgg,
    "distinctcountthetasketch": DistinctCountThetaAgg,
    "distinctcountrawthetasketch": DistinctCountRawThetaAgg,
}


def make_agg(call: Function) -> AggFunc:
    name = call.name
    if call.name == "count" and call.distinct:
        # COUNT(DISTINCT x) -> DISTINCTCOUNT(x), reference does the same rewrite
        return DistinctCountAgg(Function("distinctcount", call.args))
    for prefix, cls in (("percentiletdigest", PercentileTDigestAgg),
                        ("percentileest", PercentileEstAgg),
                        ("percentile", PercentileAgg)):
        if name.startswith(prefix) and name[len(prefix):].isdigit():
            return cls(call)
    cls = _REGISTRY.get(name)
    if cls is None:
        raise QueryValidationError(f"unsupported aggregation function {name!r}")
    return cls(call)
