"""Mergeable sketch states: theta sketch (approx distinct) and t-digest (approx quantiles).

Analog of the reference's DataSketches-backed aggregations
(`pinot-core/.../aggregation/function/DistinctCountThetaSketchAggregationFunction.java`,
`PercentileTDigestAggregationFunction.java`, `PercentileEstAggregationFunction.java`;
enum entries `pinot-segment-spi/.../AggregationFunctionType.java:31-80`). Implemented from
the published algorithms (KMV theta sketch; Dunning's merging t-digest) — numpy-vectorized,
with states that merge associatively so they flow through segment combine, mesh psum-style
reduce, and broker reduce unchanged.
"""

from __future__ import annotations

import struct
from typing import Any, Iterable, List, Optional, Tuple

import numpy as np

_MAX64 = np.float64(2 ** 64)


def hash64(values: np.ndarray) -> np.ndarray:
    """Vectorized 64-bit mix hash (splitmix64 finalizer) over arbitrary values.

    Strings/bytes hash via a per-element FNV-1a pass (python loop — the scan path only
    hashes *dictionary values*, cardinality-sized, not row-sized)."""
    arr = np.asarray(values)
    if arr.dtype.kind in "iuf b":
        x = np.ascontiguousarray(arr).view(np.uint8).reshape(len(arr), -1)
        h = np.zeros(len(arr), dtype=np.uint64)
        FNV_PRIME = np.uint64(0x100000001B3)
        for byte_col in x.T:
            h = (h ^ byte_col.astype(np.uint64)) * FNV_PRIME
    else:
        h = np.fromiter((_fnv1a(v) for v in arr), dtype=np.uint64, count=len(arr))
    # splitmix64 finalizer for avalanche
    h = h.copy()
    h ^= h >> np.uint64(30)
    h *= np.uint64(0xBF58476D1CE4E5B9)
    h ^= h >> np.uint64(27)
    h *= np.uint64(0x94D049BB133111EB)
    h ^= h >> np.uint64(31)
    return h


def _fnv1a(v: Any) -> int:
    data = v if isinstance(v, bytes) else str(v).encode("utf-8")
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class ThetaSketch:
    """KMV theta sketch: keep the k smallest 64-bit hashes; theta = sampling threshold.

    Union (merge) is the only set operation the aggregation path needs; intersection /
    a-not-b are provided for the reference's SET_UNION/SET_INTERSECT/SET_DIFF post-ops
    (DistinctCountThetaSketchAggregationFunction parameters)."""

    __slots__ = ("k", "theta", "hashes")

    def __init__(self, k: int = 4096, theta: float = 1.0,
                 hashes: Optional[np.ndarray] = None):
        self.k = k
        self.theta = theta
        self.hashes = hashes if hashes is not None else np.empty(0, dtype=np.uint64)

    @classmethod
    def from_values(cls, values: np.ndarray, k: int = 4096) -> "ThetaSketch":
        if len(values) == 0:
            return cls(k)
        h = np.unique(hash64(values))
        sk = cls(k)
        sk._absorb(h)
        return sk

    def _absorb(self, sorted_hashes: np.ndarray) -> None:
        cutoff = np.uint64(self.theta * float(_MAX64)) if self.theta < 1.0 else None
        if cutoff is not None:
            sorted_hashes = sorted_hashes[sorted_hashes < cutoff]
        merged = np.union1d(self.hashes, sorted_hashes)
        if len(merged) > self.k:
            # retain the k smallest; theta becomes the (k+1)-th (all retained are < theta)
            self.theta = float(merged[self.k]) / float(_MAX64)
            merged = merged[:self.k]
        self.hashes = merged

    def union(self, other: "ThetaSketch") -> "ThetaSketch":
        out = ThetaSketch(min(self.k, other.k), min(self.theta, other.theta))
        cutoff = np.uint64(out.theta * float(_MAX64)) if out.theta < 1.0 else None
        merged = np.union1d(self.hashes, other.hashes)
        if cutoff is not None:
            merged = merged[merged < cutoff]
        out.hashes = merged
        if len(merged) > out.k:
            out.theta = float(merged[out.k]) / float(_MAX64)
            out.hashes = merged[:out.k]
        return out

    def intersect(self, other: "ThetaSketch") -> "ThetaSketch":
        theta = min(self.theta, other.theta)
        cutoff = np.uint64(theta * float(_MAX64)) if theta < 1.0 else None
        common = np.intersect1d(self.hashes, other.hashes)
        if cutoff is not None:
            common = common[common < cutoff]
        return ThetaSketch(min(self.k, other.k), theta, common)

    def a_not_b(self, other: "ThetaSketch") -> "ThetaSketch":
        theta = min(self.theta, other.theta)
        cutoff = np.uint64(theta * float(_MAX64)) if theta < 1.0 else None
        diff = np.setdiff1d(self.hashes, other.hashes)
        if cutoff is not None:
            diff = diff[diff < cutoff]
        return ThetaSketch(min(self.k, other.k), theta, diff)

    def estimate(self) -> float:
        if self.theta >= 1.0:
            return float(len(self.hashes))
        return len(self.hashes) / self.theta

    # -- serialization (compact: k, theta, hashes) --------------------------
    def to_bytes(self) -> bytes:
        return struct.pack("<id", self.k, self.theta) + self.hashes.tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ThetaSketch":
        k, theta = struct.unpack_from("<id", data)
        hashes = np.frombuffer(data[12:], dtype=np.uint64).copy()
        return cls(k, theta, hashes)


class TDigest:
    """Merging t-digest (Dunning): centroids sized by the k1 scale function, accurate at
    the tails. States merge associatively: concatenate centroids + re-compress."""

    __slots__ = ("compression", "means", "weights")

    def __init__(self, compression: float = 100.0,
                 means: Optional[np.ndarray] = None,
                 weights: Optional[np.ndarray] = None):
        self.compression = compression
        self.means = means if means is not None else np.empty(0, dtype=np.float64)
        self.weights = weights if weights is not None else np.empty(0, dtype=np.float64)

    @classmethod
    def from_values(cls, values: np.ndarray, compression: float = 100.0) -> "TDigest":
        td = cls(compression)
        v = np.asarray(values, dtype=np.float64)
        v = v[~np.isnan(v)]
        if len(v):
            td.means = np.sort(v)
            td.weights = np.ones(len(v), dtype=np.float64)
            td._compress()
        return td

    @classmethod
    def from_weighted(cls, values: np.ndarray, weights: np.ndarray,
                      compression: float = 100.0) -> "TDigest":
        """Digest from (value, multiplicity) pairs — the device path's shape:
        a dictionary's sorted values with per-id masked row counts, so the
        build cost is O(cardinality), not O(rows)."""
        td = cls(compression)
        v = np.asarray(values, dtype=np.float64)
        w = np.asarray(weights, dtype=np.float64)
        keep = (w > 0) & ~np.isnan(v)
        v, w = v[keep], w[keep]
        if len(v):
            order = np.argsort(v, kind="stable")
            td.means = v[order]
            td.weights = w[order]
            td._compress()
        return td

    def merge(self, other: "TDigest") -> "TDigest":
        out = TDigest(max(self.compression, other.compression))
        out.means = np.concatenate([self.means, other.means])
        out.weights = np.concatenate([self.weights, other.weights])
        order = np.argsort(out.means, kind="stable")
        out.means = out.means[order]
        out.weights = out.weights[order]
        out._compress()
        return out

    def _compress(self) -> None:
        n = len(self.means)
        if n <= 1:
            return
        total = self.weights.sum()
        d = self.compression
        # k1 scale: k(q) = d/(2π) asin(2q-1); centroid boundary where k advances by 1
        new_means: List[float] = []
        new_weights: List[float] = []
        w_so_far = 0.0
        cur_mean = self.means[0]
        cur_w = self.weights[0]

        def k_fn(q: float) -> float:
            return d / (2 * np.pi) * np.arcsin(max(-1.0, min(1.0, 2 * q - 1)))

        k_lo = k_fn(0.0)
        for i in range(1, n):
            q = (w_so_far + cur_w + self.weights[i] / 2) / total
            if k_fn(q) - k_lo < 1.0:
                # absorb into current centroid
                cw = cur_w + self.weights[i]
                cur_mean = (cur_mean * cur_w + self.means[i] * self.weights[i]) / cw
                cur_w = cw
            else:
                new_means.append(cur_mean)
                new_weights.append(cur_w)
                w_so_far += cur_w
                k_lo = k_fn(w_so_far / total)
                cur_mean = self.means[i]
                cur_w = self.weights[i]
        new_means.append(cur_mean)
        new_weights.append(cur_w)
        self.means = np.asarray(new_means)
        self.weights = np.asarray(new_weights)

    def quantile(self, q: float) -> Optional[float]:
        if len(self.means) == 0:
            return None
        if len(self.means) == 1:
            return float(self.means[0])
        total = self.weights.sum()
        target = q * total
        # centroid centers in cumulative-weight space
        cum = np.cumsum(self.weights) - self.weights / 2
        if target <= cum[0]:
            return float(self.means[0])
        if target >= cum[-1]:
            return float(self.means[-1])
        i = int(np.searchsorted(cum, target)) - 1
        frac = (target - cum[i]) / (cum[i + 1] - cum[i])
        return float(self.means[i] + frac * (self.means[i + 1] - self.means[i]))

    def to_bytes(self) -> bytes:
        return struct.pack("<di", self.compression, len(self.means)) \
            + self.means.tobytes() + self.weights.tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "TDigest":
        compression, n = struct.unpack_from("<di", data)
        off = 12
        means = np.frombuffer(data[off:off + 8 * n], dtype=np.float64).copy()
        weights = np.frombuffer(data[off + 8 * n:off + 16 * n], dtype=np.float64).copy()
        return cls(compression, means, weights)
