"""PEP 249 (DB-API 2.0) driver over the HTTP broker — the JDBC-client analog.

The reference ships a `java.sql` driver (`pinot-clients/pinot-jdbc-client`:
`PinotDriver` / `PinotConnection` / `PinotPreparedStatement`) layered on its
java-client; this module is the same layering on `pinot_tpu.client`, so any
DB-API tooling (pandas `read_sql`, SQLAlchemy raw connections, plain scripts)
can talk to a cluster:

    import pinot_tpu.dbapi as dbapi
    conn = dbapi.connect(broker="http://localhost:8099")
    cur = conn.cursor()
    cur.execute("SELECT city, COUNT(*) FROM trips WHERE fare > ? GROUP BY city", [10])
    print(cur.description, cur.fetchall())

`paramstyle` is "qmark": `?` placeholders are substituted with escaped SQL
literals, mirroring `PinotPreparedStatement`'s client-side substitution (the
wire protocol has no server-side prepared statements). `?` inside string
literals is left alone.
"""

from __future__ import annotations

import datetime
from typing import Any, List, Optional, Sequence, Tuple

from .client import Connection as _ClientConnection

apilevel = "2.0"
threadsafety = 2          # threads may share the module and connections
paramstyle = "qmark"


# -- exceptions (PEP 249 hierarchy) -----------------------------------------

class Warning(Exception):            # noqa: A001 — name mandated by PEP 249
    pass


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class DataError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


# -- module-level constructors/type objects (PEP 249) ------------------------

Date = datetime.date
Time = datetime.time
Timestamp = datetime.datetime


def DateFromTicks(ticks):
    return Date.fromtimestamp(ticks)


def TimeFromTicks(ticks):
    return Timestamp.fromtimestamp(ticks).time()


def TimestampFromTicks(ticks):
    return Timestamp.fromtimestamp(ticks)


Binary = bytes


class _TypeObject:
    def __init__(self, *py_types):
        self.py_types = py_types

    def __eq__(self, other):
        return other in self.py_types


STRING = _TypeObject(str)
BINARY = _TypeObject(bytes)
NUMBER = _TypeObject(int, float)
DATETIME = _TypeObject(datetime.datetime, datetime.date)
ROWID = _TypeObject(int)


# -- literal escaping --------------------------------------------------------

def escape(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (datetime.date, datetime.datetime, datetime.time)):
        return f"'{value.isoformat()}'"
    if isinstance(value, str):
        return "'" + value.replace("'", "''") + "'"
    if isinstance(value, (list, tuple)):
        return ", ".join(escape(v) for v in value)
    raise ProgrammingError(f"cannot bind parameter of type {type(value).__name__}")


def _substitute(operation: str, parameters: Sequence[Any]) -> str:
    """Replace `?` placeholders with escaped values. A `?` inside a
    single-quoted string literal, a double-quoted identifier, or a `--` line
    / `/* */` block comment is literal text, not a parameter slot."""
    out: List[str] = []
    it = iter(parameters)
    i = 0
    n = len(operation)
    used = 0
    while i < n:
        ch = operation[i]
        if ch in ("'", '"'):
            # quoted region, copied verbatim; a doubled quote char escapes it
            q = ch
            out.append(ch)
            i += 1
            while i < n:
                out.append(operation[i])
                if operation[i] == q:
                    if i + 1 < n and operation[i + 1] == q:
                        out.append(q)
                        i += 2
                        continue
                    i += 1
                    break
                i += 1
        elif ch == "-" and i + 1 < n and operation[i + 1] == "-":
            # -- line comment: verbatim to end of line
            j = operation.find("\n", i)
            j = n if j < 0 else j + 1
            out.append(operation[i:j])
            i = j
        elif ch == "/" and i + 1 < n and operation[i + 1] == "*":
            # /* block comment */: verbatim through the terminator
            j = operation.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(operation[i:j])
            i = j
        elif ch == "?":
            try:
                out.append(escape(next(it)))
            except StopIteration:
                raise ProgrammingError(
                    f"SQL has more placeholders than the {len(parameters)} "
                    "parameters given") from None
            used += 1
            i += 1
        else:
            out.append(ch)
            i += 1
    if used != len(parameters):
        raise ProgrammingError(
            f"SQL has {used} placeholders but {len(parameters)} parameters given")
    return "".join(out)


# -- cursor / connection -----------------------------------------------------

class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: List[List[Any]] = []
        self._pos = 0
        self.description: Optional[List[Tuple]] = None
        self.rowcount = -1
        self.stats = {}
        self._closed = False

    # -- execution ---------------------------------------------------------
    def execute(self, operation: str, parameters: Optional[Sequence[Any]] = None
                ) -> "Cursor":
        if self._closed:
            raise InterfaceError("cursor is closed")
        if self._conn._client is None:
            raise InterfaceError("connection is closed")
        sql = _substitute(operation, list(parameters)) if parameters else operation
        try:
            rs = self._conn._client.execute(sql)
        except Error:
            raise
        except Exception as exc:  # transport / server-side failures
            raise OperationalError(str(exc)) from exc
        self._rows = rs.rows
        self._pos = 0
        self.rowcount = len(rs.rows)
        self.stats = rs.stats
        self.description = [
            (name, self._infer_type(idx), None, None, None, None, None)
            for idx, name in enumerate(rs.columns)
        ]
        return self

    def executemany(self, operation: str,
                    seq_of_parameters: Sequence[Sequence[Any]]) -> "Cursor":
        for parameters in seq_of_parameters:
            self.execute(operation, parameters)
        return self

    def _infer_type(self, idx: int):
        for row in self._rows:
            v = row[idx]
            if v is not None:
                return type(v)
        return None

    # -- fetch -------------------------------------------------------------
    def fetchone(self) -> Optional[List[Any]]:
        self._check_results()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[List[Any]]:
        self._check_results()
        size = size or self.arraysize
        out = self._rows[self._pos:self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[List[Any]]:
        self._check_results()
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def _check_results(self) -> None:
        if self._closed:
            raise InterfaceError("cursor is closed")
        if self.description is None:
            raise ProgrammingError("no query has been executed")

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    # -- misc --------------------------------------------------------------
    def close(self) -> None:
        self._closed = True
        self._rows = []

    def setinputsizes(self, sizes) -> None:
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Connection:
    def __init__(self, broker: str, controller: Optional[str] = None,
                 token: Optional[str] = None):
        self._client: Optional[_ClientConnection] = _ClientConnection(
            broker, controller, token=token)

    def cursor(self) -> Cursor:
        if self._client is None:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def execute(self, operation: str,
                parameters: Optional[Sequence[Any]] = None) -> Cursor:
        """Convenience shortcut (sqlite3-style): cursor + execute in one call."""
        return self.cursor().execute(operation, parameters)

    def close(self) -> None:
        self._client = None

    def commit(self) -> None:
        pass  # reads only — nothing to commit, but PEP 249 requires the method

    def rollback(self) -> None:
        raise NotSupportedError("transactions are not supported")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(broker: str, controller: Optional[str] = None,
            token: Optional[str] = None) -> Connection:
    return Connection(broker, controller, token=token)
