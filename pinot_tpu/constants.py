"""Cross-layer constants shared by heavy (broker) and light (external
connector) modules alike — deliberately dependency-free."""

# "unbounded" LIMIT sentinel for synthesized leaf/export scans: one value for
# the in-proc context, the SQL shipped to remote servers, and connector split
# scans, so every transport behaves identically.
UNBOUNDED_LIMIT = 1 << 40
