"""Device mesh helpers."""

from __future__ import annotations

from typing import Optional

import jax

SEGMENT_AXIS = "seg"


def default_mesh(n_devices: Optional[int] = None, axis: str = SEGMENT_AXIS) -> jax.sharding.Mesh:
    """1-D mesh over available devices; the single parallel axis is segment scatter
    (the reference's only data-parallel dimension — SURVEY.md §2.11 row 'DP')."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, only {len(devices)} available")
    return jax.make_mesh((n,), (axis,), devices=devices[:n])
