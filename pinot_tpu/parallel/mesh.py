"""Device mesh helpers: the 1-D segment mesh plus chip-aware placement.

Placement policy (README "Multi-chip execution"): segments are assigned to
device slots LPT-style — sorted by descending doc count, each segment goes to
the least-loaded device that still has a free slot. Per-device capacity is
bounded at `s_pad / n_devices` so the shard_map block stays rectangular; the
residual imbalance (the biggest device's doc load over the mean) is what
`deviceSkewPct` reports, since the slowest chip bounds every collective.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax

SEGMENT_AXIS = "seg"


def pad_slots(n_segments: int, n_devices: int) -> int:
    """Slot count for a stacked segment block: per-device slots quantized to
    the next power of two on multi-device meshes, so ragged segment-count
    tails share a compile-cache bucket (log2 variants) instead of retracing
    the shard kernel per distinct count. Single-device blocks keep the exact
    count — there is no cross-device rectangularity to buy and padding slots
    would only add masked scan rows."""
    per = -(-n_segments // n_devices)
    if n_devices > 1 and per > 1:
        per = 1 << (per - 1).bit_length()
    return per * n_devices


def placement_slots(seg_docs: Sequence[int], s_pad: int, n_devices: int
                    ) -> Tuple[List[int], List[int]]:
    """LPT assignment of segments to block slots.

    Returns (slots, loads): `slots[i]` is segment i's row in the stacked
    [s_pad, rows] block (slot // (s_pad/n_devices) is its device), `loads[d]`
    the total docs device d scans. Biggest segments place first onto the
    least-loaded device with free capacity, so an uneven set (one fat segment
    + many small ones) doesn't serialize the mesh behind one chip."""
    k = max(s_pad // max(n_devices, 1), 1)
    order = sorted(range(len(seg_docs)), key=lambda i: (-seg_docs[i], i))
    loads = [0] * n_devices
    used = [0] * n_devices
    slots = [0] * len(seg_docs)
    for i in order:
        free = [d for d in range(n_devices) if used[d] < k]
        d = min(free, key=lambda d: (loads[d], d))
        slots[i] = d * k + used[d]
        used[d] += 1
        loads[d] += int(seg_docs[i])
    return slots, loads


def skew_pct(loads: Sequence[int]) -> float:
    """Percent by which the most-loaded device exceeds the mean load (0 for a
    perfectly balanced or empty mesh) — the per-launch `deviceSkewPct`."""
    total = sum(loads)
    if not loads or total <= 0:
        return 0.0
    mean = total / len(loads)
    return (max(loads) / mean - 1.0) * 100.0


def default_mesh(n_devices: Optional[int] = None, axis: str = SEGMENT_AXIS) -> jax.sharding.Mesh:
    """1-D mesh over available devices; the single parallel axis is segment scatter
    (the reference's only data-parallel dimension — SURVEY.md §2.11 row 'DP')."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, only {len(devices)} available")
    return jax.make_mesh((n,), (axis,), devices=devices[:n])
