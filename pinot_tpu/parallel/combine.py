"""Sharded multi-segment execution: scatter segments over a mesh, psum-combine partials.

The TPU-native analog of the reference's entire distributed query data plane for
aggregations (SURVEY.md §2.11): where the reference scatters segments to servers over
Netty (`QueryRouter.submitQuery`), runs per-segment operator trees on thread pools
(`BaseCombineOperator`), and merges DataTables on the broker
(`GroupByDataTableReducer`), here the segment axis IS a mesh axis:

    stacked columns [S, P] --shard_map--> per-device fused scan --psum/pmin/pmax--> result

Dense group keys and LUT ids must agree across devices so partial aggregates combine
with one ICI collective and no host-side value merge. Two ways a segment set qualifies:

* *aligned dictionaries* (`dictHash` equal — built via `segment.writer.
  build_aligned_segments` or a shared ingestion dictionary): ids already agree;
* anything else — including consuming (mutable) segments — rides the **merged-
  dictionary path** (`parallel/merged.py`): a global sorted dictionary per referenced
  column, per-segment ids remapped host-side once at block-build time, after which the
  set is aligned by construction.

JSON_MATCH/TEXT_MATCH/geo doc-set bitmaps stack [S, rows] into the kernel's
`docsets` input (cached per predicate on the block), and multi-value LUT filter
columns stack as [S, rows, W] padded id matrices — both on the ALIGNED immutable
path; unaligned or mutable sets with those shapes keep the per-segment fallback.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.datablock import lut_size, padded_rows
from ..engine.kernels import KernelSpec, _fence_first_call, tree_bytes
from ..query import stats as qstats
from ..query.aggregates import make_agg
from ..query.context import QueryContext, compile_query
from ..query.executor import ServerQueryExecutor
from ..query.planner import build_device_geometry, plan_segment
from ..query.predicate import CmpLeaf, LutLeaf, NullLeaf
from ..query.reduce import merge_segment_results, reduce_to_result
from ..query.result import ResultTable
from ..segment.reader import ImmutableSegment
from ..sql.ast import Expr, Function, Identifier, identifiers_in
from ..utils.metrics import get_registry
from .merged import MergedSegmentView, view_key
from .mesh import (SEGMENT_AXIS, default_mesh, pad_slots, placement_slots,
                   skew_pct)


def _has_docset_filter(ctx: QueryContext) -> bool:
    """JSON_MATCH/TEXT_MATCH resolve to per-segment doc bitmaps (DocSetLeaf):
    on the ALIGNED immutable path they stack into the mesh kernel's `docsets`
    input (_stacked_docsets); unaligned/mutable sets keep the fallback."""
    def walk(e) -> bool:
        if isinstance(e, Function):
            if e.name in ("json_match", "text_match"):
                return True
            return any(walk(a) for a in e.args)
        return False
    return ctx.filter is not None and walk(ctx.filter)

_SHARD_KERNEL_CACHE: Dict[Tuple, object] = {}

# Dense grouped outputs at or above this key count combine with a reduce-
# scatter (`psum_scatter`, each device keeping 1/n of the key space) instead
# of a full psum: the all-gather half of the psum is pure waste when the host
# fetch reassembles the shards anyway, so the collective moves half the bytes.
# Below it the savings don't cover the sharded-layout bookkeeping.
SCATTER_MIN_KEYS = 4096

# one-time measured cost of a mesh psum per (mesh, element-count bucket):
# the `collectiveMs` ESTIMATE attached to mesh results (the collective is
# fused into the kernel by XLA, so it cannot be timed in situ without
# perturbing the launch)
_COLLECTIVE_BENCH: Dict[Tuple, float] = {}


def device_topk_screen(ctx: QueryContext) -> bool:
    """Cheap handler-thread pre-screen: could this SELECTION ride the device
    top-k path? (single plain-column ORDER BY, bounded LIMIT). The full
    eligibility check (numeric dtype, int bounds, dictionary alignment,
    device-safe filter) runs in `prepare_partial`; a miss there resolves to
    the host fallback. Without this screen every orderless selection would
    wait out the pipeline's batch window just to learn it must fall back."""
    k = ctx.offset + ctx.limit
    return (len(ctx.order_by) == 1
            and isinstance(ctx.order_by[0].expr, Identifier)
            and 0 < k <= ServerQueryExecutor.MAX_DEVICE_TOPK)


@dataclass
class PreparedDispatch:
    """A planned-but-not-launched device dispatch (pipeline tentpole unit).

    The pipeline groups prepared items before launching: items with equal
    `dedupe_key` are byte-identical dispatches (same executable, same runtime
    operands) and share ONE kernel launch + ONE fetched result; items with
    equal `stack_key` (same `KernelSpec.signature()` executable over the same
    block, differing only in runtime scalars) stack into ONE batched kernel
    launch (`lax.scan` over the stacked scalar streams) instead of N
    sequential dispatches."""

    kind: str                    # "agg" | "topk"
    spec: Any                    # KernelSpec ("agg") or static key tuple ("topk")
    inputs: dict
    s_pad: int
    rows: int
    stack_key: Tuple             # same traced executable + same device operands
    dedupe_key: Optional[Tuple]  # fully identical dispatch (None = never dedupe)
    stackable: bool
    decode: Any                  # decode(host outs dict) -> partial | DEVICE_FALLBACK
    iscal_np: Optional[np.ndarray] = None  # host scalar streams (stacking)
    fscal_np: Optional[np.ndarray] = None
    trim_keys: Tuple[int, int] = (0, 0)  # (num_keys_pad, num_keys_real) device trim
    launch: Any = None           # "topk": () -> outs_dev (pre-bound kernel)


class DocsetPlanDivergence(Exception):
    """Segments in one set compile to different doc-set leaf structures (e.g.
    a geo index present on some segments only): the stacked mesh dispatch
    cannot serve them — callers fall back to per-segment execution."""


def _refs_multi_value(ctx: QueryContext, seg) -> bool:
    """True when any column the query touches is multi-value."""
    from ..sql.ast import identifiers_in
    names = set()
    if ctx.filter is not None:
        names.update(identifiers_in(ctx.filter))
    for e in ctx.group_by:
        names.update(identifiers_in(e))
    for f in ctx.aggregations:
        names.update(identifiers_in(f))
    for e, _ in ctx.select_items:
        names.update(identifiers_in(e))
    for name in names:
        try:
            if getattr(seg.column(name), "is_multi_value", False):
                return True
        except KeyError:
            continue  # '*' / alias — not a physical column
    return False


# below this many combined star-tree records, the per-segment host loop beats
# any device dispatch (relay round trip >> microseconds of numpy); above it the
# stacked device star path wins (high-cardinality split dimensions)
STAR_DEVICE_MIN_RECORDS = 1 << 16


@dataclass
class StarSetPlan:
    """Stacked device star-tree execution: one slot plan over every segment's
    record-table view + the per-segment traversal masks."""
    plans: list       # per-segment StarTreePlan (masks + reassembly)
    views: list       # per-segment StarTreeView (the stacked mini-segments)
    plan2: Any        # device SegmentPlan of the slot query over views[0]
    kind = "star"


def aligned_dictionaries(segments: Sequence[ImmutableSegment], cols: Sequence[str]) -> bool:
    """True iff every column in `cols` has identical dictionaries across segments."""
    for col in cols:
        hashes = set()
        for seg in segments:
            reader = seg.column(col)
            if not reader.has_dictionary:
                return False
            h = reader.meta.get("dictHash")
            if h is None:
                return False
            hashes.add((h, reader.cardinality))
        if len(hashes) > 1:
            return False
    return True


class SegmentSetBlock:
    """Stacked device columns for an aligned segment set: [S_pad, P] arrays.

    Arrays are `device_put` once with their final mesh sharding (segment axis sharded,
    decode tables replicated) so repeated queries dispatch with zero re-shard copies —
    the analog of the reference's segment-resident mmap buffers being scan-ready.
    """

    def __init__(self, segments: Sequence[ImmutableSegment], s_pad: int,
                 mesh: jax.sharding.Mesh, view=None):
        self.segments = list(segments)
        self.s_pad = s_pad
        self.view = view  # MergedSegmentView for unaligned sets, else None
        self.seg_docs = view.seg_docs if view is not None \
            else tuple(s.num_docs for s in segments)
        self.rows = max(padded_rows(n) for n in self.seg_docs)
        self.n_devices = mesh.devices.size
        # chip-aware placement (mesh.placement_slots): slots[i] is segment i's
        # row in the stacked block. Aligned immutable sets reorder freely; a
        # merged view keeps identity order — its remap tables and mutable
        # snapshots are rebuilt per growth step, so the conservative identity
        # placement keeps block reuse simple there.
        if self.n_devices > 1 and view is None:
            self.slots, self.device_loads = placement_slots(
                self.seg_docs, s_pad, self.n_devices)
        else:
            self.slots = list(range(len(segments)))
            k = max(s_pad // max(self.n_devices, 1), 1)
            loads = [0] * self.n_devices
            for i, d in enumerate(self.seg_docs):
                loads[i // k] += int(d)
            self.device_loads = loads
        self.slot_to_seg = np.full(s_pad, -1, dtype=np.int64)
        for i, sl in enumerate(self.slots):
            self.slot_to_seg[sl] = i
        self.skew_pct = skew_pct(self.device_loads)
        cells = s_pad * self.rows
        self.pad_waste_pct = \
            (1.0 - sum(self.seg_docs) / cells) * 100.0 if cells else 0.0
        P = jax.sharding.PartitionSpec
        self._sharded = jax.sharding.NamedSharding(mesh, P(SEGMENT_AXIS))
        self._replicated = jax.sharding.NamedSharding(mesh, P())
        self._cache: Dict[Tuple[str, str], jnp.ndarray] = {}

    def _stack(self, kind: str, col: str, fill, per_seg) -> jnp.ndarray:
        key = (kind, col)
        if key not in self._cache:
            first = np.asarray(per_seg(0, self.segments[0]))
            # 1-D per-segment arrays stack to [S, rows]; 2-D (padded MV id
            # matrices [rows, W]) stack to [S, rows, W]
            shape = (self.s_pad, self.rows) + first.shape[1:]
            out = np.full(shape, fill, dtype=first.dtype)
            for i, seg in enumerate(self.segments):
                # slice to the view's snapshot row count: mutable members may have
                # grown since the view (and its remap tables) were built
                arr = np.asarray(per_seg(i, seg))[:self.seg_docs[i]]
                out[self.slots[i], :len(arr)] = arr
            self._cache[key] = jax.device_put(out, self._sharded)
        return self._cache[key]

    def ids(self, col: str) -> jnp.ndarray:
        """Dict ids in the space the plan was made in: segment-local ids for aligned
        sets, remapped GLOBAL ids (merged.py) for unaligned ones. Multi-value
        columns stack as [S, rows, W] left-justified id matrices (W = the
        set-wide max values per row), out-of-dictionary fill = cardinality —
        exactly the single-device MV layout with a segment axis in front."""
        remaps = self.view.remap(col) if self.view is not None else None
        if remaps is None:
            r0 = self.segments[0].column(col)
            card = r0.cardinality
            if getattr(r0, "is_multi_value", False):
                w = max(max(s.column(col).max_num_values, 1)
                        for s in self.segments)

                def per_seg_mv(i, s):
                    reader = s.column(col)
                    flat = np.asarray(reader.fwd).astype(np.int32)
                    off = np.asarray(reader.mv_offsets)
                    counts = np.diff(off)
                    n = len(counts)
                    mat = np.full((n, w), card, dtype=np.int32)
                    rows = np.repeat(np.arange(n), counts)
                    within = np.arange(len(flat)) - np.repeat(off[:-1], counts)
                    mat[rows, within] = flat
                    return mat
                return self._stack("ids", col, np.int32(card), per_seg_mv)
            return self._stack("ids", col, np.int32(card),
                               lambda i, s: np.asarray(s.column(col).fwd).astype(np.int32))
        mc = self.view.column(col)
        return self._stack("ids", col, np.int32(mc.cardinality),
                           lambda i, s: mc.local_ids(i).astype(np.int32)
                           if remaps[i] is None
                           else remaps[i][mc.local_ids(i)])

    def raw(self, col: str) -> jnp.ndarray:
        from ..engine.datablock import _narrow
        return self._stack("raw", col, 0,
                           lambda i, s: _narrow(np.asarray(s.column(col).fwd)))

    def decoded(self, col: str) -> jnp.ndarray:
        """Decoded numeric values regardless of encoding, host-materialized ONCE.

        Dict decode never happens on device: the relay serializes each device gather
        into an extra host round trip per dispatch, so queries read pre-decoded HBM
        columns (the `DataFetcher.java:47` value-buffer analog). Decode uses each
        segment's OWN dictionary, so it is alignment-independent."""
        from ..engine.datablock import _narrow

        def per_seg(i, s):
            reader = s.column(col)
            arr = np.asarray(reader.fwd)
            if reader.has_dictionary:
                vals = _narrow(np.asarray(reader.dictionary.values))
                return vals[arr.astype(np.int64)]
            return _narrow(arr)

        return self._stack("decoded", col, 0, per_seg)

    def dict_luts(self, col: str) -> jnp.ndarray:
        """Per-segment padded decode tables stacked [S_pad, Lmax], sharded on
        the segment axis like every other block array.

        Row i is segment i's OWN dictionary zero-padded to the set-wide max
        lut_size, so the fused kernel's `take_along_axis` gather
        (`kernels._fused_env`) decodes segment-local ids in-register and the
        decoded [S_pad, rows] column never materializes in HBM. Aligned
        sets only: merged views remap ids into the global dictionary space,
        which a per-segment LUT stack cannot decode."""
        key = ("dictlut", col)
        if key not in self._cache:
            from ..engine.datablock import _narrow, lut_size
            tables = []
            for s in self.segments:
                reader = s.column(col)
                vals = _narrow(np.asarray(reader.dictionary.values))
                t = np.zeros(lut_size(reader.cardinality), dtype=vals.dtype)
                t[:len(vals)] = vals
                tables.append(t)
            lmax = max(len(t) for t in tables)
            out = np.zeros((self.s_pad, lmax),
                           dtype=np.result_type(*[t.dtype for t in tables]))
            for i, t in enumerate(tables):
                out[self.slots[i], :len(t)] = t
            self._cache[key] = jax.device_put(out, self._sharded)
        return self._cache[key]

    def null_mask(self, col: str) -> jnp.ndarray:
        def per_seg(i, s):
            nb = s.column(col).null_bitmap
            return nb if nb is not None else np.zeros(s.num_docs, dtype=bool)
        return self._stack("null", col, False, per_seg)

    @property
    def valid(self) -> jnp.ndarray:
        def per_seg(i, s):
            return np.ones(s.num_docs, dtype=bool)
        return self._stack("valid", "", False, per_seg)


class MeshQueryExecutor:
    """Executes aggregation queries over segment sets sharded across a device mesh."""

    def __init__(self, mesh: Optional[jax.sharding.Mesh] = None,
                 fused_enabled: Optional[bool] = None):
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = self.mesh.devices.size
        # fused in-register dict decode over the stacked block
        # (clusterConfig/server.fused.enabled): None defers to the
        # calibrated KernelCaps.fused_enabled regime
        self.fused_enabled = fused_enabled
        self._fallback = ServerQueryExecutor(fused_enabled=fused_enabled)
        self._set_blocks: Dict[Tuple, SegmentSetBlock] = {}
        self._views: Dict[Tuple, MergedSegmentView] = {}
        self._replicated = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec())
        # content-addressed cache of replicated query constants (LUTs, scalars, strides):
        # repeated queries dispatch with zero host->device transfers
        self._const_cache: Dict[bytes, jnp.ndarray] = {}

    def _const(self, arr: np.ndarray) -> jnp.ndarray:
        # shape is part of identity: equal bytes at different shapes (e.g.
        # an empty [0] scalar stream vs its stacked [B, 0] form) are
        # different device constants
        key = arr.dtype.str.encode() + repr(arr.shape).encode() + arr.tobytes()
        dev = self._const_cache.get(key)
        if dev is None:
            if len(self._const_cache) > 4096:
                self._const_cache.clear()
            dev = jax.device_put(arr, self._replicated)
            self._const_cache[key] = dev
        return dev

    # ------------------------------------------------------------------
    def execute(self, segments: Sequence[ImmutableSegment],
                query: Union[str, QueryContext], schema=None) -> ResultTable:
        ctx = compile_query(query, schema or segments[0].schema) \
            if isinstance(query, str) else query
        plan, view = self._plan_for_set(ctx, segments)
        if isinstance(plan, StarSetPlan):
            outs_dev, decode = self._dispatch_star(ctx, plan)
            return decode(jax.device_get(outs_dev))
        if plan is None or plan.kind != "device":
            return self._fallback.execute(segments, ctx)
        try:
            return self._execute_sharded(ctx, plan, segments, view)
        except DocsetPlanDivergence:
            return self._fallback.execute(segments, ctx)

    def _plan_for_set(self, ctx: QueryContext, segments):
        """Choose the planning surface for a segment set.

        Returns (plan, view): view is None for the aligned fast path (ids agree by
        dictHash), a MergedSegmentView when ids must be remapped to a global
        dictionary, and plan is None when the set must take the per-segment
        fallback."""
        star_plans = self._star_fit_plans(ctx, segments)
        if star_plans is not None:
            # every segment answers from a pre-aggregated star-tree record
            # table. SMALL tables (~100s of records): the per-segment host
            # executor beats any device dispatch outright, so the mesh
            # planner yields to it (reference: StarTreeUtils.isFitForStarTree
            # gating in the leaf plan). LARGE record tables (high-cardinality
            # split dimensions, 1e5+ records): stack the record tables like
            # base segments and run the fused kernel over them — the
            # split-dim predicates compile into the kernel mask as LUT/
            # interval leaves and the tree-traversal record masks ride the
            # kernel's valid input (BASELINE config 3 as designed).
            star = self._plan_star_device(ctx, segments, star_plans)
            if star is not None:
                return star, "star"
            return None, None
        # doc-set filters (JSON/TEXT_MATCH bitmaps, stacked per segment) and
        # MV LUT filters ([S, rows, W] padded id matrices) ride the mesh
        # kernel on the ALIGNED immutable path only: the merged view has no
        # aux indexes to match against and no MV remap, so those sets keep
        # the per-segment fallback
        special = _has_docset_filter(ctx) or _refs_multi_value(ctx, segments[0])
        total_docs = sum(s.num_docs for s in segments)
        any_mutable = any(getattr(s, "is_mutable", False) for s in segments)
        if not any_mutable:
            plan = plan_segment(ctx, segments[0], scan_docs=total_docs)
            if plan.kind != "device":
                return plan, None
            if self._alignable(plan, segments):
                return plan, None
        if special:
            return None, None
        view = self._merged_view(segments)
        return plan_segment(ctx, view, scan_docs=total_docs), view

    def _star_fit_plans(self, ctx: QueryContext, segments):
        """Per-segment StarTreePlans when EVERY segment answers this query
        from a star-tree, else None (a mixed set keeps the mesh scan: one
        full-scan segment would serialize the whole query behind the host
        fallback). Computed ONCE — both the device decision and the stacked
        dispatch reuse these plans (the traversal mask is the expensive part
        for large trees)."""
        if not all(getattr(s, "star_trees", None) for s in segments):
            return None
        if any(getattr(s, "is_mutable", False) for s in segments):
            return None
        from ..query.startree_exec import try_star_tree
        plans = []
        for s in segments:
            p = try_star_tree(ctx, s)
            if p is None:
                return None
            plans.append(p)
        return plans

    def _plan_star_device(self, ctx: QueryContext, segments, plans=None):
        """StarSetPlan when the stacked device star path applies: every tree
        fits, the combined record tables are big enough to beat the host
        loop, the slot plan is device-feasible, and the views' dictionaries
        (the parents') align across segments."""
        if plans is None:
            plans = self._star_fit_plans(ctx, segments)
        if plans is None:
            return None
        total = sum(p.tree.num_records for p in plans)
        if total < STAR_DEVICE_MIN_RECORDS:
            return None
        views = [p.tree.view for p in plans]
        plan2 = plan_segment(plans[0].ctx2, views[0], scan_docs=total)
        if plan2.kind != "device" or not self._alignable(plan2, views):
            return None
        return StarSetPlan(plans, views, plan2)

    def _dispatch_star(self, ctx: QueryContext, sp: "StarSetPlan",
                       partial=False):
        """Dispatch the stacked star-tree kernel: per-segment tree-traversal
        record masks stack into the kernel's valid input (the split-dim LUT
        predicates are already fused into the mask by the slot plan)."""
        p = self._prepare_star(ctx, sp, partial=partial)
        fn = self._get_shard_kernel(p.spec, p.s_pad, p.rows)
        return fn(p.inputs), p.decode

    def _stacked_docsets(self, ctx: QueryContext, plan, segments,
                         block: SegmentSetBlock) -> Tuple:
        """Per-segment JSON/TEXT_MATCH (or id-set) doc bitmaps, stacked
        [S_pad, rows] in leaf order and sharded on the segment axis — the
        `docsets` kernel input. The masks come from each segment's OWN aux
        index (a filter compile per segment IS the index lookup); the leaf
        structure is deterministic for a fixed expression, so leaf order
        agrees with the probe plan's.

        Stacked masks are CACHED on the block keyed by each leaf's
        `cache_token` (kind + every predicate parameter — geo leaves include
        the center point): immutable segments give one index lookup + one
        device transfer per distinct predicate, so repeated TEXT_MATCH
        queries dispatch at the same cost as any other filter (id-set leaves
        are content-addressed by a digest of the serialized set). A leaf
        without a token is never cached; cached entries reuse PER KEY, so one
        uncacheable leaf doesn't defeat the others' cache."""
        from ..query.predicate import DocSetLeaf, compile_filter
        probe_leaves = [l for l in plan.filter_prog.leaves
                        if isinstance(l, DocSetLeaf)]
        cache = block._cache
        keys = [("docset", f"{l.col}\x00{l.cache_token}")
                if l.cache_token else None for l in probe_leaves]
        out: List = [cache.get(k) if k is not None else None for k in keys]
        if any(v is None for v in out):
            per_seg: List[List[np.ndarray]] = []
            for s in segments:
                prog = compile_filter(ctx.filter, s)
                masks = [l.mask for l in prog.leaves
                         if isinstance(l, DocSetLeaf)]
                if len(masks) != len(probe_leaves):
                    raise DocsetPlanDivergence(
                        "doc-set leaf structure diverged across segments")
                per_seg.append(masks)
            n_docset_entries = sum(1 for k in cache if k[0] == "docset")
            if n_docset_entries > 32:
                # bound device memory: each entry is an [S_pad, rows] device
                # array; a stream of distinct search terms must not grow HBM
                # without limit
                for k in [k for k in cache if k[0] == "docset"]:
                    del cache[k]
            for j, key in enumerate(keys):
                if out[j] is not None:
                    continue
                stacked = np.zeros((block.s_pad, block.rows), dtype=bool)
                for i in range(len(segments)):
                    m = np.asarray(per_seg[i][j])
                    stacked[block.slots[i], :len(m)] = m[:block.rows]
                out[j] = jax.device_put(stacked, block._sharded)
                if key is not None:
                    cache[key] = out[j]
        return tuple(out)

    def _merged_view(self, segments) -> MergedSegmentView:
        # keyed by STABLE segment identity; the volatile part (mutable row counts)
        # is the value's subkey, so a grown consuming segment REPLACES its stale
        # view instead of accumulating one per growth step
        stable = tuple(getattr(s, "path", s.name) for s in segments)
        vkey = view_key(segments)
        entry = self._views.get(stable)
        if entry is None or entry[0] != vkey:
            if len(self._views) > 64:
                self._views.clear()
            entry = (vkey, MergedSegmentView(segments))
            self._views[stable] = entry
        return entry[1]

    def _alignable(self, plan, segments) -> bool:
        """Dictionary alignment is only needed where dict IDS are shared across
        devices: dense group keys, id-interval/LUT filters, and the
        distinct-family presence vectors (DISTINCTCOUNT/HLL/theta — HLL moved
        onto the presence path for the ~15x matmul-vs-scatter kernel win, at
        the cost of now needing alignment; unaligned sets take the merged-view
        global-dictionary remap instead). Decoded value columns (CmpLeaf
        expressions, SUM/MIN/MAX args) are materialized per segment against its
        OWN dictionary, so mixed segment sets still ride the mesh kernel for them."""
        cols = set(plan.group_cols)
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                cols.add(leaf.col)
        for agg in plan.aggs:
            if "distinct" in agg.device_outputs:
                cols.add(agg.arg.name)
        return aligned_dictionaries(segments, cols)

    # ------------------------------------------------------------------
    def _execute_sharded(self, ctx: QueryContext, plan, segments, view=None) -> ResultTable:
        outs_dev, decode = self._dispatch_sharded(ctx, plan, segments, view)
        return decode(self.fetch(outs_dev))  # one host sync for all partials

    def execute_many(self, segments: Sequence[ImmutableSegment],
                     queries: Sequence[Union[str, QueryContext]],
                     schema=None) -> List[ResultTable]:
        """Pipelined batch execution: dispatch every query's kernel asynchronously,
        then fetch ALL results with ONE device_get round trip.

        The relay charges one full host round trip per synchronization (~65ms) no
        matter how much work it covers, so a serving loop that drains its queue
        through this path amortizes the round trip across the batch — the TPU analog
        of the reference broker pipelining queries over its Netty channels."""
        pending: List = []  # (index, outs_dev, decode) | (index, ResultTable)
        for qi, query in enumerate(queries):
            ctx = compile_query(query, schema or segments[0].schema) \
                if isinstance(query, str) else query
            plan, view = self._plan_for_set(ctx, segments)
            if isinstance(plan, StarSetPlan):
                outs_dev, decode = self._dispatch_star(ctx, plan)
                pending.append((qi, outs_dev, decode))
            elif plan is None or plan.kind != "device":
                pending.append((qi, self._fallback.execute(segments, ctx)))
            else:
                try:
                    outs_dev, decode = self._dispatch_sharded(ctx, plan,
                                                              segments, view)
                    pending.append((qi, outs_dev, decode))
                except DocsetPlanDivergence:
                    pending.append((qi, self._fallback.execute(segments, ctx)))
        fetched = self.fetch([p[1] for p in pending if len(p) == 3])
        results: List[Optional[ResultTable]] = [None] * len(queries)
        it = iter(fetched)
        for p in pending:
            results[p[0]] = p[1] if len(p) == 2 else p[2](next(it))
        return results

    def dispatch_partial(self, ctx: QueryContext, segments):
        """Plan + asynchronously dispatch a SERVER-LEVEL partial for the set.

        Returns (device outputs, decode) where decode(host_outs) ->
        SegmentResult — the pre-broker-reduce partial a server ships to the
        broker (reference: ServerQueryExecutorV1Impl returning a DataTable,
        not a reduced result) — or None when the set cannot ride the device
        path (selection/host plans, doc-set divergence). Group partials are
        NOT order-by trimmed: the broker merges partials from every server
        before trimming, exactly like the CPU per-segment path."""
        plan, view = self._plan_for_set(ctx, segments)
        if isinstance(plan, StarSetPlan):
            return self._dispatch_star(ctx, plan, partial=True)
        if plan is None or plan.kind != "device":
            return None
        try:
            return self._dispatch_sharded(ctx, plan, segments, view,
                                          partial=True)
        except DocsetPlanDivergence:
            return None

    # -- prepared dispatch (the serving pipeline's unit of work) -------
    def prepare_partial(self, ctx: QueryContext, segments):
        """Plan + build (but do NOT launch) a server-level partial dispatch.

        Returns a PreparedDispatch or None (host fallback). The pipeline
        groups prepared items by dedupe/stack key and launches them through
        `dispatch_prepared`, so N same-shape queries pay one traced
        executable and — where only runtime scalars differ — one batched
        kernel launch."""
        if not ctx.aggregations and not ctx.distinct:
            # selection: only the immutable top-k path rides the device (no
            # merged-view remap — a fallback verdict must stay cheap)
            if not segments or any(getattr(s, "is_mutable", False)
                                   for s in segments):
                return None
            plan = plan_segment(ctx, segments[0],
                                scan_docs=sum(s.num_docs for s in segments))
            if plan.kind != "selection":
                return None  # empty/pruned: the host path answers trivially
            return self._prepare_topk(ctx, plan, segments)
        plan, view = self._plan_for_set(ctx, segments)
        if isinstance(plan, StarSetPlan):
            return self._prepare_star(ctx, plan)
        if plan is None or plan.kind != "device":
            return None
        try:
            return self._prepare_sharded(ctx, plan, segments, view,
                                         partial=True)
        except DocsetPlanDivergence:
            return None

    def fetch(self, trees):
        """One host sync for a batch of dispatched output trees (the
        pipeline's fetch hook; fakes in tests override this). The wall spent
        blocking here is the batch's device-exec + transfer time."""
        t0 = time.perf_counter()
        out = jax.device_get(trees)
        ms = (time.perf_counter() - t0) * 1000
        get_registry().histogram("pinot_mesh_fetch_ms").observe(ms)
        qstats.record(qstats.DEVICE_FETCH_MS, ms)
        qstats.record(qstats.BYTES_FETCHED, tree_bytes(out))
        return out

    def dispatch_prepared(self, reps: Sequence[PreparedDispatch]):
        """Launch a deduped batch of prepared dispatches.

        `reps` are dedupe-group representatives. Returns a list of launches
        `(outs_dev, finish, indices)`: `indices` are positions into `reps`
        covered by that launch and `finish(host_fetched)` -> list of decoded
        host outs dicts aligned with `indices`. Stackable reps sharing a
        `stack_key` collapse into ONE batched kernel launch."""
        groups: Dict[Tuple, List[int]] = {}
        order: List[Tuple] = []
        for i, p in enumerate(reps):
            key = p.stack_key if (p.kind == "agg" and p.stackable) \
                else ("solo", i)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(i)
        launches = []
        for key in order:
            idxs = groups[key]
            ps = [reps[i] for i in idxs]
            if len(ps) == 1:
                p = ps[0]
                if p.kind == "topk":
                    outs = p.launch()
                else:
                    fn = self._get_shard_kernel(p.spec, p.s_pad, p.rows)
                    if p.spec.fused_cols:
                        qstats.record(qstats.FUSED_LAUNCHES)
                    outs = fn(p.inputs)
                packed, unpack = self._pack(outs, p.trim_keys, batched=0)
                launches.append((packed,
                                 (lambda host, u=unpack: [u(host)]), idxs))
            else:
                outs, b_real = self._launch_stacked(ps)
                packed, unpack = self._pack(outs, ps[0].trim_keys,
                                            batched=b_real)
                launches.append((packed,
                                 (lambda host, u=unpack, n=b_real:
                                  [u(host, b) for b in range(n)]), idxs))
        return launches

    def _launch_stacked(self, ps: List[PreparedDispatch]):
        """ONE batched kernel launch for same-executable prepared dispatches
        differing only in runtime scalars: scan the fused body over stacked
        [B, n] scalar streams (columns/LUTs/valid broadcast). B pads to the
        next power of two (repeating the last scalars) so the jit cache holds
        log2 variants, not one per concurrency level."""
        b = len(ps)
        b_pad = 1 << (b - 1).bit_length()
        iscal = np.stack([p.iscal_np for p in ps]
                         + [ps[-1].iscal_np] * (b_pad - b))
        fscal = np.stack([p.fscal_np for p in ps]
                         + [ps[-1].fscal_np] * (b_pad - b))
        inputs = dict(ps[0].inputs)
        inputs["iscal"] = self._const(iscal)
        inputs["fscal"] = self._const(fscal)
        fn = self._get_shard_kernel(ps[0].spec, ps[0].s_pad, ps[0].rows,
                                    batch=b_pad)
        if ps[0].spec.fused_cols:
            # one persistent launch carries every stacked query's fused scan
            qstats.record(qstats.FUSED_LAUNCHES)
        return fn(inputs), b

    def _pack(self, outs_dev: Dict[str, jnp.ndarray], trim_keys: Tuple[int, int],
              batched: int):
        """Device-resident combine of a launch's outputs before the fetch.

        Concatenates every output leaf (raveled, grouped by dtype, key axis
        trimmed from num_keys_pad to num_keys_real) into one flat array per
        dtype ON DEVICE, so the batched `device_get` ships a couple of
        combined arrays per launch instead of per-output (and, stacked,
        per-item) leaves. Returns (packed device dict, unpack) where
        unpack(host_packed[, b]) rebuilds the named outs dict."""
        meta = tuple(sorted((k, tuple(v.shape), v.dtype.str)
                            for k, v in outs_dev.items()))
        pad, real = trim_keys
        key = ("pack", meta, trim_keys, bool(batched))
        fn = _SHARD_KERNEL_CACHE.get(key)

        # grouped outputs carry the key axis at either `pad` (reduce-scattered
        # dense outputs, overflow bucket dropped on device) or `pad + 1` (the
        # psum/pmin/pmax path keeps the masked-row overflow bucket at index
        # pad); both trim to `real` — every partial decoder reads only
        # [:num_keys_real]
        def _core(shape):
            core = shape[1:] if batched else shape
            if pad and real < pad and core and core[0] in (pad, pad + 1):
                core = (real,) + tuple(core[1:])
            return core

        if fn is None:
            def pack_impl(outs):
                by_dt: Dict[str, list] = {}
                for name, shape, dts in meta:
                    v = outs[name]
                    core = shape[1:] if batched else shape
                    if pad and real < pad and core and core[0] in (pad, pad + 1):
                        v = v[:, :real] if batched else v[:real]
                    flat = v.reshape((v.shape[0], -1)) if batched \
                        else v.reshape(-1)
                    by_dt.setdefault(dts, []).append(flat)
                return {dt: (jnp.concatenate(parts, axis=-1)
                             if len(parts) > 1 else parts[0])
                        for dt, parts in by_dt.items()}
            fn = jax.jit(pack_impl)
            _SHARD_KERNEL_CACHE[key] = fn

        def unpack(host: Dict[str, np.ndarray], b: Optional[int] = None):
            out = {}
            offs: Dict[str, int] = {}
            for name, shape, dts in meta:
                core = _core(shape)
                n = int(np.prod(core)) if core else 1
                flat = host[dts]
                row = flat[b] if batched else flat
                o = offs.get(dts, 0)
                out[name] = np.asarray(row[o:o + n]).reshape(core)
                offs[dts] = o + n
            return out

        return fn(outs_dev), unpack

    def _block_for(self, segments, view, s_pad: int) -> SegmentSetBlock:
        # stable key + volatile subkey: growth of a consuming segment frees the
        # superseded block's device arrays instead of pinning up to 64 dead copies
        stable = (tuple(getattr(s, "path", s.name) for s in segments),
                  view is not None)
        vkey = (view_key(segments), s_pad)
        entry = self._set_blocks.get(stable)
        if entry is None or entry[0] != vkey:
            if len(self._set_blocks) > 64:
                self._set_blocks.clear()
            entry = (vkey, SegmentSetBlock(segments, s_pad, self.mesh, view))
            self._set_blocks[stable] = entry
            # padding-waste accounting: fraction of the stacked [s_pad, rows]
            # block that is fill (ragged tails + pow2 slot quantization), the
            # scan overhead uneven segment sets pay for mesh rectangularity
            get_registry().histogram("pinot_mesh_pad_waste_pct").observe(
                entry[1].pad_waste_pct)
        return entry[1]

    def _collective_ms(self, nelems: int) -> float:
        """Measured-once estimate of one mesh psum over `nelems` f32 elements
        (pow2-bucketed), the `collectiveMs` attached to mesh results. XLA
        fuses the collective into the fused-scan kernel, so the real launch
        cannot time it in isolation; a standalone shard_map psum of the same
        payload is the honest proxy."""
        if self.n_devices <= 1 or nelems <= 0:
            return 0.0
        bucket = 1 << (max(int(nelems), 1) - 1).bit_length()
        key = (id(self.mesh), self.n_devices, bucket)
        est = _COLLECTIVE_BENCH.get(key)
        if est is None:
            P = jax.sharding.PartitionSpec
            if hasattr(jax, "shard_map"):
                shard_map = jax.shard_map
            else:
                from jax.experimental.shard_map import shard_map
            fn = jax.jit(shard_map(
                lambda x: jax.lax.psum(x, SEGMENT_AXIS), mesh=self.mesh,
                in_specs=(P(),), out_specs=P()))
            arr = jax.device_put(np.zeros(bucket, np.float32),
                                 self._replicated)
            jax.block_until_ready(fn(arr))  # compile + warm outside the timer
            t0 = time.perf_counter()
            reps = 3
            for _ in range(reps):
                out = fn(arr)
            jax.block_until_ready(out)
            est = (time.perf_counter() - t0) / reps * 1000.0
            _COLLECTIVE_BENCH[key] = est
        return est

    def _finish_mesh_stats(self, res, outs, block: SegmentSetBlock):
        """Attach per-launch mesh accounting to a decoded result: worst
        per-device doc-load skew (`deviceSkewPct`, max-merged upstream) and
        the estimated cross-chip merge time (`collectiveMs`). Partials carry
        them in `SegmentResult.stats` (riding the wire to the broker merge);
        full results record into the request thread's active stats."""
        if self.n_devices <= 1:
            return res
        from ..query.reduce import SegmentResult
        est = self._collective_ms(
            sum(int(np.asarray(v).size) for v in outs.values()))
        if isinstance(res, SegmentResult):
            st = dict(res.stats or {})
            st[qstats.COLLECTIVE_MS] = st.get(qstats.COLLECTIVE_MS, 0.0) + est
            st[qstats.DEVICE_SKEW_PCT] = max(
                st.get(qstats.DEVICE_SKEW_PCT, 0.0), block.skew_pct)
            res.stats = st
        else:
            qstats.record(qstats.COLLECTIVE_MS, est)
            qstats.record_max(qstats.DEVICE_SKEW_PCT, block.skew_pct)
        return res

    def _dispatch_sharded(self, ctx: QueryContext, plan, segments, view=None,
                          valid_override=None, star=None, partial=False):
        """Dispatch the fused mesh kernel asynchronously.

        Returns (device outputs, decode) where decode(host_outs) -> ResultTable
        (or a SegmentResult partial when `partial=True`); the
        caller chooses when to pay the fetch round trip (one query vs a batch).
        `valid_override` replaces the block's all-true validity (stacked
        star-tree record masks); `star` = (original ctx, StarSetPlan) makes
        decode reassemble slot states into the original aggregations."""
        p = self._prepare_sharded(ctx, plan, segments, view, valid_override,
                                  star, partial)
        fn = self._get_shard_kernel(p.spec, p.s_pad, p.rows)
        return fn(p.inputs), p.decode

    def _prepare_star(self, ctx: QueryContext, sp: "StarSetPlan",
                      partial=True):
        s_pad = pad_slots(len(sp.views), self.n_devices)
        # build (or fetch) the block FIRST so the stacked record masks land in
        # the same placement slots as the record-table columns
        block = self._block_for(sp.views, None, s_pad)
        valid = np.zeros((s_pad, block.rows), dtype=bool)
        for i, p in enumerate(sp.plans):
            m = np.asarray(p.record_mask, dtype=bool)
            valid[block.slots[i], :len(m)] = m[:block.rows]
        valid_dev = jax.device_put(valid, block._sharded)
        return self._prepare_sharded(sp.plans[0].ctx2, sp.plan2, sp.views,
                                     valid_override=valid_dev,
                                     star=(ctx, sp), partial=partial)

    def _mesh_fused_cols(self, plan, segments,
                         view) -> Tuple[Tuple[str, str], ...]:
        """Dict value columns the stacked kernel decodes in-register
        ((col, "dict") KernelSpec routing) instead of reading a
        host-materialized decoded HBM column.

        Aligned sets only — a merged view remaps ids into the GLOBAL
        dictionary space, which the per-segment LUT stack cannot decode.
        FOR forms stay single-device: per-segment bases cannot ride the
        replicated iscal stream. Ineligible columns (multi-value, raw, or
        over `fused_lut_cap`) simply keep the decoded path — there is no
        separate staged mode on the mesh, fusion here only removes the
        decode materialization."""
        from ..engine.calibrate import get_caps
        from ..query.executor import _plan_vals_cols
        caps = get_caps()
        enabled = caps.fused_enabled if self.fused_enabled is None \
            else self.fused_enabled
        if not enabled or view is not None:
            return ()
        fused = []
        for c in sorted(_plan_vals_cols(plan)):
            readers = [s.column(c) for s in segments]
            if all(r.has_dictionary
                   and not getattr(r, "is_multi_value", False)
                   for r in readers) \
                    and max(lut_size(r.cardinality)
                            for r in readers) <= caps.fused_lut_cap:
                fused.append((c, "dict"))
        return tuple(fused)

    def _prepare_sharded(self, ctx: QueryContext, plan, segments, view=None,
                         valid_override=None, star=None,
                         partial=False) -> PreparedDispatch:
        """Plan-shape + runtime-input construction WITHOUT the kernel launch
        (the separable front half of `_dispatch_sharded`)."""
        build_device_geometry(plan)
        agg_specs = []
        distinct_lut_sizes: Dict[int, int] = {}
        agg_luts: Dict[str, jnp.ndarray] = {}

        s_pad = pad_slots(len(segments), self.n_devices)
        block = self._block_for(segments, view, s_pad)

        for i, agg in enumerate(plan.aggs):
            agg_specs.append((agg, agg.device_outputs))
            if "distinct" in agg.device_outputs:
                # plan.segment is the merged view on the unaligned path, so this is
                # the GLOBAL cardinality there (ids arrive remapped)
                distinct_lut_sizes[i] = lut_size(plan.segment.column(agg.arg.name).cardinality)

        from ..query.executor import _mv_lut_cols
        # star-tree record tables dispatch pre-decoded (their views are not
        # plain segment readers); everything else may fuse
        fused_cols = () if star is not None \
            else self._mesh_fused_cols(plan, segments, view)
        spec = KernelSpec(plan.filter_prog, plan.group_cols, plan.num_keys_pad,
                          tuple(agg_specs), distinct_lut_sizes, block.rows,
                          mv_cols=_mv_lut_cols(plan, plan.segment),
                          fused_cols=fused_cols)

        # -- gather runtime inputs ------------------------------------
        # ids only where dict ids are semantically needed (group keys, interval/LUT
        # filters, distinct); everything value-like reads pre-decoded HBM columns.
        ids_cols, vals_cols, nulls_cols = set(plan.group_cols), set(), set()
        luts, iscal, fscal = [], [], []
        has_docsets = False
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                ids_cols.add(leaf.col)
                if leaf.intervals is not None:
                    for lo, hi in leaf.intervals:
                        iscal.extend((lo, hi))
                else:
                    luts.append(self._const(leaf.lut))
            elif isinstance(leaf, CmpLeaf):
                vals_cols.update(identifiers_in(leaf.expr))
                (iscal if leaf.is_int else fscal).extend(leaf.operands)
            elif isinstance(leaf, NullLeaf):
                nulls_cols.add(leaf.col)
            else:
                has_docsets = True
        docsets: Tuple = ()
        if has_docsets:
            docsets = self._stacked_docsets(ctx, plan, segments, block)
        for i, agg in enumerate(plan.aggs):
            if "distinct" in agg.device_outputs:
                ids_cols.add(agg.arg.name)
            elif agg.arg is not None and not (isinstance(agg.arg, Identifier)
                                              and agg.arg.name == "*"):
                vals_cols.update(identifiers_in(agg.arg))

        iscal_np = np.asarray(iscal, dtype=np.int32)
        fscal_np = np.asarray(fscal, dtype=np.float32)
        # fused dict columns ship their per-segment LUT stack via vals and
        # their id column via ids; the kernel gathers in-register, so the
        # decoded HBM column is never built for them
        fused = dict(fused_cols)
        for c in vals_cols:
            if fused.get(c) == "dict":
                ids_cols.add(c)
        inputs = dict(
            ids={c: block.ids(c) for c in ids_cols},
            vals={c: block.dict_luts(c) if fused.get(c) == "dict"
                  else block.decoded(c) for c in vals_cols},
            luts=tuple(luts),
            iscal=self._const(iscal_np),
            fscal=self._const(fscal_np),
            nulls={c: block.null_mask(c) for c in nulls_cols},
            valid=block.valid if valid_override is None else valid_override,
            strides=self._const(np.asarray(plan.strides, dtype=np.int32)),
            agg_luts=agg_luts,
            docsets=docsets,
        )

        def decode(outs):
            return self._finish_mesh_stats(_decode_impl(outs), outs, block)

        def _decode_impl(outs):
            # replicated outputs decode exactly like the single-segment path;
            # plan.segment's dictionaries (segment[0] when aligned, the merged global
            # dictionaries otherwise) decode the dense keys.
            if star is not None:
                # stacked star-tree path: decode SLOT states (no trim — the
                # order-by refers to the ORIGINAL aggregations), reassemble
                # them into original-agg states, reduce with the original ctx
                from ..query.aggregates import make_agg
                from ..query.startree_exec import reassemble
                orig_ctx, sp = star
                if plan.group_cols:
                    seg_result = self._fallback._decode_group_partials(
                        plan, outs, trim_global=False)
                else:
                    seg_result = self._fallback._decode_scalar_partials(plan,
                                                                        outs)
                reassemble(sp.plans[0], seg_result)
                if partial:
                    return seg_result
                orig_aggs = [make_agg(f) for f in orig_ctx.aggregations]
                merged = merge_segment_results([seg_result], orig_aggs)
                group_exprs = ([e for e, _ in orig_ctx.select_items]
                               if orig_ctx.distinct else list(orig_ctx.group_by))
                return reduce_to_result(orig_ctx, merged, orig_aggs,
                                        group_exprs)
            if plan.group_cols:
                if not partial:
                    # vectorized dense decode for the common agg shapes:
                    # post-psum outputs are GLOBAL, so groups finalize
                    # straight to rows with no state dicts (the decode half
                    # of the high-cardinality group-by redesign — the Python
                    # per-group loop costs more than the fused kernel past
                    # ~10k groups; query/dense_reduce.py)
                    from ..query.dense_reduce import try_dense_decode
                    dense = try_dense_decode(ctx, plan, outs)
                    if dense is not None:
                        return dense
                if partial:
                    # high-cardinality server partial: keep the kernel's dense
                    # arrays as-is (reduce.DensePartial) instead of densifying
                    # 100k+ Python state dicts that the broker would re-hash
                    dense_partial = self._fallback._decode_dense_partial(
                        plan, outs)
                    if dense_partial is not None:
                        return dense_partial
                # an order-by trim is exact for a FULL result; a server
                # partial stays untrimmed — the broker merges every server's
                # groups before trimming
                seg_result = self._fallback._decode_group_partials(
                    plan, outs, trim_global=not partial)
            else:
                seg_result = self._fallback._decode_scalar_partials(plan, outs)
            if partial:
                return seg_result
            merged = merge_segment_results([seg_result], plan.aggs)
            group_exprs = ([e for e, _ in ctx.select_items] if ctx.distinct
                           else list(ctx.group_by))
            return reduce_to_result(ctx, merged, plan.aggs, group_exprs)

        sig = spec.signature()
        shape_key = ("agg", sig, id(block), s_pad, block.rows, id(self.mesh))
        # device operands are content-addressed (`_const`) or block-cached, so
        # object identity == content identity: two queries stack iff the same
        # executable reads the same device arrays (scalars ride the stack)
        operands = (tuple(id(a) for a in inputs["luts"]),
                    id(inputs["valid"]), id(inputs["strides"]),
                    tuple(id(d) for d in docsets))
        stackable = (star is None and valid_override is None and not docsets)
        stack_key = shape_key + operands
        dedupe_key = None if valid_override is not None else \
            stack_key + (iscal_np.tobytes(), fscal_np.tobytes())
        # device-side key-axis trim: a grouped server partial only ever decodes
        # the first num_keys_real entries, so padding rows never cross the relay
        trim = (plan.num_keys_pad, plan.num_keys_real) \
            if (partial and plan.group_cols and star is None) else (0, 0)
        return PreparedDispatch(
            kind="agg", spec=spec, inputs=inputs, s_pad=s_pad,
            rows=block.rows, stack_key=stack_key, dedupe_key=dedupe_key,
            stackable=stackable, decode=decode, iscal_np=iscal_np,
            fscal_np=fscal_np, trim_keys=trim)

    # ------------------------------------------------------------------
    def _prepare_topk(self, ctx: QueryContext, plan, segments):
        """Prepared device top-k for a served ORDER-BY-limit selection.

        Mirrors `ServerQueryExecutor._topk_candidates` eligibility over the
        STACKED segment set, dispatching the same fused `compute_topk` kernel
        (`kernels.topk_kernel`) over the block's [S_pad, rows] arrays so the
        candidate trim happens on device and only k+slack doc ids ship in the
        pipeline's batched fetch. Returns None -> host fallback."""
        from ..query.planner import _expr_device_ok
        from ..query.predicate import DocSetLeaf
        if not device_topk_screen(ctx):
            return None
        order = ctx.order_by[0]
        k = ctx.offset + ctx.limit
        seg0 = segments[0]
        from ..query.executor import topk_order_key_device_ok
        if any(not topk_order_key_device_ok(s, order.expr)
               for s in segments):
            return None
        col = order.expr.name
        if _refs_multi_value(ctx, seg0):
            return None  # MV select/filter cells keep the per-segment path
        lut_cols = []
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, CmpLeaf) and _expr_device_ok(leaf.expr, seg0):
                return None  # mask itself needs the host path
            if isinstance(leaf, DocSetLeaf):
                return None  # per-segment aux-index bitmaps: host path
            if isinstance(leaf, LutLeaf):
                lut_cols.append(leaf.col)
        if lut_cols and not aligned_dictionaries(segments, lut_cols):
            return None  # plan's id intervals only valid set-wide when aligned

        from ..engine.kernels import topk_kernel
        s_pad = pad_slots(len(segments), self.n_devices)
        block = self._block_for(segments, None, s_pad)
        spec = KernelSpec(plan.filter_prog, (), 1, (), {}, block.rows)

        ids_cols, vals_cols, nulls_cols = set(), {col}, set()
        luts, iscal, fscal = [], [], []
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                ids_cols.add(leaf.col)
                if leaf.intervals is not None:
                    for lo, hi in leaf.intervals:
                        iscal.extend((lo, hi))
                else:
                    luts.append(self._const(leaf.lut))
            elif isinstance(leaf, CmpLeaf):
                vals_cols.update(identifiers_in(leaf.expr))
                (iscal if leaf.is_int else fscal).extend(leaf.operands)
            elif isinstance(leaf, NullLeaf):
                nulls_cols.add(leaf.col)
        iscal_np = np.asarray(iscal, dtype=np.int32)
        fscal_np = np.asarray(fscal, dtype=np.float32)
        inputs = dict(
            ids={c: block.ids(c) for c in ids_cols},
            vals={c: block.decoded(c) for c in vals_cols},
            luts=tuple(luts),
            iscal=self._const(iscal_np),
            fscal=self._const(fscal_np),
            nulls={c: block.null_mask(c) for c in nulls_cols},
            valid=block.valid,
        )
        slack = ServerQueryExecutor.TOPK_SLACK
        fn, kk = topk_kernel(spec, order.expr, order.desc, k + slack,
                             total_rows=s_pad * block.rows)

        def launch(inp=inputs):
            return fn(inp["ids"], inp["vals"], inp["luts"], inp["iscal"],
                      inp["fscal"], inp["nulls"], inp["valid"], ())

        decode = self._make_topk_decode(ctx, plan, segments, block, k, kk)
        static = ("topk", plan.filter_prog.signature(), repr(order.expr),
                  order.desc, kk, id(block), s_pad, block.rows)
        return PreparedDispatch(
            kind="topk", spec=static, inputs=inputs, s_pad=s_pad,
            rows=block.rows, stack_key=static,
            dedupe_key=static + (tuple(id(a) for a in luts),
                                 iscal_np.tobytes(), fscal_np.tobytes()),
            stackable=False, decode=decode, launch=launch)

    def _make_topk_decode(self, ctx: QueryContext, plan, segments, block,
                          k: int, kk: int):
        """decode(host outs) for the served top-k: gather the few candidate
        rows from the segments on host and ship a 'selection' partial whose
        exact sort keys the broker re-sorts (f32 only decided the CANDIDATE
        set, same contract as the single-segment `_topk_candidates`)."""
        from ..cluster.device_server import DEVICE_FALLBACK
        from ..engine.expr import eval_expr as _eval
        from ..query.executor import _is_const
        from ..query.reduce import SegmentResult

        def decode(outs):
            count = int(outs["count"])
            if int(outs["nanMatches"]) > 0:
                # NaN sort keys displace candidates unpredictably vs the
                # Python sort: parity demands the host path decide
                return DEVICE_FALLBACK
            idx = np.asarray(outs["idx"])
            ok = np.asarray(outs["ok"])
            keep = min(kk, count)
            idx, ok = idx[:keep], ok[:keep]
            idx = idx[ok]
            # block rows are placement SLOTS (chip-aware, not identity order):
            # map back to segment indices before the per-segment gather
            seg_i = block.slot_to_seg[idx // block.rows]
            row_i = idx % block.rows
            if len(idx) < min(k, count):
                return DEVICE_FALLBACK  # -inf ties displaced matches
            # gather candidates per segment (order is irrelevant: the broker
            # sorts the merged partial by the exact sort keys below)
            perm = np.lexsort((row_i, seg_i))
            seg_i, row_i = seg_i[perm], row_i[perm]
            needed = set()
            for e, _ in ctx.select_items:
                needed.update(identifiers_in(e))
            for o in ctx.order_by:
                needed.update(identifiers_in(o.expr))
            env = {}
            for c in needed:
                parts = []
                for s in np.unique(seg_i):
                    rows_in = row_i[seg_i == s]
                    parts.append(np.asarray(
                        segments[s].column(c).values()[rows_in]))
                env[c] = np.concatenate(parts) if parts else np.empty(0)
            n = len(row_i)
            out_cols = [np.asarray(_eval(e, env, np)) if not _is_const(e)
                        else np.full(n, _eval(e, env, np), dtype=object)
                        for e, _ in ctx.select_items]

            def _cell(v):
                if isinstance(v, np.generic):
                    return v.item()
                if isinstance(v, np.ndarray):
                    return v.tolist()
                return v
            rows = [tuple(_cell(c[i]) for c in out_cols) for i in range(n)]
            sort_cols = [np.asarray(_eval(o.expr, env, np))
                         for o in ctx.order_by]
            sort_keys = [tuple(c[i].item() if isinstance(c[i], np.generic)
                               else c[i] for c in sort_cols)
                         for i in range(n)]
            return SegmentResult("selection", rows=rows, sort_keys=sort_keys,
                                 num_docs_scanned=count)

        return decode

    # ------------------------------------------------------------------
    def _get_shard_kernel(self, spec: KernelSpec, s_pad: int, rows: int,
                          batch: int = 0):
        cache_key = (spec.signature(), self.n_devices, s_pad, rows,
                     id(self.mesh), batch)
        fn = _SHARD_KERNEL_CACHE.get(cache_key)
        if fn is None:
            qstats.record(qstats.COMPILE_CACHE_MISSES)
            get_registry().counter("pinot_kernel_cache_misses").inc()
            # same first-call compile fence as the single-device cache: the
            # cold call's wall (trace + compile + first run) lands in the
            # compile histogram, not in whichever query drew the short straw
            fn = _fence_first_call(self._build_shard_kernel(spec, batch))
            _SHARD_KERNEL_CACHE[cache_key] = fn
        else:
            qstats.record(qstats.COMPILE_CACHE_HITS)
            get_registry().counter("pinot_kernel_cache_hits").inc()
        return fn

    def _build_shard_kernel(self, spec: KernelSpec, batch: int = 0):
        """jit(shard_map(fused scan body + per-output ICI collective)).

        The body is the SAME gather/scatter-free kernel as the single-device path
        (`kernels.make_kernel_body`); partials agree on dense keys across devices, so
        each output merges with exactly one collective. Low-cardinality (and
        min/max) outputs psum/pmin/pmax to a replicated result as before;
        HIGH-cardinality dense sum outputs (DensePartial group-bys, distinct
        presence matrices) instead reduce-scatter (`psum_scatter`): each device
        keeps 1/n of the key space, the overflow bucket is dropped on device,
        and the fetch reassembles the shards host-side — a pure memcpy, zero
        host-side value merges, at half the collective bandwidth of a psum.

        Output names/shapes are only known from the body, so the shard_map is
        constructed LAZILY at the first invocation: `jax.eval_shape` over the
        per-shard input shapes learns the outputs, which decides each one's
        collective and out_spec. The first call runs inside the compile fence,
        so the extra trace lands in `compileMs` like any cold compile.

        `batch > 0` builds the STACKED variant: iscal/fscal arrive [B, n] and
        the body scans over them — B same-shape queries in one launch, reading
        the HBM columns once per scan step but paying ONE dispatch."""
        from ..engine.kernels import combine_collective, make_kernel_body
        body = make_kernel_body(spec)
        P = jax.sharding.PartitionSpec
        ax = SEGMENT_AXIS
        n = self.n_devices
        sharded, repl = P(ax), P()

        in_specs = (dict(ids=sharded, vals=sharded, luts=repl, iscal=repl,
                         fscal=repl, nulls=sharded, valid=sharded, strides=repl,
                         agg_luts=sharded, docsets=sharded),)
        _REPL_KEYS = ("luts", "iscal", "fscal", "strides")

        num_seg = spec.num_keys_pad + 1
        pad = spec.num_keys_pad
        key_dim = 1 if batch else 0  # scan stacks a leading batch axis

        if batch:
            def call_body(inputs):
                def step(carry, scal):
                    i_s, f_s = scal
                    out = body(inputs["ids"], inputs["vals"], inputs["luts"],
                               i_s, f_s, inputs["nulls"], inputs["valid"],
                               inputs["strides"], inputs["agg_luts"],
                               inputs["docsets"])
                    return carry, out
                _, outs = jax.lax.scan(step, 0,
                                       (inputs["iscal"], inputs["fscal"]))
                return outs
        else:
            def call_body(inputs):
                return body(inputs["ids"], inputs["vals"], inputs["luts"],
                            inputs["iscal"], inputs["fscal"], inputs["nulls"],
                            inputs["valid"], inputs["strides"],
                            inputs["agg_luts"], inputs["docsets"])

        def scatterable(name, shape) -> bool:
            return (n > 1 and pad >= SCATTER_MIN_KEYS and pad % n == 0
                    and len(shape) > key_dim and shape[key_dim] == num_seg
                    and not name.endswith((".min", ".max")))

        if hasattr(jax, "shard_map"):
            shard_map = jax.shard_map
        else:  # jax < 0.5: shard_map not yet promoted out of experimental
            from jax.experimental.shard_map import shard_map

        built: Dict[str, Any] = {}

        def fn(inputs):
            compiled = built.get("fn")
            if compiled is None:
                # learn output names/shapes from the per-shard input shapes
                shard_in = {
                    key: jax.tree_util.tree_map(
                        lambda x, sh=(key not in _REPL_KEYS):
                        jax.ShapeDtypeStruct(
                            ((x.shape[0] // n,) + tuple(x.shape[1:]))
                            if sh and x.ndim else tuple(x.shape), x.dtype),
                        val)
                    for key, val in inputs.items()}
                out_shapes = jax.eval_shape(call_body, shard_in)
                scat = {name for name, s in out_shapes.items()
                        if scatterable(name, s.shape)}

                def shard_body(sin):
                    outs = call_body(sin)
                    res = {}
                    for name, v in outs.items():
                        if name in scat:
                            core = v[:, :pad] if batch else v[:pad]
                            res[name] = jax.lax.psum_scatter(
                                core, ax, scatter_dimension=key_dim,
                                tiled=True)
                        else:
                            res[name] = combine_collective(name, v, ax)
                    return res

                out_specs = {
                    name: ((P(None, ax) if batch else P(ax))
                           if name in scat else repl)
                    for name in out_shapes}
                built["fn"] = jax.jit(shard_map(
                    shard_body, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs))
                compiled = built["fn"]
            return compiled(inputs)

        return fn


