"""Sharded multi-segment execution: scatter segments over a mesh, psum-combine partials.

The TPU-native analog of the reference's entire distributed query data plane for
aggregations (SURVEY.md §2.11): where the reference scatters segments to servers over
Netty (`QueryRouter.submitQuery`), runs per-segment operator trees on thread pools
(`BaseCombineOperator`), and merges DataTables on the broker
(`GroupByDataTableReducer`), here the segment axis IS a mesh axis:

    stacked columns [S, P] --shard_map--> per-device fused scan --psum/pmin/pmax--> result

The fast path requires segments with *aligned dictionaries* (`dictHash` equal — built via
`segment.writer.build_aligned_segments` or a shared ingestion dictionary): dense group
keys and LUT ids then agree across devices, so partial aggregates combine with one ICI
collective and no host-side value merge. Unaligned segment sets fall back to the
per-segment executor + value-keyed host merge, which is always correct.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.datablock import lut_size, padded_rows
from ..engine.kernels import KernelSpec
from ..query.aggregates import make_agg
from ..query.context import QueryContext, compile_query
from ..query.executor import ServerQueryExecutor
from ..query.planner import build_device_geometry, plan_segment
from ..query.predicate import CmpLeaf, LutLeaf, NullLeaf
from ..query.reduce import merge_segment_results, reduce_to_result
from ..query.result import ResultTable
from ..segment.reader import ImmutableSegment
from ..sql.ast import Identifier, identifiers_in
from .mesh import SEGMENT_AXIS, default_mesh

_SHARD_KERNEL_CACHE: Dict[Tuple, object] = {}


def aligned_dictionaries(segments: Sequence[ImmutableSegment], cols: Sequence[str]) -> bool:
    """True iff every column in `cols` has identical dictionaries across segments."""
    for col in cols:
        hashes = set()
        for seg in segments:
            reader = seg.column(col)
            if not reader.has_dictionary:
                return False
            h = reader.meta.get("dictHash")
            if h is None:
                return False
            hashes.add((h, reader.cardinality))
        if len(hashes) > 1:
            return False
    return True


class SegmentSetBlock:
    """Stacked device columns for an aligned segment set: [S_pad, P] arrays.

    Arrays are `device_put` once with their final mesh sharding (segment axis sharded,
    decode tables replicated) so repeated queries dispatch with zero re-shard copies —
    the analog of the reference's segment-resident mmap buffers being scan-ready.
    """

    def __init__(self, segments: Sequence[ImmutableSegment], s_pad: int,
                 mesh: jax.sharding.Mesh):
        self.segments = list(segments)
        self.s_pad = s_pad
        self.rows = max(padded_rows(s.num_docs) for s in segments)
        P = jax.sharding.PartitionSpec
        self._sharded = jax.sharding.NamedSharding(mesh, P(SEGMENT_AXIS))
        self._replicated = jax.sharding.NamedSharding(mesh, P())
        self._cache: Dict[Tuple[str, str], jnp.ndarray] = {}

    def _stack(self, kind: str, col: str, fill, per_seg) -> jnp.ndarray:
        key = (kind, col)
        if key not in self._cache:
            first = np.asarray(per_seg(self.segments[0]))
            out = np.full((self.s_pad, self.rows), fill, dtype=first.dtype)
            for i, seg in enumerate(self.segments):
                arr = np.asarray(per_seg(seg))
                out[i, :len(arr)] = arr
            self._cache[key] = jax.device_put(out, self._sharded)
        return self._cache[key]

    def ids(self, col: str) -> jnp.ndarray:
        card = self.segments[0].column(col).cardinality
        return self._stack("ids", col, np.int32(card),
                           lambda s: np.asarray(s.column(col).fwd).astype(np.int32))

    def raw(self, col: str) -> jnp.ndarray:
        from ..engine.datablock import _narrow
        return self._stack("raw", col, 0,
                           lambda s: _narrow(np.asarray(s.column(col).fwd)))

    def decoded(self, col: str) -> jnp.ndarray:
        """Decoded numeric values regardless of encoding, host-materialized ONCE.

        Dict decode never happens on device: the relay serializes each device gather
        into an extra host round trip per dispatch, so queries read pre-decoded HBM
        columns (the `DataFetcher.java:47` value-buffer analog)."""
        from ..engine.datablock import _narrow

        def per_seg(s):
            reader = s.column(col)
            arr = np.asarray(reader.fwd)
            if reader.has_dictionary:
                vals = _narrow(np.asarray(reader.dictionary.values))
                return vals[arr.astype(np.int64)]
            return _narrow(arr)

        return self._stack("decoded", col, 0, per_seg)

    def hll(self, col: str, p: int):
        """Per-doc (bucket, rank) HLL update vectors, host-materialized once."""
        from ..query.executor import _hll_luts

        def bucket_per_seg(s):
            reader = s.column(col)
            bucket_lut, _ = _hll_luts(reader, p)
            return bucket_lut[np.asarray(reader.fwd).astype(np.int64)]

        def rank_per_seg(s):
            reader = s.column(col)
            _, rank_lut = _hll_luts(reader, p)
            return rank_lut[np.asarray(reader.fwd).astype(np.int64)]

        # padding rows: bucket = 2**p overflow slot, rank 0
        return (self._stack(f"hllb{p}", col, np.int32(1 << p), bucket_per_seg),
                self._stack(f"hllr{p}", col, np.int32(0), rank_per_seg))

    def null_mask(self, col: str) -> jnp.ndarray:
        def per_seg(s):
            nb = s.column(col).null_bitmap
            return nb if nb is not None else np.zeros(s.num_docs, dtype=bool)
        return self._stack("null", col, False, per_seg)

    @property
    def valid(self) -> jnp.ndarray:
        def per_seg(s):
            return np.ones(s.num_docs, dtype=bool)
        return self._stack("valid", "", False, per_seg)


class MeshQueryExecutor:
    """Executes aggregation queries over segment sets sharded across a device mesh."""

    def __init__(self, mesh: Optional[jax.sharding.Mesh] = None):
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = self.mesh.devices.size
        self._fallback = ServerQueryExecutor()
        self._set_blocks: Dict[Tuple[str, ...], SegmentSetBlock] = {}
        self._replicated = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec())
        # content-addressed cache of replicated query constants (LUTs, scalars, strides):
        # repeated queries dispatch with zero host->device transfers
        self._const_cache: Dict[bytes, jnp.ndarray] = {}

    def _const(self, arr: np.ndarray) -> jnp.ndarray:
        key = arr.dtype.str.encode() + arr.tobytes()
        dev = self._const_cache.get(key)
        if dev is None:
            if len(self._const_cache) > 4096:
                self._const_cache.clear()
            dev = jax.device_put(arr, self._replicated)
            self._const_cache[key] = dev
        return dev

    # ------------------------------------------------------------------
    def execute(self, segments: Sequence[ImmutableSegment],
                query: Union[str, QueryContext], schema=None) -> ResultTable:
        ctx = compile_query(query, schema or segments[0].schema) \
            if isinstance(query, str) else query
        plan = plan_segment(ctx, segments[0])
        if plan.kind != "device" or not self._alignable(plan, segments):
            return self._fallback.execute(segments, ctx)
        return self._execute_sharded(ctx, plan, segments)

    def _alignable(self, plan, segments) -> bool:
        """Dictionary alignment is only needed where dict IDS are shared across
        devices: dense group keys, id-interval/LUT filters, and exact-distinct
        presence vectors. Decoded value columns (CmpLeaf expressions, SUM/MIN/MAX
        args) and HLL (bucket, rank) vectors are materialized per segment against its
        OWN dictionary, so mixed segment sets still ride the mesh kernel for them."""
        from ..query.predicate import DocSetLeaf
        if any(isinstance(l, DocSetLeaf) for l in plan.filter_prog.leaves):
            return False  # doc-set masks are per-segment; plan[0] can't be reused
        cols = set(plan.group_cols)
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                cols.add(leaf.col)
        for agg in plan.aggs:
            if "distinct" in agg.device_outputs:
                cols.add(agg.arg.name)
        return aligned_dictionaries(segments, cols)

    # ------------------------------------------------------------------
    def _execute_sharded(self, ctx: QueryContext, plan, segments) -> ResultTable:
        outs_dev, decode = self._dispatch_sharded(ctx, plan, segments)
        return decode(jax.device_get(outs_dev))  # one host sync for all partials

    def execute_many(self, segments: Sequence[ImmutableSegment],
                     queries: Sequence[Union[str, QueryContext]],
                     schema=None) -> List[ResultTable]:
        """Pipelined batch execution: dispatch every query's kernel asynchronously,
        then fetch ALL results with ONE device_get round trip.

        The relay charges one full host round trip per synchronization (~65ms) no
        matter how much work it covers, so a serving loop that drains its queue
        through this path amortizes the round trip across the batch — the TPU analog
        of the reference broker pipelining queries over its Netty channels."""
        pending: List = []  # (index, outs_dev, decode) | (index, ResultTable)
        for qi, query in enumerate(queries):
            ctx = compile_query(query, schema or segments[0].schema) \
                if isinstance(query, str) else query
            plan = plan_segment(ctx, segments[0])
            if plan.kind != "device" or not self._alignable(plan, segments):
                pending.append((qi, self._fallback.execute(segments, ctx)))
            else:
                outs_dev, decode = self._dispatch_sharded(ctx, plan, segments)
                pending.append((qi, outs_dev, decode))
        fetched = jax.device_get([p[1] for p in pending if len(p) == 3])
        results: List[Optional[ResultTable]] = [None] * len(queries)
        it = iter(fetched)
        for p in pending:
            results[p[0]] = p[1] if len(p) == 2 else p[2](next(it))
        return results

    def _dispatch_sharded(self, ctx: QueryContext, plan, segments):
        """Dispatch the fused mesh kernel asynchronously.

        Returns (device outputs, decode) where decode(host_outs) -> ResultTable; the
        caller chooses when to pay the fetch round trip (one query vs a batch)."""
        build_device_geometry(plan)
        agg_specs = []
        distinct_lut_sizes: Dict[int, int] = {}
        hll_params: Dict[int, int] = {}
        agg_luts: Dict[str, jnp.ndarray] = {}

        s_pad = -(-len(segments) // self.n_devices) * self.n_devices
        key = tuple(s.path for s in segments)
        block = self._set_blocks.get(key)
        if block is None or block.s_pad != s_pad:
            block = SegmentSetBlock(segments, s_pad, self.mesh)
            self._set_blocks[key] = block

        for i, agg in enumerate(plan.aggs):
            agg_specs.append((agg, agg.device_outputs))
            if "distinct" in agg.device_outputs:
                distinct_lut_sizes[i] = lut_size(segments[0].column(agg.arg.name).cardinality)
            if "hll" in agg.device_outputs:
                hll_params[i] = agg.p
                bucket, rank = block.hll(agg.arg.name, agg.p)
                agg_luts[f"{i}.bucket"] = bucket
                agg_luts[f"{i}.rank"] = rank

        spec = KernelSpec(plan.filter_prog, plan.group_cols, plan.num_keys_pad,
                          tuple(agg_specs), distinct_lut_sizes, block.rows, hll_params)

        # -- gather runtime inputs ------------------------------------
        # ids only where dict ids are semantically needed (group keys, interval/LUT
        # filters, distinct); everything value-like reads pre-decoded HBM columns.
        ids_cols, vals_cols, nulls_cols = set(plan.group_cols), set(), set()
        luts, iscal, fscal = [], [], []
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                ids_cols.add(leaf.col)
                if leaf.intervals is not None:
                    for lo, hi in leaf.intervals:
                        iscal.extend((lo, hi))
                else:
                    luts.append(self._const(leaf.lut))
            elif isinstance(leaf, CmpLeaf):
                vals_cols.update(identifiers_in(leaf.expr))
                (iscal if leaf.is_int else fscal).extend(leaf.operands)
            elif isinstance(leaf, NullLeaf):
                nulls_cols.add(leaf.col)
        for i, agg in enumerate(plan.aggs):
            if "distinct" in agg.device_outputs:
                ids_cols.add(agg.arg.name)
            elif "hll" in agg.device_outputs:
                pass  # per-doc (bucket, rank) vectors already in agg_luts
            elif agg.arg is not None and not (isinstance(agg.arg, Identifier)
                                              and agg.arg.name == "*"):
                vals_cols.update(identifiers_in(agg.arg))

        inputs = dict(
            ids={c: block.ids(c) for c in ids_cols},
            vals={c: block.decoded(c) for c in vals_cols},
            luts=tuple(luts),
            iscal=self._const(np.asarray(iscal, dtype=np.int32)),
            fscal=self._const(np.asarray(fscal, dtype=np.float32)),
            nulls={c: block.null_mask(c) for c in nulls_cols},
            valid=block.valid,
            strides=self._const(np.asarray(plan.strides, dtype=np.int32)),
            agg_luts=agg_luts,
        )

        fn = self._get_shard_kernel(spec, s_pad, block.rows)
        outs_dev = fn(inputs)

        def decode(outs) -> ResultTable:
            # replicated outputs decode exactly like the single-segment path;
            # group/distinct dictionaries are aligned, so segment[0]'s dictionaries
            # decode the global dense keys.
            if plan.group_cols:
                seg_result = self._fallback._decode_group_partials(plan, outs)
            else:
                seg_result = self._fallback._decode_scalar_partials(plan, outs)
            merged = merge_segment_results([seg_result], plan.aggs)
            group_exprs = ([e for e, _ in ctx.select_items] if ctx.distinct
                           else list(ctx.group_by))
            return reduce_to_result(ctx, merged, plan.aggs, group_exprs)

        return outs_dev, decode

    # ------------------------------------------------------------------
    def _get_shard_kernel(self, spec: KernelSpec, s_pad: int, rows: int):
        cache_key = (spec.signature(), self.n_devices, s_pad, rows, id(self.mesh))
        fn = _SHARD_KERNEL_CACHE.get(cache_key)
        if fn is None:
            fn = self._build_shard_kernel(spec)
            _SHARD_KERNEL_CACHE[cache_key] = fn
        return fn

    def _build_shard_kernel(self, spec: KernelSpec):
        """jit(shard_map(fused scan body + per-output ICI collective)).

        The body is the SAME gather/scatter-free kernel as the single-device path
        (`kernels.make_kernel_body`); partials agree on dense keys across devices, so
        each output merges with exactly one collective (psum / pmin / pmax)."""
        from ..engine.kernels import combine_collective, make_kernel_body
        body = make_kernel_body(spec)
        P = jax.sharding.PartitionSpec
        ax = SEGMENT_AXIS
        sharded, repl = P(ax), P()

        in_specs = (dict(ids=sharded, vals=sharded, luts=repl, iscal=repl,
                         fscal=repl, nulls=sharded, valid=sharded, strides=repl,
                         agg_luts=sharded),)

        def shard_body(inputs):
            out = body(inputs["ids"], inputs["vals"], inputs["luts"], inputs["iscal"],
                       inputs["fscal"], inputs["nulls"], inputs["valid"],
                       inputs["strides"], inputs["agg_luts"], ())
            return {k: combine_collective(k, v, ax) for k, v in out.items()}

        return jax.jit(jax.shard_map(shard_body, mesh=self.mesh,
                                     in_specs=in_specs, out_specs=repl))


