"""Sharded multi-segment execution: scatter segments over a mesh, psum-combine partials.

The TPU-native analog of the reference's entire distributed query data plane for
aggregations (SURVEY.md §2.11): where the reference scatters segments to servers over
Netty (`QueryRouter.submitQuery`), runs per-segment operator trees on thread pools
(`BaseCombineOperator`), and merges DataTables on the broker
(`GroupByDataTableReducer`), here the segment axis IS a mesh axis:

    stacked columns [S, P] --shard_map--> per-device fused scan --psum/pmin/pmax--> result

The fast path requires segments with *aligned dictionaries* (`dictHash` equal — built via
`segment.writer.build_aligned_segments` or a shared ingestion dictionary): dense group
keys and LUT ids then agree across devices, so partial aggregates combine with one ICI
collective and no host-side value merge. Unaligned segment sets fall back to the
per-segment executor + value-keyed host merge, which is always correct.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.datablock import lut_size, padded_rows
from ..engine.kernels import KernelSpec, _make_mask_fn
from ..query.aggregates import make_agg
from ..query.context import QueryContext, compile_query
from ..query.executor import ServerQueryExecutor
from ..query.planner import build_device_geometry, plan_segment
from ..query.predicate import CmpLeaf, LutLeaf, NullLeaf
from ..query.reduce import merge_segment_results, reduce_to_result
from ..query.result import ResultTable
from ..segment.reader import ImmutableSegment
from ..sql.ast import Identifier, identifiers_in
from .mesh import SEGMENT_AXIS, default_mesh

_SHARD_KERNEL_CACHE: Dict[Tuple, object] = {}


def aligned_dictionaries(segments: Sequence[ImmutableSegment], cols: Sequence[str]) -> bool:
    """True iff every column in `cols` has identical dictionaries across segments."""
    for col in cols:
        hashes = set()
        for seg in segments:
            reader = seg.column(col)
            if not reader.has_dictionary:
                return False
            h = reader.meta.get("dictHash")
            if h is None:
                return False
            hashes.add((h, reader.cardinality))
        if len(hashes) > 1:
            return False
    return True


class SegmentSetBlock:
    """Stacked device columns for an aligned segment set: [S_pad, P] arrays.

    Arrays are `device_put` once with their final mesh sharding (segment axis sharded,
    decode tables replicated) so repeated queries dispatch with zero re-shard copies —
    the analog of the reference's segment-resident mmap buffers being scan-ready.
    """

    def __init__(self, segments: Sequence[ImmutableSegment], s_pad: int,
                 mesh: jax.sharding.Mesh):
        self.segments = list(segments)
        self.s_pad = s_pad
        self.rows = max(padded_rows(s.num_docs) for s in segments)
        P = jax.sharding.PartitionSpec
        self._sharded = jax.sharding.NamedSharding(mesh, P(SEGMENT_AXIS))
        self._replicated = jax.sharding.NamedSharding(mesh, P())
        self._cache: Dict[Tuple[str, str], jnp.ndarray] = {}

    def _stack(self, kind: str, col: str, fill, per_seg) -> jnp.ndarray:
        key = (kind, col)
        if key not in self._cache:
            first = np.asarray(per_seg(self.segments[0]))
            out = np.full((self.s_pad, self.rows), fill, dtype=first.dtype)
            for i, seg in enumerate(self.segments):
                arr = np.asarray(per_seg(seg))
                out[i, :len(arr)] = arr
            self._cache[key] = jax.device_put(out, self._sharded)
        return self._cache[key]

    def ids(self, col: str) -> jnp.ndarray:
        card = self.segments[0].column(col).cardinality
        return self._stack("ids", col, np.int32(card),
                           lambda s: np.asarray(s.column(col).fwd).astype(np.int32))

    def raw(self, col: str) -> jnp.ndarray:
        from ..engine.datablock import _narrow
        return self._stack("raw", col, 0,
                           lambda s: _narrow(np.asarray(s.column(col).fwd)))

    def decode_table(self, col: str) -> jnp.ndarray:
        key = ("decode", col)
        if key not in self._cache:
            from ..engine.datablock import _narrow
            reader = self.segments[0].column(col)
            vals = _narrow(np.asarray(reader.dictionary.values))
            out = np.zeros(lut_size(reader.cardinality), dtype=vals.dtype)
            out[:len(vals)] = vals
            self._cache[key] = jax.device_put(out, self._replicated)
        return self._cache[key]

    def null_mask(self, col: str) -> jnp.ndarray:
        def per_seg(s):
            nb = s.column(col).null_bitmap
            return nb if nb is not None else np.zeros(s.num_docs, dtype=bool)
        return self._stack("null", col, False, per_seg)

    @property
    def valid(self) -> jnp.ndarray:
        def per_seg(s):
            return np.ones(s.num_docs, dtype=bool)
        return self._stack("valid", "", False, per_seg)


class MeshQueryExecutor:
    """Executes aggregation queries over segment sets sharded across a device mesh."""

    def __init__(self, mesh: Optional[jax.sharding.Mesh] = None):
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = self.mesh.devices.size
        self._fallback = ServerQueryExecutor()
        self._set_blocks: Dict[Tuple[str, ...], SegmentSetBlock] = {}
        self._replicated = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec())
        # content-addressed cache of replicated query constants (LUTs, scalars, strides):
        # repeated queries dispatch with zero host->device transfers
        self._const_cache: Dict[bytes, jnp.ndarray] = {}

    def _const(self, arr: np.ndarray) -> jnp.ndarray:
        key = arr.dtype.str.encode() + arr.tobytes()
        dev = self._const_cache.get(key)
        if dev is None:
            if len(self._const_cache) > 4096:
                self._const_cache.clear()
            dev = jax.device_put(arr, self._replicated)
            self._const_cache[key] = dev
        return dev

    # ------------------------------------------------------------------
    def execute(self, segments: Sequence[ImmutableSegment],
                query: Union[str, QueryContext], schema=None) -> ResultTable:
        ctx = compile_query(query, schema or segments[0].schema) \
            if isinstance(query, str) else query
        plan = plan_segment(ctx, segments[0])
        if plan.kind != "device" or not self._alignable(plan, segments):
            return self._fallback.execute(segments, ctx)
        return self._execute_sharded(ctx, plan, segments)

    def _alignable(self, plan, segments) -> bool:
        from ..query.predicate import DocSetLeaf
        if any(isinstance(l, DocSetLeaf) for l in plan.filter_prog.leaves):
            return False  # doc-set masks are per-segment; plan[0] can't be reused
        cols = set(plan.group_cols)
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                cols.add(leaf.col)
            elif isinstance(leaf, CmpLeaf):
                cols.update(c for c in identifiers_in(leaf.expr)
                            if segments[0].column(c).has_dictionary)
        for agg in plan.aggs:
            if agg.arg is None or (isinstance(agg.arg, Identifier) and agg.arg.name == "*"):
                continue
            cols.update(c for c in identifiers_in(agg.arg)
                        if segments[0].column(c).has_dictionary)
        return aligned_dictionaries(segments, cols)

    # ------------------------------------------------------------------
    def _execute_sharded(self, ctx: QueryContext, plan, segments) -> ResultTable:
        build_device_geometry(plan)
        agg_specs = []
        distinct_lut_sizes: Dict[int, int] = {}
        hll_params: Dict[int, int] = {}
        agg_luts: Dict[str, jnp.ndarray] = {}
        for i, agg in enumerate(plan.aggs):
            agg_specs.append((agg, agg.device_outputs))
            if "distinct" in agg.device_outputs:
                distinct_lut_sizes[i] = lut_size(segments[0].column(agg.arg.name).cardinality)
            if "hll" in agg.device_outputs:
                from ..query.executor import _hll_luts
                hll_params[i] = agg.p
                bucket, rank = _hll_luts(segments[0].column(agg.arg.name), agg.p)
                agg_luts[f"{i}.bucket"] = self._const(bucket)
                agg_luts[f"{i}.rank"] = self._const(rank)

        s_pad = -(-len(segments) // self.n_devices) * self.n_devices
        key = tuple(s.path for s in segments)
        block = self._set_blocks.get(key)
        if block is None or block.s_pad != s_pad:
            block = SegmentSetBlock(segments, s_pad, self.mesh)
            self._set_blocks[key] = block

        spec = KernelSpec(plan.filter_prog, plan.group_cols, plan.num_keys_pad,
                          tuple(agg_specs), distinct_lut_sizes, block.rows, hll_params)

        # -- gather runtime inputs ------------------------------------
        ids_cols, decode_cols, raw_cols, nulls_cols = set(plan.group_cols), set(), set(), set()
        luts, iscal, fscal = [], [], []
        for leaf in plan.filter_prog.leaves:
            if isinstance(leaf, LutLeaf):
                ids_cols.add(leaf.col)
                luts.append(self._const(leaf.lut))
            elif isinstance(leaf, CmpLeaf):
                for c in identifiers_in(leaf.expr):
                    (decode_cols if segments[0].column(c).has_dictionary else raw_cols).add(c)
                (iscal if leaf.is_int else fscal).extend(leaf.operands)
            elif isinstance(leaf, NullLeaf):
                nulls_cols.add(leaf.col)
        for i, agg in enumerate(plan.aggs):
            if "distinct" in agg.device_outputs or "hll" in agg.device_outputs:
                ids_cols.add(agg.arg.name)
            elif agg.arg is not None and not (isinstance(agg.arg, Identifier)
                                              and agg.arg.name == "*"):
                for c in identifiers_in(agg.arg):
                    (decode_cols if segments[0].column(c).has_dictionary else raw_cols).add(c)
        ids_cols |= decode_cols  # decode needs the ids too

        inputs = dict(
            ids={c: block.ids(c) for c in ids_cols},
            raw={c: block.raw(c) for c in raw_cols},
            decode={c: block.decode_table(c) for c in decode_cols},
            luts=tuple(luts),
            iscal=self._const(np.asarray(iscal, dtype=np.int32)),
            fscal=self._const(np.asarray(fscal, dtype=np.float32)),
            nulls={c: block.null_mask(c) for c in nulls_cols},
            valid=block.valid,
            strides=self._const(np.asarray(plan.strides, dtype=np.int32)),
            agg_luts=agg_luts,
        )

        fn = self._get_shard_kernel(spec, s_pad, block.rows)
        outs = jax.device_get(fn(inputs))  # one host sync for all partials

        # replicated outputs decode exactly like the single-segment path; dictionaries
        # are shared, so segment[0]'s dictionaries decode the global dense keys.
        if plan.group_cols:
            seg_result = self._fallback._decode_group_partials(plan, outs)
        else:
            seg_result = self._fallback._decode_scalar_partials(plan, outs)
        merged = merge_segment_results([seg_result], plan.aggs)
        group_exprs = ([e for e, _ in ctx.select_items] if ctx.distinct
                       else list(ctx.group_by))
        return reduce_to_result(ctx, merged, plan.aggs, group_exprs)

    # ------------------------------------------------------------------
    def _get_shard_kernel(self, spec: KernelSpec, s_pad: int, rows: int):
        cache_key = (spec.signature(), self.n_devices, s_pad, rows, id(self.mesh))
        fn = _SHARD_KERNEL_CACHE.get(cache_key)
        if fn is None:
            fn = self._build_shard_kernel(spec)
            _SHARD_KERNEL_CACHE[cache_key] = fn
        return fn

    def _build_shard_kernel(self, spec: KernelSpec):
        mask_fn = _make_mask_fn(spec)
        group = bool(spec.group_cols)
        num_seg = spec.num_keys_pad + 1
        P = jax.sharding.PartitionSpec
        ax = SEGMENT_AXIS
        sharded, repl = P(ax), P()

        in_specs = (dict(ids=sharded, raw=sharded, decode=repl, luts=repl, iscal=repl,
                         fscal=repl, nulls=sharded, valid=sharded, strides=repl,
                         agg_luts=repl),)

        def shard_body(inputs):
            ids, raw, decode = inputs["ids"], inputs["raw"], inputs["decode"]
            luts, iscal, fscal = inputs["luts"], inputs["iscal"], inputs["fscal"]
            nulls, valid, strides = inputs["nulls"], inputs["valid"], inputs["strides"]
            agg_luts = inputs["agg_luts"]
            # local shapes: [s_local, P] — decode dict values in-kernel (one gather)
            vals = {c: decode[c][ids[c]] for c in decode}
            vals.update(raw)
            mask = mask_fn(ids, vals, luts, iscal, fscal, nulls, valid)
            out = {}
            if group:
                key = jnp.zeros_like(ids[spec.group_cols[0]])
                for gi, gc in enumerate(spec.group_cols):
                    key = key + ids[gc] * strides[gi]
                key = jnp.where(mask, key, spec.num_keys_pad).ravel()
                flat_mask = mask.ravel()
                counts = jax.ops.segment_sum(jnp.ones_like(key), key, num_segments=num_seg)
                out["count"] = jax.lax.psum(counts, ax)
                for ai, (agg, outs_names) in enumerate(spec.aggs):
                    v = None if agg.arg is None or (
                        isinstance(agg.arg, Identifier) and agg.arg.name == "*") \
                        else _eval_flat(agg.arg, vals).ravel()
                    for o in outs_names:
                        if o == "count":
                            continue
                        if o == "sum":
                            part = jax.ops.segment_sum(
                                jnp.where(flat_mask, v.astype(jnp.float32), 0.0), key,
                                num_segments=num_seg)
                            out[f"{ai}.sum"] = jax.lax.psum(part, ax)
                        elif o == "min":
                            part = jax.ops.segment_min(v, key, num_segments=num_seg)
                            out[f"{ai}.min"] = jax.lax.pmin(part, ax)
                        elif o == "max":
                            part = jax.ops.segment_max(v, key, num_segments=num_seg)
                            out[f"{ai}.max"] = jax.lax.pmax(part, ax)
            else:
                flat_mask = mask.ravel()
                out["count"] = jax.lax.psum(flat_mask.sum(dtype=jnp.int32), ax)
                for ai, (agg, outs_names) in enumerate(spec.aggs):
                    if "distinct" in outs_names:
                        presence = jax.ops.segment_sum(
                            flat_mask.astype(jnp.int32), ids[agg.arg.name].ravel(),
                            num_segments=spec.distinct_lut_sizes[ai])
                        out[f"{ai}.distinct"] = jax.lax.psum(presence, ax)
                        continue
                    if "hll" in outs_names:
                        m = 1 << spec.hll_params[ai]
                        col_ids = ids[agg.arg.name].ravel()
                        bucket = jnp.where(flat_mask,
                                           agg_luts[f"{ai}.bucket"][col_ids], m)
                        rank = jnp.where(flat_mask, agg_luts[f"{ai}.rank"][col_ids], 0)
                        regs = jax.ops.segment_max(rank, bucket, num_segments=m + 1)[:m]
                        out[f"{ai}.hll"] = jax.lax.pmax(jnp.maximum(regs, 0), ax)
                        continue
                    if outs_names == ("count",):
                        continue
                    v = _eval_flat(agg.arg, vals)
                    for o in outs_names:
                        if o == "count":
                            continue
                        if o == "sum":
                            s = (v.astype(jnp.float32) * mask.astype(jnp.float32)).sum()
                            out[f"{ai}.sum"] = jax.lax.psum(s, ax)
                        elif o == "min":
                            ident = np.iinfo(np.int32).max if v.dtype.kind == "i" else jnp.inf
                            out[f"{ai}.min"] = jax.lax.pmin(
                                jnp.where(mask, v, ident).min(), ax)
                        elif o == "max":
                            ident = np.iinfo(np.int32).min if v.dtype.kind == "i" else -jnp.inf
                            out[f"{ai}.max"] = jax.lax.pmax(
                                jnp.where(mask, v, ident).max(), ax)
            return out

        return jax.jit(jax.shard_map(shard_body, mesh=self.mesh,
                                     in_specs=in_specs, out_specs=repl))


def _eval_flat(expr, vals):
    from ..engine.expr import eval_expr
    return eval_expr(expr, vals, jnp)
