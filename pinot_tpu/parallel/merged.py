"""Merged-dictionary segment view: the device path for UNALIGNED segment sets.

Real segment sets — anything committed at different times without a shared ingestion
dictionary, including consuming (mutable) segments — have per-segment dictionaries, so
dict ids disagree across segments and the mesh kernel's dense group keys / id-interval
filters / distinct presence vectors cannot combine with one collective.

The reference solves the analogous problem on the broker: every server ships *values*
(DataTable rows) and `GroupByDataTableReducer` re-hashes them. The TPU-native answer is
instead to agree on ids *before* the scan: build one GLOBAL sorted dictionary per
referenced column (sorted union of the per-segment dictionaries) and remap each
segment's local ids to global ids host-side, once, at block-build time. After the remap
the set behaves exactly like an aligned set — dense keys, interval filters and distinct
vectors combine with one psum — and the per-query dispatch stays gather-free on device.

`MergedSegmentView` presents the merged column surface (`ColumnReader`-compatible) so
`plan_segment`/`compile_filter` plan in global-id space unchanged; `remap(col)` hands the
per-segment id translation tables to `SegmentSetBlock` for host-side application while
stacking. Mutable segments participate via their query-time snapshot (dict + ids at a
fixed row count), giving consuming data a device scan path — the view is rebuilt when
any mutable segment grows (cheap: O(sum of cardinalities) host work), the TPU analog of
the reference re-reading the mutable indexes each query
(`MutableSegmentImpl.java:495`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import DataType
from ..segment.dictionary import Dictionary


def _merge_sorted_values(dicts: List[Dictionary], data_type: DataType):
    """Sorted union of per-segment dictionary values + per-segment remap arrays.

    remap[i][local_id] -> global_id; all inputs are sorted, so the union is one
    np.unique over the concatenation and each remap one vectorized searchsorted.

    A member whose dictionary already equals the union gets remap `None`
    (sorted + unique + same length as the union means its local ids ARE the
    global ids), so the stacker skips the O(rows) remap gather for it —
    mostly-aligned sets (immutable members sharing an ingestion dictionary
    plus one consuming snapshot) pay the remap only where ids actually move.
    """
    if data_type.is_numeric:
        merged = np.unique(np.concatenate([np.asarray(d.values) for d in dicts]))
        remaps = [None if len(d.values) == len(merged) else
                  np.searchsorted(merged, np.asarray(d.values)).astype(np.int32)
                  for d in dicts]
        return Dictionary(merged, data_type), remaps
    arrays = [np.array(list(d.values), dtype=object) for d in dicts]
    merged = np.unique(np.concatenate(arrays)) if arrays else np.array([], dtype=object)
    remaps = [None if len(a) == len(merged) else
              np.searchsorted(merged, a).astype(np.int32) for a in arrays]
    return Dictionary(list(merged), data_type), remaps


class MergedColumnReader:
    """ColumnReader-compatible view of one column across a segment set.

    Dict-encoded everywhere -> exposes the merged global dictionary (+ remaps).
    Otherwise -> a metadata proxy (merged min/max/nulls) over the raw columns.
    """

    def __init__(self, name: str, readers: Sequence[Any],
                 mutable_flags: Optional[Sequence[bool]] = None,
                 seg_docs: Optional[Sequence[int]] = None):
        self.name = name
        self._readers = list(readers)
        self.data_type = readers[0].data_type
        self.has_dictionary = all(r.has_dictionary for r in readers)
        self.num_docs = sum(r.num_docs for r in readers)
        self.is_sorted = False
        self._dictionary: Optional[Dictionary] = None
        # per-member local->global tables; an entry is None when that member's
        # ids are already global (dictionary == the merged union)
        self.remaps: Optional[List[Optional[np.ndarray]]] = None
        # Local ids for mutable members are snapshotted TOGETHER with the dictionary
        # the remap table was built from: a mutable reader re-snapshots (new sorted
        # dict, new ids) whenever rows arrive, so reading `fwd` later could pair new
        # ids with a stale remap. Immutable members read their mmap fwd lazily.
        self._fwd_snap: Dict[int, np.ndarray] = {}
        if self.has_dictionary:
            dicts = []
            for i, r in enumerate(readers):
                if mutable_flags and mutable_flags[i]:
                    # atomic (rows, dict, ids): dict and ids from the SAME snapshot
                    _, d, ids = r.dict_snapshot()
                    n = seg_docs[i] if seg_docs else len(ids)
                    dicts.append(d)
                    self._fwd_snap[i] = np.asarray(ids)[:n].astype(np.int64)
                else:
                    dicts.append(r.dictionary)
            self._dictionary, self.remaps = _merge_sorted_values(dicts, self.data_type)

    def local_ids(self, i: int) -> np.ndarray:
        """Member i's local dict ids, consistent with remaps[i]."""
        snap = self._fwd_snap.get(i)
        if snap is not None:
            return snap
        return np.asarray(self._readers[i].fwd).astype(np.int64)

    @property
    def dictionary(self) -> Optional[Dictionary]:
        return self._dictionary

    @property
    def cardinality(self) -> int:
        return len(self._dictionary) if self._dictionary is not None else -1

    @property
    def meta(self) -> Dict[str, Any]:
        if self.has_dictionary:
            fwd_dtype = "int32"  # remapped ids
        else:
            # value dtype across members (a member may still be dict-encoded when
            # others are raw; its fwd dtype would be an id width, not a value dtype)
            def value_dtype(r):
                if r.has_dictionary and r.data_type.is_numeric:
                    return np.asarray(r.dictionary.values).dtype
                return np.dtype(r.meta["fwdDtype"])
            fwd_dtype = str(np.result_type(*[value_dtype(r) for r in self._readers]))
        return {
            "dataType": self.data_type.value,
            "hasDictionary": self.has_dictionary,
            "hasNulls": any(r.meta.get("hasNulls", False) for r in self._readers),
            "fwdDtype": fwd_dtype,
            "cardinality": self.cardinality,
        }

    def _merged_bound(self, attr: str, combine) -> Any:
        """min/max over members with rows; a NONEMPTY member without stats poisons
        the bound to None (empty members genuinely contribute no values)."""
        vals = []
        for r in self._readers:
            v = getattr(r, attr)
            if v is None:
                if r.num_docs > 0:
                    return None
                continue
            vals.append(v)
        return combine(vals) if vals else None

    @property
    def min_value(self) -> Any:
        return self._merged_bound("min_value", min)

    @property
    def max_value(self) -> Any:
        return self._merged_bound("max_value", max)

    # aux indexes are per-segment; the mesh path pre-bails on JSON/TEXT_MATCH filters
    inverted_index = None
    range_index = None
    bloom_filter = None
    json_index = None
    text_index = None
    index_types: List[str] = []

    def values(self) -> np.ndarray:
        raise NotImplementedError(
            "MergedColumnReader is a planning surface; row data stays per-segment")


class MergedSegmentView:
    """Virtual segment over an unaligned set, planned against like one segment.

    Not mutable even when members are: the planner's mutable->host routing is about
    single-segment host scans; here mutable members are snapshotted into the stacked
    device block (see `SegmentSetBlock`), so the device path applies.
    """

    is_mutable = False

    def __init__(self, segments: Sequence[Any]):
        self.segments = list(segments)
        self.schema = segments[0].schema
        self.name = "merged:" + ",".join(s.name for s in segments)
        self.path = self.name
        self.num_docs = sum(s.num_docs for s in segments)
        # row count of each member at view-build time: mutable members may grow
        # concurrently; every consumer slices to this snapshot for consistency
        self.seg_docs: Tuple[int, ...] = tuple(s.num_docs for s in segments)
        self._columns: Dict[str, MergedColumnReader] = {}

    def column(self, name: str) -> MergedColumnReader:
        if name not in self._columns:
            self._columns[name] = MergedColumnReader(
                name, [s.column(name) for s in self.segments],
                mutable_flags=[getattr(s, "is_mutable", False) for s in self.segments],
                seg_docs=self.seg_docs)
        return self._columns[name]

    @property
    def column_names(self) -> List[str]:
        return self.segments[0].column_names

    def remap(self, col: str) -> Optional[List[Optional[np.ndarray]]]:
        """Per-segment local-id -> global-id translation tables (None for raw
        cols; a None ENTRY means that member's ids are already global)."""
        return self.column(col).remaps

    star_trees: List = []

    def __repr__(self) -> str:
        return f"MergedSegmentView({len(self.segments)} segments, docs={self.num_docs})"


def view_key(segments: Sequence[Any]) -> Tuple:
    """Cache key for a segment set; mutable members key on their current row count so
    growth invalidates (and re-stacks) the view — the consuming-buffer device refresh."""
    return tuple((getattr(s, "path", s.name),
                  s.num_docs if getattr(s, "is_mutable", False) else -1)
                 for s in segments)
