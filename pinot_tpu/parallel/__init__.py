"""Multi-chip execution: device mesh scatter/combine via shard_map + ICI collectives.

The TPU-native replacement for the reference's scatter/gather data plane
(broker fan-out `QueryRouter.submitQuery` + per-server combine operators + broker reduce,
SURVEY.md §2.11): segments shard over a 1-D `Mesh` axis, each device scans its shard with
the same fused kernel as single-chip, and partial aggregates combine with
`psum`/`pmin`/`pmax` over ICI instead of DataTable shuffles over TCP.
"""

from .combine import MeshQueryExecutor, aligned_dictionaries
from .mesh import default_mesh

__all__ = ["MeshQueryExecutor", "aligned_dictionaries", "default_mesh"]
