"""Python client for a pinot_tpu cluster.

Analog of the reference's language clients (`pinot-clients/pinot-java-client` /
`pinot3-python` `pinotdb`): connect to a broker, run SQL, iterate rows; plus
the controller admin surface. One import for applications:

    from pinot_tpu.client import connect
    conn = connect(broker="http://localhost:8099", token="...")
    for row in conn.execute("SELECT city, COUNT(*) FROM trips GROUP BY city"):
        print(row)

`Connection.execute` returns a `ResultSet` with `columns`, `rows`,
`stats`, and iteration — a deliberately DB-API-flavored surface without the
full PEP 249 ceremony (no transactions in an OLAP store).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

from .cluster.process import BrokerClient, ControllerClient


class ResultSet:
    def __init__(self, resp: Dict[str, Any]):
        table = resp.get("resultTable") or {}
        self.columns: List[str] = table.get("dataSchema", {}).get("columnNames", [])
        self.rows: List[List[Any]] = table.get("rows", [])
        self.stats: Dict[str, Any] = {k: v for k, v in resp.items()
                                      if k != "resultTable"}

    def __iter__(self) -> Iterator[List[Any]]:
        return iter(self.rows)

    def __len__(self) -> int:
        return len(self.rows)

    def first(self) -> Optional[List[Any]]:
        return self.rows[0] if self.rows else None

    def scalar(self) -> Any:
        """The single value of a one-cell result (e.g. SELECT COUNT(*))."""
        return self.rows[0][0] if self.rows and self.rows[0] else None

    def __repr__(self) -> str:
        return f"ResultSet({self.columns}, {len(self.rows)} rows)"


class Connection:
    """A broker connection (+ optional controller admin surface).

    `token` is PER-CONNECTION — it rides each request's Authorization header,
    so two connections with different credentials coexist in one process
    (a global default would cross-contaminate them)."""

    def __init__(self, broker: str, controller: Optional[str] = None,
                 token: Optional[str] = None):
        self._broker = BrokerClient(broker, token=token)
        self.admin: Optional[ControllerClient] = (
            ControllerClient(controller, token=token) if controller else None)

    def execute(self, sql: str, timeout: float = 120.0) -> ResultSet:
        return ResultSet(self._broker.query(sql, timeout=timeout))


def connect(broker: str, controller: Optional[str] = None,
            token: Optional[str] = None) -> Connection:
    return Connection(broker, controller=controller, token=token)
