"""pinot-tpu admin CLI: operate a cluster without writing Python.

Analog of the reference's `pinot-admin.sh` command surface
(`pinot-tools/src/main/java/org/apache/pinot/tools/admin/PinotAdministrator.java`):
role starters, schema/table management, segment push, queries, and segment
tools, all against the controller/broker HTTP APIs.

    python -m pinot_tpu.tools.admin start-controller --work-dir /data --run-dir /run
    python -m pinot_tpu.tools.admin add-schema    --controller URL --file schema.json
    python -m pinot_tpu.tools.admin add-table     --controller URL --file table.json
    python -m pinot_tpu.tools.admin list-tables   --controller URL
    python -m pinot_tpu.tools.admin upload-segment --controller URL --table t_OFFLINE --dir seg/
    python -m pinot_tpu.tools.admin build-segment --schema schema.json --input rows.json \\
                                                  --out dir --name seg_0
    python -m pinot_tpu.tools.admin query         --broker URL --sql "SELECT ..."
    python -m pinot_tpu.tools.admin table-status  --controller URL --table t_OFFLINE
    python -m pinot_tpu.tools.admin reload-table  --controller URL --table t_OFFLINE
    python -m pinot_tpu.tools.admin dump-segment  --dir seg/
    python -m pinot_tpu.tools.admin verify-segment --dir seg/
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Optional, Sequence


def _print(obj: Any) -> None:
    print(json.dumps(obj, indent=2, default=str))


def _controller(args):
    from ..cluster.process import ControllerClient
    return ControllerClient(args.controller)


def cmd_start_role(args) -> int:
    from ..cluster import process
    if args.cmd == "start-controller":
        process.run_controller(args.work_dir, args.run_dir, args.port, args.config)
    elif args.cmd == "start-server":
        process.run_server(args.controller, args.instance_id or "server_0",
                           args.work_dir, args.run_dir, args.port, args.config)
    else:
        process.run_broker(args.controller, args.instance_id or "broker_0",
                           args.run_dir, args.port, args.config)
    return 0


def cmd_add_schema(args) -> int:
    from ..schema import Schema
    with open(args.file) as f:
        schema = Schema.from_json(json.load(f))
    _controller(args).add_schema(schema)
    _print({"status": "OK", "schema": schema.name})
    return 0


def cmd_add_table(args) -> int:
    from ..table import TableConfig
    with open(args.file) as f:
        cfg = TableConfig.from_json(json.load(f))
    resp = _controller(args).add_table(cfg, num_partitions=args.num_partitions)
    _print(resp)
    return 0


def cmd_list_tables(args) -> int:
    _print(_controller(args).list_tables())
    return 0


def cmd_table_status(args) -> int:
    _print(_controller(args).table_status(args.table))
    return 0


def cmd_upload_segment(args) -> int:
    _print(_controller(args).upload_segment(args.table, args.dir))
    return 0


def cmd_build_segment(args) -> int:
    """Build a segment from a JSON-lines (or CSV) file + schema json
    (reference: CreateSegmentCommand)."""
    from ..ingest.readers import reader_for
    from ..schema import Schema
    from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig
    with open(args.schema) as f:
        schema = Schema.from_json(json.load(f))
    rows = list(reader_for(args.input, args.format or None).rows())
    cols = {c: [r.get(c) for r in rows] for c in schema.column_names}
    path = SegmentBuilder(schema, SegmentGeneratorConfig()).build(
        cols, args.out, args.name)
    _print({"status": "OK", "segmentDir": path, "rows": len(rows)})
    return 0


def cmd_reload_table(args) -> int:
    _print(_controller(args).reload_table(args.table))
    return 0


def cmd_query(args) -> int:
    from ..cluster.process import BrokerClient
    resp = BrokerClient(args.broker).query(args.sql)
    if args.json:
        _print(resp)
        return 0
    table = resp.get("resultTable", {})
    names = table.get("dataSchema", {}).get("columnNames", [])
    rows = table.get("rows", [])
    if names:
        print("\t".join(map(str, names)))
    for row in rows:
        print("\t".join(map(str, row)))
    stats = {k: v for k, v in resp.items() if k != "resultTable"}
    print(f"-- {len(rows)} rows, {json.dumps(stats, default=str)}", file=sys.stderr)
    return 0


def cmd_dump_segment(args) -> int:
    from .segment import dump_segment
    _print(dump_segment(args.dir, max_rows=args.rows))
    return 0


def cmd_verify_segment(args) -> int:
    from .segment import verify_segment
    report = verify_segment(args.dir)
    _print(report)
    return 0 if report["ok"] else 1


def cmd_recommend_config(args) -> int:
    """Reference: the controller recommender endpoint (schema + query
    patterns + throughput -> config advice)."""
    from .tuner import (recommend, recommend_from_workload,
                        recommend_realtime_provisioning)
    if args.queries:
        with open(args.queries) as f:
            queries = [ln.strip() for ln in f if ln.strip()]
        rec = recommend_from_workload(args.segment_dir, queries,
                                      num_servers=args.num_servers)
    else:
        rec = recommend(args.segment_dir)
    rec.pop("profile", None)   # advice, not the raw dump
    if args.events_per_sec:
        rec["realtimeProvisioning"] = recommend_realtime_provisioning(
            args.events_per_sec, args.avg_row_bytes,
            retention_hours=args.retention_hours,
            host_memory_gb=args.host_memory_gb,
            num_hosts=args.num_servers)
    _print(rec)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pinot-tpu-admin", description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    def role(name):
        sp = sub.add_parser(name)
        # only the controller bootstraps without a controller URL
        sp.add_argument("--controller", required=(name != "start-controller"),
                        default="")
        sp.add_argument("--instance-id", default="")
        sp.add_argument("--work-dir", default="")
        sp.add_argument("--run-dir", required=True)
        sp.add_argument("--port", type=int, default=0)
        sp.add_argument("--config", default="")
        sp.set_defaults(fn=cmd_start_role)
    role("start-controller")
    role("start-server")
    role("start-broker")

    sp = sub.add_parser("start-service-manager")
    sp.add_argument("--work-dir", required=True)
    sp.add_argument("--run-dir", required=True)
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--config", default="")
    sp.set_defaults(fn=cmd_start_service_manager)

    sp = sub.add_parser("add-schema")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--file", required=True)
    sp.set_defaults(fn=cmd_add_schema)

    sp = sub.add_parser("add-table")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--file", required=True)
    sp.add_argument("--num-partitions", type=int, default=1)
    sp.set_defaults(fn=cmd_add_table)

    sp = sub.add_parser("list-tables")
    sp.add_argument("--controller", required=True)
    sp.set_defaults(fn=cmd_list_tables)

    sp = sub.add_parser("table-status")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.set_defaults(fn=cmd_table_status)

    sp = sub.add_parser("upload-segment")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.add_argument("--dir", required=True)
    sp.set_defaults(fn=cmd_upload_segment)

    sp = sub.add_parser("build-segment")
    sp.add_argument("--schema", required=True)
    sp.add_argument("--input", required=True)
    sp.add_argument("--format", default="")
    sp.add_argument("--out", required=True)
    sp.add_argument("--name", required=True)
    sp.set_defaults(fn=cmd_build_segment)

    sp = sub.add_parser("reload-table")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.set_defaults(fn=cmd_reload_table)

    sp = sub.add_parser("query")
    sp.add_argument("--broker", required=True)
    sp.add_argument("--sql", required=True)
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_query)

    sp = sub.add_parser("dump-segment")
    sp.add_argument("--dir", required=True)
    sp.add_argument("--rows", type=int, default=10)
    sp.set_defaults(fn=cmd_dump_segment)

    sp = sub.add_parser("verify-segment")
    sp.add_argument("--dir", required=True)
    sp.set_defaults(fn=cmd_verify_segment)

    sp = sub.add_parser("recommend-config")
    sp.add_argument("--segment-dir", required=True,
                    help="a representative built segment")
    sp.add_argument("--queries", default=None,
                    help="file with one representative SQL query per line")
    sp.add_argument("--num-servers", type=int, default=2)
    sp.add_argument("--events-per-sec", type=float, default=0.0,
                    help="also emit realtime provisioning advice")
    sp.add_argument("--avg-row-bytes", type=int, default=256)
    sp.add_argument("--retention-hours", type=int, default=72)
    sp.add_argument("--host-memory-gb", type=float, default=16.0)
    sp.set_defaults(fn=cmd_recommend_config)

    sp = sub.add_parser("quickstart")
    sp.add_argument("--type", dest="qtype", default="batch",
                    choices=["batch", "realtime", "hybrid"])
    sp.add_argument("--rows", type=int, default=10_000)
    sp.add_argument("--work-dir", default=None)
    sp.add_argument("--exit-after-queries", action="store_true")
    sp.set_defaults(fn=cmd_quickstart)

    sp = sub.add_parser("infer-schema")
    sp.add_argument("--input", required=True, help=".csv or .jsonl sample")
    sp.add_argument("--table-name", default=None)
    sp.add_argument("--time-column", default=None)
    sp.set_defaults(fn=cmd_infer_schema)

    sp = sub.add_parser("ingest-job")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--spec", required=True, help="job spec JSON/YAML file")
    sp.add_argument("--distributed", action="store_true",
                    help="queue one task per input file for the minion fleet "
                         "(POST /ingestJobs) instead of running standalone")
    sp.set_defaults(fn=cmd_ingest_job)

    sp = sub.add_parser("cluster-info")
    sp.add_argument("--controller", required=True)
    sp.set_defaults(fn=cmd_cluster_info)

    sp = sub.add_parser("list-tenants")
    sp.add_argument("--controller", required=True)
    sp.set_defaults(fn=cmd_list_tenants)

    sp = sub.add_parser("tag-instance")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--instance", required=True)
    sp.add_argument("--tags", required=True, help="comma-separated")
    sp.set_defaults(fn=cmd_tag_instance)

    sp = sub.add_parser("pause-consumption")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.set_defaults(fn=cmd_pause_consumption)

    sp = sub.add_parser("resume-consumption")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.set_defaults(fn=cmd_resume_consumption)

    sp = sub.add_parser("rebalance-table")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.set_defaults(fn=cmd_rebalance_table)

    sp = sub.add_parser("change-table-state")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True)
    sp.add_argument("--state", required=True, choices=["enable", "disable"])
    sp.set_defaults(fn=cmd_change_table_state)

    sp = sub.add_parser("cluster-config")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--set", default=None, help="key=value (omit to list)")
    sp.add_argument("--delete", default=None, help="key to delete")
    sp.set_defaults(fn=cmd_cluster_config)

    sp = sub.add_parser("drop-table")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--table", required=True, help="table name with type")
    sp.set_defaults(fn=cmd_drop_table)

    sp = sub.add_parser("generate-data")
    sp.add_argument("--schema-file", required=True)
    sp.add_argument("--rows", type=int, default=1000)
    sp.add_argument("--out", required=True, help=".csv or .jsonl output path")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--cardinality", action="append", default=[],
                    help="col=N, repeatable")
    sp.set_defaults(fn=cmd_generate_data)

    sp = sub.add_parser("anonymize-data")
    sp.add_argument("--input", required=True, help=".csv or .jsonl input")
    sp.add_argument("--out", required=True)
    sp.add_argument("--columns", required=True, help="comma-separated")
    sp.set_defaults(fn=cmd_anonymize_data)

    sp = sub.add_parser("compat-check")
    sp.add_argument("--controller", required=True)
    sp.add_argument("--broker", required=True)
    sp.add_argument("--ops", required=True, help="YAML op-sequence file")
    sp.set_defaults(fn=cmd_compat_check)
    return p


def cmd_start_service_manager(args) -> int:
    """Reference: StartServiceManagerCommand — all roles in one process."""
    from ..cluster.process import run_service_manager
    run_service_manager(args.work_dir, args.run_dir, args.port, args.config)
    return 0


def cmd_quickstart(args) -> int:
    """Reference: Quickstart / RealtimeQuickStart / HybridQuickstart."""
    from .quickstart import run_quickstart
    return run_quickstart(args.qtype, rows=args.rows, work_dir=args.work_dir,
                          exit_after_queries=args.exit_after_queries)


def cmd_infer_schema(args) -> int:
    """Reference: JsonToPinotSchema / AvroSchemaToPinotSchema."""
    from .datagen import infer_schema
    schema = infer_schema(args.input, table_name=args.table_name,
                          time_column=args.time_column)
    _print(schema.to_json())
    return 0


def cmd_ingest_job(args) -> int:
    """Reference: LaunchDataIngestionJobCommand over a job-spec file."""
    import json as _json
    from ..cluster.process import ControllerClient
    from ..ingest.batch import BatchIngestionJobSpec, run_batch_ingestion

    with open(args.spec) as f:
        text = f.read()
    try:
        d = _json.loads(text)
    except ValueError:
        import yaml
        d = yaml.safe_load(text)
    if getattr(args, "distributed", False):
        # scale-out path: the controller splits the job per input file and
        # the minion fleet executes in parallel (hadoop/spark-runner analog)
        from ..cluster.http_service import post_json
        resp = post_json(f"{args.controller.rstrip('/')}/ingestJobs", {
            "table": d["table"],
            "inputPaths": d.get("inputPaths", d.get("input_paths", [])),
            "inputFormat": d.get("inputFormat"),
            "segmentNamePrefix": d.get("segmentNamePrefix", ""),
            "segmentRows": int(d.get("segmentRows", 1_000_000)),
            "filterExpr": d.get("filterExpr"),
            "columnTransforms": d.get("columnTransforms", {}),
        })
        print(f"queued {len(resp['tasks'])} tasks: {resp['tasks']}")
        return 0
    spec = BatchIngestionJobSpec(
        input_paths=d.get("inputPaths", d.get("input_paths", [])),
        input_format=d.get("inputFormat"),
        table=d["table"],
        segment_name_prefix=d.get("segmentNamePrefix", ""),
        segment_rows=int(d.get("segmentRows", 1_000_000)),
        filter_expr=d.get("filterExpr"),
        column_transforms=d.get("columnTransforms", {}),
    )
    import tempfile
    with tempfile.TemporaryDirectory() as work:
        pushed = run_batch_ingestion(spec, _RemoteJobController(
            ControllerClient(args.controller), spec.table), work_dir=work)
    print(f"pushed {len(pushed)} segments: {pushed}")
    return 0


class _RemoteJobController:
    """Minimal controller facade the batch runner needs, over HTTP — fetches
    only the job's table config + schema (not the whole cluster's)."""

    def __init__(self, client, table: str):
        self._client = client
        from ..schema import Schema
        from ..table import TableConfig

        class _Cat:
            pass
        cfg = TableConfig.from_json(client.table_config(table)["config"])
        self.catalog = _Cat()
        self.catalog.table_configs = {table: cfg}
        self.catalog.schemas = {
            cfg.name: Schema.from_json(client.get_schema(cfg.name))}

    def upload_segment(self, table, seg_dir, custom=None):
        import os
        import types
        resp = self._client.upload_segment(table, seg_dir)
        # normalize the HTTP response to the in-proc SegmentMeta surface the
        # batch runner consumes
        return types.SimpleNamespace(
            name=resp.get("segment") or os.path.basename(seg_dir.rstrip("/")))


def cmd_cluster_info(args) -> int:
    """Reference: ShowClusterInfo / VerifyClusterState."""
    from ..cluster.http_service import get_json
    from ..cluster.process import ControllerClient
    c = ControllerClient(args.controller)
    tables = c.list_tables().get("tables", {})
    tenants = get_json(f"{c.url}/tenants", token=c.token).get("tenants", {})
    print(f"tenants: {tenants}")
    ok = True
    for name in tables:
        st = c.table_status(name)
        ok &= bool(st.get("converged"))
        print(f"{name}: segments={st.get('segments')} "
              f"converged={st.get('converged')}")
    print("cluster state: " + ("GOOD" if ok else "NOT CONVERGED"))
    return 0 if ok else 1


def cmd_list_tenants(args) -> int:
    from ..cluster.http_service import get_json
    from ..cluster.process import ControllerClient
    c = ControllerClient(args.controller)
    _print(get_json(f"{c.url}/tenants", token=c.token))
    return 0


def cmd_tag_instance(args) -> int:
    from ..cluster.http_service import post_json
    from ..cluster.process import ControllerClient
    c = ControllerClient(args.controller)
    _print(post_json(f"{c.url}/instanceTags/{args.instance}",
                     {"tags": args.tags.split(",")}, token=c.token))
    return 0


def cmd_pause_consumption(args) -> int:
    from ..cluster.http_service import post_json
    from ..cluster.process import ControllerClient
    c = ControllerClient(args.controller)
    _print(post_json(f"{c.url}/pauseConsumption/{args.table}", {}, token=c.token))
    return 0


def cmd_resume_consumption(args) -> int:
    from ..cluster.http_service import post_json
    from ..cluster.process import ControllerClient
    c = ControllerClient(args.controller)
    _print(post_json(f"{c.url}/resumeConsumption/{args.table}", {}, token=c.token))
    return 0


def cmd_rebalance_table(args) -> int:
    from ..cluster.process import ControllerClient
    _print(ControllerClient(args.controller).rebalance(args.table))
    return 0


def cmd_change_table_state(args) -> int:
    from ..cluster.http_service import http_call
    from ..cluster.process import ControllerClient
    import json as _json
    c = ControllerClient(args.controller)
    out = http_call("POST", f"{c.url}/tableState/{args.table}?state={args.state}",
                    b"{}", token=c.token)
    _print(_json.loads(out.decode()))
    return 0


def cmd_cluster_config(args) -> int:
    """Reference: OperateClusterConfigCommand (GET/POST/DELETE cluster configs)."""
    from ..cluster.http_service import get_json, post_json
    from ..cluster.process import ControllerClient
    c = ControllerClient(args.controller)
    if args.set:
        key, _, value = args.set.partition("=")
        _print(post_json(f"{c.url}/clusterConfigs",
                         {"key": key, "value": value}, token=c.token))
    elif args.delete:
        _print(post_json(f"{c.url}/clusterConfigs",
                         {"key": args.delete, "value": None}, token=c.token))
    else:
        _print(get_json(f"{c.url}/clusterConfigs", token=c.token))
    return 0


def cmd_drop_table(args) -> int:
    from ..cluster.process import ControllerClient
    ControllerClient(args.controller).drop_table(args.table)
    print(f"dropped {args.table}")
    return 0


def cmd_generate_data(args) -> int:
    """Reference: GenerateDataCommand."""
    import json as _json
    from ..schema import Schema
    from .datagen import generate_columns, write_csv, write_jsonl
    with open(args.schema_file) as f:
        schema = Schema.from_json(_json.load(f))
    cards = {}
    for spec in args.cardinality:
        col, _, n = spec.partition("=")
        cards[col] = int(n)
    cols = generate_columns(schema, args.rows, seed=args.seed,
                            cardinalities=cards)
    (write_csv if args.out.endswith(".csv") else write_jsonl)(args.out, cols)
    print(f"wrote {args.rows} rows to {args.out}")
    return 0


def cmd_anonymize_data(args) -> int:
    """Reference: AnonymizeDataCommand."""
    from .datagen import anonymize_file
    anonymize_file(args.input, args.out, args.columns.split(","))
    print(f"anonymized {args.columns} -> {args.out}")
    return 0


def cmd_compat_check(args) -> int:
    """Reference: pinot-compatibility-verifier CompatibilityOpsRunner CLI."""
    from .compat import CompatibilityOpsRunner
    runner = CompatibilityOpsRunner(args.controller, args.broker)
    ok = runner.run(args.ops)
    for line in runner.log:
        print(line)
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    import os
    token = os.environ.get("PINOT_TPU_AUTH_TOKEN")
    if token:  # bearer identity for every remote call this invocation makes
        from ..cluster.http_service import set_default_token
        set_default_token(token)
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    # die quietly when the downstream pipe closes (e.g. `... | head`)
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
