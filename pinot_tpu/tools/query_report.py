#!/usr/bin/env python
"""Pretty-print one query's ExecutionStats as a phase waterfall.

Feed it the JSON a query response carries (the broker's `stats` block, a full
HTTP response body, or a slow-query log line — all three shapes are accepted):

    python tools/query_report.py response.json
    curl -s broker:8099/query -d '{"sql": "..."}' | python tools/query_report.py

Output: a wall-clock waterfall of the broker phases (compile / scatter /
reduce), the device-time breakdown inside the scatter window (compile, exec,
fetch, queue wait), and the scan/cache counters — everything an operator needs
to see WHERE a slow query spent its time without attaching a profiler.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List

BAR_WIDTH = 40


def _extract_stats(doc: Any) -> Dict[str, Any]:
    """Accept a bare stats dict, a response body with a 'stats' block, or a
    slow-query log entry ('stats' + 'sql')."""
    if not isinstance(doc, dict):
        raise ValueError("expected a JSON object")
    if isinstance(doc.get("stats"), dict):
        inner = dict(doc["stats"])
        for k in ("sql", "timeUsedMs", "thresholdMs"):
            if k in doc and k not in inner:
                inner[k] = doc[k]
        return inner
    return doc


def _bar(ms: float, total: float) -> str:
    if total <= 0:
        return ""
    n = int(round(BAR_WIDTH * ms / total))
    return "#" * max(n, 1 if ms > 0 else 0)


def _fmt_ms(v: Any) -> str:
    try:
        return f"{float(v):10.3f} ms"
    except (TypeError, ValueError):
        return f"{v!s:>10}"


def render_report(stats: Dict[str, Any]) -> str:
    """The report body as a string (the CLI prints it; tests assert on it)."""
    out: List[str] = []
    sql = stats.get("sql")
    if sql:
        out.append(f"query: {sql}")
    total = float(stats.get("timeUsedMs") or 0.0)
    phases = stats.get("phaseTimesMs") or {}
    out.append(f"total wall time: {total:.3f} ms")
    out.append("")
    out.append("phase waterfall (broker wall clock)")
    scale = total or sum(float(v) for v in phases.values()) or 1.0
    for name in ("compile", "scatter", "reduce"):
        if name not in phases:
            continue
        ms = float(phases[name])
        out.append(f"  {name:<10} {_fmt_ms(ms)}  |{_bar(ms, scale):<{BAR_WIDTH}}|")
    accounted = sum(float(v) for v in phases.values())
    if total and phases:
        out.append(f"  {'other':<10} {_fmt_ms(max(total - accounted, 0.0))}")
    out.append("")
    out.append("device time (inside scatter, summed over servers)")
    for key, label in (("compileMs", "jit compile"),
                       ("deviceExecMs", "device exec"),
                       ("deviceFetchMs", "device fetch"),
                       ("queueWaitMs", "queue wait")):
        if key in stats:
            out.append(f"  {label:<12} {_fmt_ms(stats.get(key, 0))}")
    out.append("")
    out.append("counters")
    for key in ("numSegmentsQueried", "numSegmentsPruned", "numSegmentsMatched",
                "numDocsScanned", "numGroupsTotal", "deviceLaunches",
                "dedupedLaunches", "stackedLaunches", "compileCacheHits",
                "compileCacheMisses", "bytesFetched", "numServersQueried",
                "numServersResponded"):
        if key in stats:
            out.append(f"  {key:<20} {stats[key]}")
    if stats.get("partialResult"):
        out.append("  ** PARTIAL RESULT — some servers/segments missing **")
    return "\n".join(out)


def main(argv: List[str]) -> int:
    if len(argv) > 1 and argv[1] not in ("-", "-h", "--help"):
        with open(argv[1]) as f:
            doc = json.load(f)
    elif len(argv) > 1 and argv[1] in ("-h", "--help"):
        print(__doc__)
        return 0
    else:
        doc = json.load(sys.stdin)
    print(render_report(_extract_stats(doc)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
