#!/usr/bin/env python
"""Pretty-print one query's ExecutionStats as a phase waterfall.

Feed it the JSON a query response carries (the broker's `stats` block, a full
HTTP response body, or a slow-query log line — all three shapes are accepted):

    python tools/query_report.py response.json
    curl -s broker:8099/query -d '{"sql": "..."}' | python tools/query_report.py

Exported traces work too, so saved `GET /debug/traces` captures analyze
offline without a live cluster: a `{"traces": [...]}` listing, a single ring
entry (`{"traceId", "spans", ...}`), or the Chrome trace-event form
(`{"traceEvents": [...]}`) all render a per-span waterfall.

Output: a wall-clock waterfall of the broker phases (compile / scatter /
reduce) or of the trace's spans, the device-time breakdown inside the scatter
window (compile, exec, fetch, queue wait), and the scan/cache counters —
everything an operator needs to see WHERE a slow query spent its time without
attaching a profiler.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List

BAR_WIDTH = 40


def _extract_stats(doc: Any) -> Dict[str, Any]:
    """Accept a bare stats dict, a response body with a 'stats' block, or a
    slow-query log entry ('stats' + 'sql')."""
    if not isinstance(doc, dict):
        raise ValueError("expected a JSON object")
    if isinstance(doc.get("stats"), dict):
        inner = dict(doc["stats"])
        for k in ("sql", "timeUsedMs", "thresholdMs"):
            if k in doc and k not in inner:
                inner[k] = doc[k]
        return inner
    return doc


def _bar(ms: float, total: float) -> str:
    if total <= 0:
        return ""
    n = int(round(BAR_WIDTH * ms / total))
    return "#" * max(n, 1 if ms > 0 else 0)


def _fmt_ms(v: Any) -> str:
    try:
        return f"{float(v):10.3f} ms"
    except (TypeError, ValueError):
        return f"{v!s:>10}"


def render_report(stats: Dict[str, Any]) -> str:
    """The report body as a string (the CLI prints it; tests assert on it)."""
    out: List[str] = []
    sql = stats.get("sql")
    if sql:
        out.append(f"query: {sql}")
    total = float(stats.get("timeUsedMs") or 0.0)
    phases = stats.get("phaseTimesMs") or {}
    out.append(f"total wall time: {total:.3f} ms")
    out.append("")
    out.append("phase waterfall (broker wall clock)")
    scale = total or sum(float(v) for v in phases.values()) or 1.0
    for name in ("compile", "scatter", "reduce"):
        if name not in phases:
            continue
        ms = float(phases[name])
        out.append(f"  {name:<10} {_fmt_ms(ms)}  |{_bar(ms, scale):<{BAR_WIDTH}}|")
    accounted = sum(float(v) for v in phases.values())
    if total and phases:
        out.append(f"  {'other':<10} {_fmt_ms(max(total - accounted, 0.0))}")
    out.append("")
    out.append("device time (inside scatter, summed over servers)")
    for key, label in (("compileMs", "jit compile"),
                       ("deviceExecMs", "device exec"),
                       ("deviceFetchMs", "device fetch"),
                       ("queueWaitMs", "queue wait"),
                       ("muxFrameQueueMs", "mux frame queue"),
                       ("muxFlowControlMs", "mux flow ctl"),
                       ("collectiveMs", "ici collective")):
        if key in stats:
            out.append(f"  {label:<15} {_fmt_ms(stats.get(key, 0))}")
    if "deviceSkewPct" in stats:
        try:
            skew = f"{float(stats['deviceSkewPct']):10.1f} %"
        except (TypeError, ValueError):
            skew = f"{stats['deviceSkewPct']!s:>10}"
        out.append(f"  {'device skew':<15} {skew}  (worst mesh launch)")
    if "rooflinePct" in stats:
        try:
            roofline = f"{float(stats['rooflinePct']):10.1f} %"
        except (TypeError, ValueError):
            roofline = f"{stats['rooflinePct']!s:>10}"
        out.append(f"  {'hbm roofline':<15} {roofline}  "
                   "(achieved/measured bandwidth, worst fetch window)")
    # join section only when a join ran (joinStrategy is set by both the
    # funnel and the P2P multistage paths)
    if stats.get("joinStrategy") or any(
            float(stats.get(k) or 0) for k in
            ("joinBuildMs", "joinProbeMs", "joinShuffleBytes",
             "joinServedHostTier")):
        out.append("")
        out.append("join (device hash-join fast path)")
        if stats.get("joinStrategy"):
            out.append(f"  {'strategy':<15} {stats['joinStrategy']:>10}")
        for key, label in (("joinBuildMs", "build"),
                           ("joinProbeMs", "probe")):
            if key in stats:
                out.append(f"  {label:<15} {_fmt_ms(stats.get(key, 0))}")
        if "joinShuffleBytes" in stats:
            out.append(f"  {'shuffle bytes':<15} "
                       f"{int(float(stats['joinShuffleBytes'] or 0)):>10}")
        if "joinSkewPct" in stats:
            try:
                jskew = f"{float(stats['joinSkewPct']):10.1f} %"
            except (TypeError, ValueError):
                jskew = f"{stats['joinSkewPct']!s:>10}"
            out.append(f"  {'probe-key skew':<15} {jskew}  "
                       "(worst hot-bucket excess)")
        if "numSegmentsPrunedByJoinKey" in stats:
            out.append(f"  {'pruned by key':<15} "
                       f"{int(float(stats['numSegmentsPrunedByJoinKey'] or 0)):>10}"
                       "  (probe segments skipped by the build-key filter)")
        if float(stats.get("joinServedHostTier") or 0):
            out.append(f"  {'host-tier joins':<15} "
                       f"{int(float(stats['joinServedHostTier'])):>10}  "
                       "(admission gate priced the join off the device)")
    out.append("")
    out.append("counters")
    for key in ("numSegmentsQueried", "numSegmentsPruned",
                "numSegmentsPrunedByPartition", "numSegmentsPrunedByTime",
                "numSegmentsPrunedByRange", "numSegmentsPrunedByBloom",
                "numSegmentsMatched", "numDocsScanned", "scanRowsAvoided",
                "numGroupsTotal", "deviceLaunches", "fusedLaunches",
                "stagedLaunches",
                "dedupedLaunches", "stackedLaunches", "compileCacheHits",
                "compileCacheMisses", "bytesFetched", "deviceFlops",
                "deviceBytesAccessed", "numServersQueried",
                "numServersResponded"):
        if key in stats:
            out.append(f"  {key:<20} {stats[key]}")
    if stats.get("partialResult"):
        out.append("  ** PARTIAL RESULT — some servers/segments missing **")
    return "\n".join(out)


def _trace_entries(doc: Any) -> List[Dict[str, Any]]:
    """Detect an exported-trace document: a /debug/traces listing, a single
    ring entry, or a Chrome trace-event export. Returns normalized entries
    ({traceId, sql?, timeUsedMs?, spans: [{name, startMs, durationMs, depth,
    error?}]}), or [] when `doc` is not a trace document."""
    if not isinstance(doc, dict):
        return []
    if isinstance(doc.get("traces"), list):
        return [e for e in doc["traces"] if isinstance(e, dict)]
    if isinstance(doc.get("spans"), list) and "traceId" in doc:
        return [doc]
    if isinstance(doc.get("traceEvents"), list):
        # fold the Chrome form back: one entry per pid, µs back to ms
        by_pid: Dict[Any, Dict[str, Any]] = {}
        for ev in doc["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            pid = ev.get("pid")
            entry = by_pid.setdefault(pid, {"traceId": f"pid{pid}",
                                            "spans": []})
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                entry["sql"] = (ev.get("args") or {}).get("name", "")
            elif ev.get("ph") == "X":
                entry["spans"].append({
                    "name": ev.get("name", ""),
                    "startMs": float(ev.get("ts", 0.0)) / 1000.0,
                    "durationMs": float(ev.get("dur", 0.0)) / 1000.0,
                    "depth": (ev.get("args") or {}).get("depth", 0),
                    "error": bool((ev.get("args") or {}).get("error")),
                })
        return list(by_pid.values())
    return []


def _events_for(trace_id: Any, events: Any) -> List[Dict[str, Any]]:
    """Journal events carrying this trace's id (the event journal stamps
    `traceId` from the ambient trace at emit time)."""
    if not trace_id or not isinstance(events, list):
        return []
    return [e for e in events
            if isinstance(e, dict) and e.get("traceId") == trace_id]


def render_events_section(events: List[Dict[str, Any]]) -> str:
    """Cluster-state transitions that fired DURING this query (same traceId),
    oldest first — a slow query that straddles a server.down or an admission
    flip shows the transition inline with its waterfall."""
    out: List[str] = ["journal events (same traceId)"]
    ordered = sorted(events, key=lambda e: (float(e.get("tsMs") or 0),
                                            str(e.get("node", "")),
                                            int(e.get("seq") or 0)))
    origin = min(float(e.get("tsMs") or 0) for e in ordered)
    for ev in ordered:
        offset = (float(ev.get("tsMs") or 0) - origin) / 1000.0
        subject = ev.get("segment") or ev.get("table") or ""
        attrs = ev.get("attrs") or {}
        detail = "  ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
        line = (f"  +{offset:7.3f}s  {ev.get('node', '?'):<14} "
                f"{ev.get('kind', '?'):<24} {subject}")
        if detail:
            line = f"{line.rstrip()}  {detail}"
        out.append(line.rstrip())
    return "\n".join(out)


def render_trace(entry: Dict[str, Any],
                 events: Any = None) -> str:
    """Span waterfall for one retained trace: rows sorted by start, indented
    by nesting depth, bars on a shared wall-clock axis. Journal events with
    the same traceId (pass the `/debug/timeline` body's `events` list, or
    embed an `events` key in the document) interleave below the spans."""
    out: List[str] = []
    head = f"trace: {entry.get('traceId', '?')}"
    if entry.get("sql"):
        head += f"  {entry['sql']}"
    out.append(head)
    meta = [f"{k}={entry[k]}" for k in ("timeUsedMs", "sampled", "slow",
                                        "error") if k in entry]
    if meta:
        out.append("  " + "  ".join(meta))
    matched = _events_for(entry.get("traceId"), events)
    spans = sorted(entry.get("spans") or [],
                   key=lambda s: float(s.get("startMs", 0.0)))
    if not spans:
        out.append("  (no spans)")
        if matched:
            out.append("")
            out.append(render_events_section(matched))
        return "\n".join(out)
    end = max(float(s.get("startMs", 0.0)) + float(s.get("durationMs", 0.0))
              for s in spans)
    origin = min(float(s.get("startMs", 0.0)) for s in spans)
    scale = (end - origin) or 1.0
    out.append("")
    for s in spans:
        depth = int(s.get("depth", 0))
        name = "  " * depth + str(s.get("name", "?"))
        start = float(s.get("startMs", 0.0))
        dur = float(s.get("durationMs", 0.0))
        lead = int(round(BAR_WIDTH * (start - origin) / scale))
        bar = " " * lead + (_bar(dur, scale) or ("|" if dur >= 0 else ""))
        flag = "  !ERROR" if s.get("error") else ""
        out.append(f"  {name:<34} {_fmt_ms(dur)}  "
                   f"|{bar:<{BAR_WIDTH}}|{flag}")
    if matched:
        out.append("")
        out.append(render_events_section(matched))
    return "\n".join(out)


def main(argv: List[str]) -> int:
    if len(argv) > 1 and argv[1] not in ("-", "-h", "--help"):
        with open(argv[1]) as f:
            doc = json.load(f)
    elif len(argv) > 1 and argv[1] in ("-h", "--help"):
        print(__doc__)
        return 0
    else:
        doc = json.load(sys.stdin)
    # a `/debug/timeline` body (or an incident bundle) pasted alongside the
    # trace doc interleaves its journal events into each trace's report
    events = doc.get("events") if isinstance(doc, dict) else None
    traces = _trace_entries(doc)
    if traces:
        print("\n\n".join(render_trace(e, events=events) for e in traces))
        return 0
    stats = _extract_stats(doc)
    report = render_report(stats)
    matched = _events_for(stats.get("traceId"), events)
    if matched:
        report = f"{report}\n\n{render_events_section(matched)}"
    print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
