"""One-command demo clusters (reference: pinot-tools Quickstart family —
Quickstart.java, RealtimeQuickStart, HybridQuickstart): boot real controller/
server/broker processes, load sample data, run showcase queries, and leave the
cluster serving so the user can explore with the CLI/clients/web UI.

    python -m pinot_tpu.tools.admin quickstart --type batch
    python -m pinot_tpu.tools.admin quickstart --type realtime
    python -m pinot_tpu.tools.admin quickstart --type hybrid
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import List, Optional

import numpy as np

from ..schema import DataType, Schema, date_time, dimension, metric

_SAMPLE_QUERIES = [
    "SELECT COUNT(*) FROM trips",
    "SELECT city, COUNT(*), SUM(fare) FROM trips GROUP BY city ORDER BY city LIMIT 10",
    "SELECT city, AVG(fare) FROM trips WHERE fare > 20 GROUP BY city "
    "ORDER BY AVG(fare) DESC LIMIT 3",
    "SELECT PERCENTILE(fare, 95), DISTINCTCOUNTHLL(city) FROM trips",
]


def _schema() -> Schema:
    return Schema("trips", [dimension("city", DataType.STRING),
                            metric("fare", DataType.DOUBLE),
                            date_time("ts", DataType.LONG)])


def _rows(n: int, seed: int = 7) -> List[dict]:
    from .datagen import columns_to_rows, generate_columns
    cols = generate_columns(_schema(), n, seed=seed, cardinalities={"city": 8})
    return columns_to_rows(cols)


def _build_and_upload(cluster, rows, work_dir: str, name: str,
                      table: str = "trips_OFFLINE") -> None:
    from ..ingest.readers import rows_to_columns
    from ..ingest.transform import TransformPipeline
    from ..segment.writer import SegmentBuilder
    cols = TransformPipeline(_schema()).apply(rows_to_columns(rows, _schema()))
    seg_dir = SegmentBuilder(_schema()).build(cols, os.path.join(work_dir, "build"),
                                              name)
    cluster.controller.upload_segment(table, seg_dir)


def _show_queries(cluster, queries=_SAMPLE_QUERIES, wait_rows: Optional[int] = None
                  ) -> None:
    if wait_rows is not None:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                got = cluster.query("SELECT COUNT(*) FROM trips")
                n = got["resultTable"]["rows"][0][0]
                if n >= wait_rows:
                    break
            # graftcheck: ignore[exception-hygiene] -- startup poll: the
            # table not existing yet is the condition being waited out
            except Exception:
                pass
            time.sleep(0.3)
    for sql in queries:
        resp = cluster.query(sql)
        table = resp.get("resultTable", {})
        print(f"\n> {sql}")
        print("  " + "\t".join(map(str, table.get("dataSchema", {})
                                   .get("columnNames", []))))
        for row in table.get("rows", []):
            print("  " + "\t".join(map(str, row)))


def run_quickstart(qtype: str = "batch", rows: int = 10_000,
                   work_dir: Optional[str] = None,
                   exit_after_queries: bool = False) -> int:
    from ..cluster.process import ProcessCluster
    from ..table import StreamConfig, TableConfig, TableType

    work_dir = work_dir or tempfile.mkdtemp(prefix="pinot_tpu_quickstart_")
    print(f"*** pinot_tpu {qtype} quickstart (work dir {work_dir}) ***")
    log_broker = None
    cluster = ProcessCluster(num_servers=1, work_dir=work_dir)
    try:
        cluster.controller.add_schema(_schema())
        total = 0
        if qtype in ("batch", "hybrid"):
            cluster.controller.add_table(TableConfig("trips"))
            data = _rows(rows)
            _build_and_upload(cluster, data, work_dir, "trips_batch_0")
            total += len(data)
        if qtype in ("realtime", "hybrid"):
            from ..ingest.kafkalite import LogBrokerClient, LogBrokerServer
            log_broker = LogBrokerServer()
            client = LogBrokerClient(log_broker.bootstrap)
            client.create_topic("trips_topic", 1)
            cfg = TableConfig(
                "trips", table_type=TableType.REALTIME, time_column="ts",
                stream=StreamConfig(
                    stream_type="kafkalite", topic="trips_topic", decoder="json",
                    properties={"bootstrap": log_broker.bootstrap},
                    flush_threshold_rows=max(rows, 1000) * 2))
            cluster.controller.add_table(cfg, num_partitions=1)
            n_rt = rows // 2 if qtype == "hybrid" else rows
            for row in _rows(n_rt, seed=11):
                client.produce("trips_topic", json.dumps(row), partition=0)
            client.close()
            total += n_rt

        _show_queries(cluster, wait_rows=total)
        print(f"\ncontroller: {cluster.controller_url}")
        print(f"broker:     {cluster.broker_url}")
        print(f'try: python -m pinot_tpu.tools.admin query --broker '
              f'{cluster.broker_url} --sql "SELECT COUNT(*) FROM trips"')
        if exit_after_queries:
            return 0
        print("\nserving — Ctrl-C to stop")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            return 0
    finally:
        cluster.shutdown()
        if log_broker is not None:
            log_broker.stop()
