#!/usr/bin/env python
"""Pretty-print a saved `GET /debug/workload` capture as a workload report.

Feed it the JSON the broker's workload endpoint returns (or the `workload`
drill-down body from `?fp=`):

    curl -s broker:8099/debug/workload > workload.json
    python tools/workload_report.py workload.json
    curl -s broker:8099/debug/workload | python tools/workload_report.py

Output: the conservation header (total queries vs per-shape counts plus the
evicted overflow), a top-K table of shapes ranked by total time share with a
share bar, and a per-shape drill-down (canonical plan, latency profile,
scan/launch counters, slot cardinality, and the cacheability signal — the
segment-version vector and how often the shape's inputs changed). Pass
`--top N` to trim the ranking, `--fp <fingerprint>` to render one shape.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List

BAR_WIDTH = 40


def _bar(share_pct: float) -> str:
    n = int(round(BAR_WIDTH * share_pct / 100.0))
    return "#" * max(n, 1 if share_pct > 0 else 0)


def render_summary(doc: Dict[str, Any], top: int = 10) -> str:
    """The ranked top-K table (the CLI prints it; tests assert on it)."""
    out: List[str] = []
    shapes = [s for s in (doc.get("shapes") or []) if isinstance(s, dict)]
    total = doc.get("totalQueries", sum(s.get("count", 0) for s in shapes))
    out.append(f"workload: {total} queries over "
               f"{doc.get('shapesSeen', len(shapes))} shapes "
               f"({doc.get('shapesResident', len(shapes))} resident, "
               f"{doc.get('shapesEvicted', 0)} evicted holding "
               f"{doc.get('evictedQueries', 0)} queries)")
    accounted = sum(s.get("count", 0) for s in shapes) \
        + (doc.get("evictedQueries") or 0)
    if total and accounted != total:
        out.append(f"  ** conservation gap: {accounted} accounted "
                   f"vs {total} total **")
    out.append("")
    out.append(f"  {'fingerprint':<17} {'count':>7} {'share':>7} "
               f"{'p50ms':>9} {'p99ms':>9} {'over':>5}  |{'time share':<{BAR_WIDTH}}|")
    for s in shapes[:top]:
        share = float(s.get("timeSharePct") or 0.0)
        over = int(s.get("overBaseline") or 0)
        out.append(
            f"  {s.get('fingerprint', '?'):<17} {int(s.get('count', 0)):>7} "
            f"{share:>6.2f}% {float(s.get('recentP50Ms') or 0):>9.3f} "
            f"{float(s.get('recentP99Ms') or 0):>9.3f} {over:>5}  "
            f"|{_bar(share):<{BAR_WIDTH}}|")
    if len(shapes) > top:
        rest = shapes[top:]
        out.append(f"  ... {len(rest)} more shapes "
                   f"({sum(s.get('count', 0) for s in rest)} queries)")
    return "\n".join(out)


def render_shape(s: Dict[str, Any]) -> str:
    """One shape's drill-down (the `?fp=` body, or a ranked entry)."""
    out: List[str] = []
    out.append(f"shape {s.get('fingerprint', '?')}  "
               f"tables={','.join(s.get('tables') or [])}")
    out.append(f"  plan: {s.get('canonical', '?')}")
    out.append(f"  count={int(s.get('count', 0))}  "
               f"avg={float(s.get('avgTimeMs') or 0):.3f}ms  "
               f"max={float(s.get('maxTimeMs') or 0):.3f}ms  "
               f"recent p50/p99={float(s.get('recentP50Ms') or 0):.3f}/"
               f"{float(s.get('recentP99Ms') or 0):.3f}ms")
    out.append(f"  baseline={float(s.get('baselineMs') or 0):.3f}ms  "
               f"overBaseline={int(s.get('overBaseline') or 0)}")
    counters = [(k, s[k]) for k in
                ("bytesFetched", "rowsScanned", "segmentsQueried",
                 "segmentsPruned", "deviceLaunches", "hostTierServes",
                 "fusedLaunches", "stagedLaunches") if k in s]
    if counters:
        out.append("  counters: " + "  ".join(
            f"{k}={int(float(v or 0))}" for k, v in counters))
    if s.get("joinStrategies"):
        out.append("  join strategies: " + "  ".join(
            f"{k}={v}" for k, v in sorted(s["joinStrategies"].items())))
    card = s.get("slotCardinality") or []
    if card:
        flags = s.get("slotOverflowed") or [False] * len(card)
        slots = "  ".join(
            f"?{i}:{'>' if flags[i] else ''}{card[i]}"
            for i in range(len(card)))
        out.append(f"  slot cardinality: {slots}")
        values = s.get("slotValues")
        if values:
            for i, vs in enumerate(values):
                out.append(f"    ?{i} sample: {', '.join(map(str, vs))}")
    # cacheability: this is the key the result-cache pairs with the plan
    versions = s.get("segmentVersions") or {}
    if versions:
        vec = "  ".join(f"{t}@v{v}" for t, v in sorted(versions.items()))
        out.append(f"  cacheability: inputs {vec}  "
                   f"(changed {int(s.get('inputChangesSinceFirstSeen') or 0)}"
                   "x since first seen)")
    return "\n".join(out)


def render(doc: Dict[str, Any], top: int = 10, fp: str = "") -> str:
    """Full report: a single-shape doc (the `?fp=` body) renders alone; a
    registry snapshot renders the ranked table plus per-shape drill-downs."""
    if "shapes" not in doc and "fingerprint" in doc:
        return render_shape(doc)
    shapes = [s for s in (doc.get("shapes") or []) if isinstance(s, dict)]
    if fp:
        for s in shapes:
            if s.get("fingerprint") == fp:
                return render_shape(s)
        return f"unknown shape {fp} (evicted, or never seen)"
    parts = [render_summary(doc, top)]
    parts.extend(render_shape(s) for s in shapes[:top])
    return "\n\n".join(parts)


def main(argv: List[str]) -> int:
    args = list(argv[1:])
    if "-h" in args or "--help" in args:
        print(__doc__)
        return 0
    top, fp, path = 10, "", None
    i = 0
    while i < len(args):
        if args[i] == "--top" and i + 1 < len(args):
            top = int(args[i + 1])
            i += 2
        elif args[i] == "--fp" and i + 1 < len(args):
            fp = args[i + 1]
            i += 2
        else:
            path = args[i]
            i += 1
    if path and path != "-":
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = json.load(sys.stdin)
    print(render(doc, top=top, fp=fp))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
