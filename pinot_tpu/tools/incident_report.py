#!/usr/bin/env python
"""Pretty-print a flight-recorder incident bundle as a postmortem report.

Feed it the JSON the controller's incident endpoint returns — the full ring
listing or one bundle:

    curl -s controller:9000/debug/incidents > incidents.json
    python tools/incident_report.py incidents.json
    curl -s controller:9000/debug/incidents?id=3 | python tools/incident_report.py

Output, per bundle: the header (which verdict plane tripped, for which
table/fingerprint, into which state, and why), the causal event timeline the
recorder froze at capture time (the last N merged journal events, oldest
first, so the sequence that led INTO the incident reads top-to-bottom), the
frozen /debug snapshots (ingestion / SLO / memory / workload verdicts plus
per-node health), and the slow-query trace ids to pull from `/debug/traces`
for span-level drill-down. Pass `--id N` to render one bundle from a listing.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List

#: event severities get a one-column marker so ERROR rows jump out of the
#: timeline without color support
_SEVERITY_MARK = {"INFO": " ", "WARN": "*", "ERROR": "!"}


def _fmt_ts(ts_ms: Any, origin_ms: float) -> str:
    """Offset from the first timeline event, in seconds — incident timelines
    read as "what happened in the last minute", not absolute wall clock."""
    try:
        return f"+{(float(ts_ms) - origin_ms) / 1000.0:8.3f}s"
    except (TypeError, ValueError):
        return f"{ts_ms!s:>9}"


def render_event_line(ev: Dict[str, Any], origin_ms: float) -> str:
    mark = _SEVERITY_MARK.get(str(ev.get("severity", "")), " ")
    subject = ev.get("segment") or ev.get("table") or ""
    attrs = ev.get("attrs") or {}
    detail = "  ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
    parts = [f"  {mark} {_fmt_ts(ev.get('tsMs'), origin_ms)}",
             f"{ev.get('node', '?'):<14}", f"{ev.get('kind', '?'):<26}"]
    if subject:
        parts.append(f"{subject:<24}")
    if detail:
        parts.append(detail)
    return " ".join(parts).rstrip()


def render_timeline(events: List[Dict[str, Any]]) -> str:
    out: List[str] = []
    out.append(f"timeline ({len(events)} events, oldest first)")
    if not events:
        out.append("  (no events captured)")
        return "\n".join(out)
    origin = min(float(e.get("tsMs") or 0) for e in events)
    out.extend(render_event_line(e, origin) for e in events)
    return "\n".join(out)


def _verdict_rows(doc: Any, state_key: str) -> List[str]:
    """One row per table from a frozen verdict snapshot ({table: {...}})."""
    rows: List[str] = []
    if not isinstance(doc, dict):
        return rows
    for table in sorted(doc):
        st = doc[table]
        if not isinstance(st, dict):
            continue
        verdict = st.get(state_key) or st.get("verdict") or "?"
        reasons = st.get("reasons") or []
        suffix = f"  ({'; '.join(map(str, reasons[:2]))})" if reasons else ""
        rows.append(f"    {table:<28} {verdict}{suffix}")
    return rows


def render_snapshots(snaps: Dict[str, Any]) -> str:
    out: List[str] = ["frozen /debug snapshots"]
    for key, title, state_key in (
            ("ingestionStatus", "ingestion", "ingestionState"),
            ("sloStatus", "slo", "verdict"),
            ("memoryStatus", "memory", "verdict")):
        doc = snaps.get(key)
        if doc:
            out.append(f"  {title}:")
            out.extend(_verdict_rows(doc, state_key) or ["    (empty)"])
    wl = snaps.get("workloadStatus")
    if isinstance(wl, dict) and wl:
        out.append("  workload:")
        out.extend(f"    {fp:<28} {v}" for fp, v in sorted(wl.items()))
    nodes = snaps.get("nodes")
    if isinstance(nodes, dict) and nodes:
        out.append("  nodes:")
        for node in sorted(nodes):
            snap = nodes[node]
            if isinstance(snap, dict) and snap.get("unreachable"):
                out.append(f"    {node:<28} UNREACHABLE at capture")
            else:
                out.append(f"    {node:<28} captured")
    if len(out) == 1:
        out.append("  (none)")
    return "\n".join(out)


def render_incident(bundle: Dict[str, Any]) -> str:
    """One bundle's postmortem (the CLI prints it; tests assert on it)."""
    out: List[str] = []
    out.append(f"incident #{bundle.get('id', '?')}  "
               f"plane={bundle.get('plane', '?')}  "
               f"key={bundle.get('key', '?')}  "
               f"-> {bundle.get('status', '?')}")
    reasons = bundle.get("reasons") or []
    for r in reasons:
        out.append(f"  reason: {r}")
    out.append("")
    out.append(render_timeline(bundle.get("events") or []))
    out.append("")
    out.append(render_snapshots(bundle.get("snapshots") or {}))
    traces = bundle.get("slowTraceIds") or []
    if traces:
        out.append("")
        out.append("slow-query traces (pull from /debug/traces?id=...):")
        out.extend(f"  {t}" for t in traces)
    return "\n".join(out)


def render(doc: Dict[str, Any], incident_id: int = -1) -> str:
    """Full report: a single bundle renders alone; a ring listing renders
    newest-first, or one bundle when `--id` selects it."""
    if "incidents" not in doc and "plane" in doc:
        return render_incident(doc)
    bundles = [b for b in (doc.get("incidents") or []) if isinstance(b, dict)]
    if incident_id >= 0:
        for b in bundles:
            if b.get("id") == incident_id:
                return render_incident(b)
        return f"unknown incident id {incident_id} (evicted, or never captured)"
    if not bundles:
        return "no incidents captured"
    return "\n\n".join(render_incident(b) for b in bundles)


def main(argv: List[str]) -> int:
    args = list(argv[1:])
    if "-h" in args or "--help" in args:
        print(__doc__)
        return 0
    incident_id, path = -1, None
    i = 0
    while i < len(args):
        if args[i] == "--id" and i + 1 < len(args):
            incident_id = int(args[i + 1])
            i += 2
        else:
            path = args[i]
            i += 1
    if path and path != "-":
        with open(path) as f:
            doc = json.load(f)
    else:
        doc = json.load(sys.stdin)
    print(render(doc, incident_id=incident_id))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
