#!/usr/bin/env python
"""Live one-screen cluster health table (a `top` for the cluster).

Polls the controller and broker debug/status endpoints and renders one row per
table: QPS, consuming-segment count, max offset lag, max freshness lag, rows/s,
the controller's ingestion verdict, and its SLO burn-rate verdict — plus a
top-consumers panel attributing device time / bytes / queue wait per table
from the broker rollups, and a servers panel showing the broker failure
detector's view (healthy vs probing, consecutive probe failures, seconds to
the next probe) with the lifetime hedged-request count in the header, and an
admission panel showing the broker's shed state, in-flight depth against its
queue thresholds, and per-table/per-reason shed counts, and a device-memory
panel with the controller's per-table HBM verdict, resident bytes, and the
worst per-server headroom, and a workload panel with the top query shapes by
time share (count, p99, and the controller sentinel's regression verdict per
plan fingerprint), and a recent-events panel tailing the controller's merged
causal timeline (`/debug/timeline`) with the incident count in its header. The
operator's first stop when a dashboard shows a table going stale, an SLO
burning, a server flapping, HBM filling up, or one query shape regressing:

    python -m pinot_tpu.tools.cluster_top --controller http://host:9000 \\
        --broker http://host:8099 [--interval 5] [--once] [--token TOKEN]

`snapshot()` and `render()` are pure (fetcher injected) so tests drive them
without sockets.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Any, Callable, Dict, List, Optional

Fetcher = Callable[[str], Any]


def _default_fetcher(token: Optional[str]) -> Fetcher:
    from ..cluster.http_service import get_json

    def fetch(url: str) -> Any:
        return get_json(url, timeout=5.0, token=token)
    return fetch


def snapshot(controller_url: str, broker_url: Optional[str],
             fetch: Fetcher) -> Dict[str, Any]:
    """One poll of the cluster: per-table ingestion verdicts from the
    controller plus the broker's lifetime query rollup. Endpoint failures
    degrade to partial data (an unreachable broker must not blank the lag
    columns)."""
    out: Dict[str, Any] = {"tables": {}, "slo": {}, "memory": {},
                           "tableStats": {}, "broker": None, "errors": [],
                           "workload": None, "workloadStatus": {}}
    try:
        tables = fetch(f"{controller_url}/tables").get("tables", [])
    except Exception as e:
        out["errors"].append(f"controller /tables: {e}")
        tables = []
    for t in tables:
        try:
            out["tables"][t] = fetch(
                f"{controller_url}/tables/{t}/ingestionStatus")
        except Exception as e:
            out["tables"][t] = {"table": t, "ingestionState": "UNKNOWN",
                                "reasons": [f"poll failed: {e}"]}
        try:
            out["slo"][t] = fetch(f"{controller_url}/tables/{t}/sloStatus")
        # graftcheck: ignore[exception-hygiene] -- read-only dashboard poll;
        # the missing entry renders visibly as "-" in the SLO column
        except Exception:
            pass   # older controller / unknown table: SLO column shows "-"
        try:
            out["memory"][t] = fetch(
                f"{controller_url}/tables/{t}/memoryStatus")
        # graftcheck: ignore[exception-hygiene] -- read-only dashboard poll;
        # the missing entry drops the table from the memory panel visibly
        except Exception:
            pass   # older controller: memory panel row shows nothing
    if broker_url:
        try:
            debug = fetch(f"{broker_url}/debug")
            out["broker"] = debug.get("queryStats")
            # per-table resource attribution (the top-consumers panel)
            out["tableStats"] = debug.get("tableStats") or {}
            # failure-detector probe states + hedge count (robustness panel)
            out["failureDetector"] = debug.get("failureDetector") or {}
            out["hedgedRequests"] = debug.get("hedgedRequests", 0)
            # adaptive-admission shed state (overload panel)
            out["admission"] = debug.get("admission") or {}
        except Exception as e:
            out["errors"].append(f"broker /debug: {e}")
        try:
            # per-shape workload registry (the workload panel, top-5)
            out["workload"] = fetch(f"{broker_url}/debug/workload?k=5")
        # graftcheck: ignore[exception-hygiene] -- read-only dashboard poll;
        # the missing body visibly drops the whole workload panel
        except Exception:
            pass   # older broker: no workload panel
    try:
        cdebug = fetch(f"{controller_url}/debug")
        out["periodicTasks"] = cdebug.get("periodicTasks", {})
        # sentinel verdicts join the workload panel's REGR column
        out["workloadStatus"] = cdebug.get("workloadStatus") or {}
        # event-journal rollup (incident count joins the events panel header)
        out["eventsSummary"] = cdebug.get("events") or {}
    except Exception as e:
        out["errors"].append(f"controller /debug: {e}")
        out["periodicTasks"] = {}
    try:
        # merged causal timeline (the recent-events panel, newest 8)
        body = fetch(f"{controller_url}/debug/timeline?limit=8")
        out["timeline"] = body.get("events") or []
    # graftcheck: ignore[exception-hygiene] -- read-only dashboard poll;
    # the missing body visibly drops the whole events panel
    except Exception:
        pass   # older controller: no events panel
    return out


def _fmt_lag_ms(v: Any) -> str:
    try:
        ms = float(v)
    except (TypeError, ValueError):
        return "-"
    if ms >= 3_600_000:
        return f"{ms / 3_600_000:.1f}h"
    if ms >= 60_000:
        return f"{ms / 60_000:.1f}m"
    if ms >= 1_000:
        return f"{ms / 1_000:.1f}s"
    return f"{ms:.0f}ms"


def _fmt_bytes(v: Any) -> str:
    try:
        n = float(v)
    except (TypeError, ValueError):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024.0
    return "-"


def render(snap: Dict[str, Any]) -> str:
    """The one-screen table for a snapshot()."""
    lines: List[str] = []
    broker = snap.get("broker") or {}
    head = time.strftime("%H:%M:%S")
    if broker:
        head += (f"  queries={broker.get('numQueries', 0)}"
                 f" avg={broker.get('avgTimeMs', 0)}ms"
                 f" slow={broker.get('numSlowQueries', 0)}"
                 f" hedged={snap.get('hedgedRequests', 0)}")
    lines.append(head)
    cols = f"{'TABLE':<28} {'HEALTH':<10} {'SLO':<12} {'CONS':>4} " \
           f"{'OFFLAG':>8} {'FRESHLAG':>9} {'ROWS/S':>8}  REASONS"
    lines.append(cols)
    lines.append("-" * len(cols))
    for t in sorted(snap.get("tables", {})):
        st = snap["tables"][t]
        slo = (snap.get("slo") or {}).get(t) or {}
        reasons = "; ".join((st.get("reasons") or []) +
                            (slo.get("reasons") or []))
        if st.get("paused") and "paused" not in reasons:
            reasons = ("paused; " + reasons).rstrip("; ")
        lines.append(
            f"{t:<28} {st.get('ingestionState', '?'):<10} "
            f"{slo.get('sloState', '-'):<12} "
            f"{st.get('numConsumingSegments', 0):>4} "
            f"{st.get('maxOffsetLag', 0):>8} "
            f"{_fmt_lag_ms(st.get('maxFreshnessLagMs')):>9} "
            f"{st.get('totalRowsPerSecond', 0):>8}  {reasons}")
    if not snap.get("tables"):
        lines.append("(no tables)")
    consumers = snap.get("tableStats") or {}
    if consumers:
        lines.append("")
        lines.append("top consumers (broker attribution, lifetime)")
        ccols = f"{'TABLE':<28} {'QUERIES':>8} {'DEVMS':>10} {'QWAITMS':>9} " \
                f"{'BYTES':>12} {'ROWS':>12} {'P99MS':>8} {'SLOW':>5} {'ERR':>4}"
        lines.append(ccols)
        lines.append("-" * len(ccols))
        ranked = sorted(consumers.items(),
                        key=lambda kv: kv[1].get("deviceExecMs") or 0.0,
                        reverse=True)
        for t, r in ranked[:10]:
            lines.append(
                f"{t:<28} {int(r.get('numQueries', 0)):>8} "
                f"{r.get('deviceExecMs', 0):>10} "
                f"{r.get('queueWaitMs', 0):>9} "
                f"{int(r.get('bytesFetched', 0)):>12} "
                f"{int(r.get('rowsScanned', 0)):>12} "
                f"{r.get('p99LatencyMs', 0):>8} "
                f"{int(r.get('numSlowQueries', 0)):>5} "
                f"{int(r.get('numErrors', 0)):>4}")
    workload = snap.get("workload") or {}
    if workload.get("shapes"):
        regressions = (snap.get("workloadStatus") or {}).get(
            "regressions") or {}
        lines.append("")
        lines.append(
            f"workload (top shapes by time share; "
            f"{workload.get('totalQueries', 0)} queries over "
            f"{workload.get('shapesSeen', '?')} shapes, "
            f"{workload.get('shapesEvicted', 0)} evicted)")
        wcols = f"{'FINGERPRINT':<17} {'COUNT':>8} {'SHARE':>7} " \
                f"{'P99MS':>9} {'REGR':<10}  PLAN"
        lines.append(wcols)
        lines.append("-" * len(wcols))
        for s in workload["shapes"][:5]:
            fp = s.get("fingerprint", "?")
            regr = (regressions.get(fp) or {}).get("state", "-")
            plan = str(s.get("canonical") or "")
            if len(plan) > 60:
                plan = plan[:57] + "..."
            lines.append(
                f"{fp:<17} {int(s.get('count', 0)):>8} "
                f"{float(s.get('timeSharePct') or 0):>6.2f}% "
                f"{float(s.get('recentP99Ms') or 0):>9.3f} "
                f"{regr:<10}  {plan}")
    admission = snap.get("admission") or {}
    if admission:
        lines.append("")
        state = admission.get("state", "?")
        flag = "" if admission.get("enabled") else " (disabled)"
        lines.append(
            f"admission{flag}: {state}"
            f"  inflight={admission.get('inflight', 0)}"
            f"/{admission.get('queueHigh', '?')}"
            f"/{admission.get('queueMax', '?')}"
            f"  admitted={admission.get('admitted', 0)}"
            f"  sheds={admission.get('sheds', 0)}"
            f"  p99={admission.get('predictedServiceMs', 0)}ms"
            f"(n={admission.get('predictionSamples', 0)})")
        by_table = admission.get("shedByTable") or {}
        if by_table:
            ranked = sorted(by_table.items(), key=lambda kv: -kv[1])[:5]
            shed_reasons = admission.get("shedByReason") or {}
            reasons = " ".join(f"{k}={v}" for k, v in
                               sorted(shed_reasons.items()))
            lines.append("  shed by table: " +
                         " ".join(f"{t}={n}" for t, n in ranked) +
                         (f"   by reason: {reasons}" if reasons else ""))
    memory = {t: m for t, m in (snap.get("memory") or {}).items()
              if m and m.get("memoryState") not in (None, "UNKNOWN")}
    if memory:
        lines.append("")
        lines.append("device memory (controller verdicts)")
        mcols = f"{'TABLE':<28} {'MEM':<10} {'RESIDENT':>10} " \
                f"{'MINHEADROOM':>12}  REASONS"
        lines.append(mcols)
        lines.append("-" * len(mcols))
        servers_seen: Dict[str, Any] = {}
        for t in sorted(memory):
            m = memory[t]
            headroom = m.get("minServerHeadroomPct")
            lines.append(
                f"{t:<28} {m.get('memoryState', '?'):<10} "
                f"{_fmt_bytes(m.get('residentBytes')):>10} "
                f"{(f'{headroom:.1f}%' if headroom is not None else '-'):>12}"
                f"  {'; '.join(m.get('reasons') or [])}")
            servers_seen.update(m.get("servers") or {})
        if servers_seen:
            lines.append("  server headroom: " + " ".join(
                f"{s}={h:.1f}%" if isinstance(h, (int, float)) else f"{s}=-"
                for s, h in sorted(servers_seen.items())))
        # tiered-storage lifecycle rollup: every verdict carries the same
        # cluster-wide counter sums, so max per key is the cluster view
        tiering: Dict[str, int] = {}
        for m in memory.values():
            for k, v in (m.get("tiering") or {}).items():
                if isinstance(v, (int, float)):
                    tiering[k] = max(tiering.get(k, 0), int(v))
        if any(tiering.values()):
            lines.append("  tiering: " + " ".join(
                f"{k}={tiering.get(k, 0)}"
                for k in ("admissions", "promotions", "evictions",
                          "rejections", "coldLoads")))
    detector = snap.get("failureDetector") or {}
    if detector:
        lines.append("")
        lines.append("servers (broker failure detector)")
        dcols = f"{'SERVER':<28} {'STATE':<10} {'FAILS':>6} {'NEXTPROBE':>10}"
        lines.append(dcols)
        lines.append("-" * len(dcols))
        for server_id in sorted(detector):
            d = detector[server_id]
            nxt = d.get("nextProbeInS")
            lines.append(
                f"{server_id:<28} {d.get('state', '?'):<10} "
                f"{int(d.get('consecutiveFailures', 0)):>6} "
                f"{(f'{nxt}s' if nxt is not None else '-'):>10}")
    timeline = snap.get("timeline") or []
    if timeline:
        summary = snap.get("eventsSummary") or {}
        lines.append("")
        lines.append(
            f"recent events (controller timeline; "
            f"{summary.get('timelineEvents', len(timeline))} merged, "
            f"{summary.get('incidents', 0)} incidents)")
        ecols = f"{'AGE':>8} {'NODE':<16} {'KIND':<26} {'SEV':<5}  SUBJECT"
        lines.append(ecols)
        lines.append("-" * len(ecols))
        now_ms = time.time() * 1000.0
        for ev in timeline[-8:]:
            subject = ev.get("segment") or ev.get("table") or ""
            age = _fmt_lag_ms(max(now_ms - float(ev.get("tsMs") or now_ms),
                                  0.0))
            lines.append(
                f"{age:>8} {ev.get('node', '?'):<16} "
                f"{ev.get('kind', '?'):<26} "
                f"{ev.get('severity', '?'):<5}  {subject}")
    failing = {n: s for n, s in (snap.get("periodicTasks") or {}).items()
               if s.get("lastError")}
    for name, s in sorted(failing.items()):
        lines.append(f"! task {name}: {s['lastError']} "
                     f"(errors={s.get('errorCount')})")
    for err in snap.get("errors", []):
        lines.append(f"! {err}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--controller", required=True)
    ap.add_argument("--broker", default=None)
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no screen clearing)")
    ap.add_argument("--token", default=None, help="bearer token")
    args = ap.parse_args(argv)
    fetch = _default_fetcher(args.token)
    while True:
        text = render(snapshot(args.controller, args.broker, fetch))
        if args.once:
            print(text)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
