"""Compatibility verifier: YAML-driven operation sequences against a live cluster.

Analog of the reference's `pinot-compatibility-verifier`
(`compat/CompatibilityOpsRunner.java` + `TableOp`/`SegmentOp`/`QueryOp`/`StreamOp`):
an operator writes a YAML file of cluster operations and expected outcomes, and the
runner executes them in order over HTTP — the same file can be replayed against two
software versions (upgrade/downgrade testing) or used as a smoke test after deploy.

YAML shape:

    description: round-trip smoke
    operations:
      - type: tableOp
        op: CREATE                  # or DELETE
        schemaFile: schema.json     # Schema.to_json format
        tableConfigFile: table.json # TableConfig.to_json format
      - type: segmentOp
        op: UPLOAD                  # or DELETE
        tableName: trips_OFFLINE
        segmentName: trips_1
        inputDataFile: rows.csv     # csv with header
      - type: queryOp
        queryFile: queries.sql              # one SQL per non-empty line
        expectedResultsFile: results.jsonl  # one JSON {"rows": [...]} per line
      - type: streamOp
        op: PRODUCE
        streamTopic: events_topic
        partition: 0
        inputDataFile: rows.jsonl   # one JSON object per line
        tableName: events_REALTIME
        recordCount: 25             # wait until COUNT(*) >= this through the broker

Paths are resolved relative to the YAML file. Each op returns True/False; the run
stops at the first failure (reference behavior) and reports which op failed.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..client import connect
from ..schema import Schema
from ..table import TableConfig


class OpFailure(Exception):
    pass


class CompatibilityOpsRunner:
    def __init__(self, controller_url: str, broker_url: str,
                 token: Optional[str] = None, work_dir: Optional[str] = None,
                 query_timeout_s: float = 60.0):
        self.conn = connect(broker_url, controller=controller_url, token=token)
        self.work_dir = work_dir or "/tmp/pinot_tpu_compat"
        self.query_timeout_s = query_timeout_s
        self.log: List[str] = []

    # -- entry -------------------------------------------------------------
    def run(self, yaml_path: str) -> bool:
        import yaml
        with open(yaml_path) as f:
            doc = yaml.safe_load(f)
        base = os.path.dirname(os.path.abspath(yaml_path))
        ops = doc.get("operations", [])
        for i, op in enumerate(ops):
            kind = op.get("type", "")
            handler = {
                "tableOp": self._table_op,
                "segmentOp": self._segment_op,
                "queryOp": self._query_op,
                "streamOp": self._stream_op,
            }.get(kind)
            if handler is None:
                self.log.append(f"op {i}: unknown type {kind!r}")
                return False
            try:
                handler(op, base)
                self.log.append(f"op {i} ({kind}): OK")
            except Exception as e:
                self.log.append(f"op {i} ({kind}): FAILED — {e}")
                return False
        return True

    # -- ops ----------------------------------------------------------------
    def _table_op(self, op: Dict[str, Any], base: str) -> None:
        action = op.get("op", "CREATE").upper()
        if action == "CREATE":
            schema = Schema.from_json(_load_json(base, op["schemaFile"]))
            cfg = TableConfig.from_json(_load_json(base, op["tableConfigFile"]))
            self.conn.admin.add_schema(schema)
            self.conn.admin.add_table(cfg, num_partitions=op.get("numPartitions", 1))
        elif action == "DELETE":
            cfg = TableConfig.from_json(_load_json(base, op["tableConfigFile"]))
            self.conn.admin.drop_table(cfg.table_name_with_type)
        else:
            raise OpFailure(f"unknown tableOp {action!r}")

    def _segment_op(self, op: Dict[str, Any], base: str) -> None:
        from ..ingest.readers import CsvRecordReader
        from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig

        action = op.get("op", "UPLOAD").upper()
        table = op["tableName"]
        if action == "DELETE":
            from ..cluster.process import http_call
            http_call("DELETE",
                      f"{self.conn.admin.url}/segments/{table}/{op['segmentName']}",
                      token=self.conn.admin.token)
            return
        if action != "UPLOAD":
            raise OpFailure(f"unknown segmentOp {action!r}")
        raw_name = table.rsplit("_", 1)[0]
        schema = Schema.from_json(self.conn.admin.get_schema(raw_name))
        from ..ingest.readers import rows_to_columns
        from ..ingest.transform import TransformPipeline
        reader = CsvRecordReader(os.path.join(base, op["inputDataFile"]))
        cols = TransformPipeline(schema).apply(
            rows_to_columns(list(reader.rows()), schema))
        out = os.path.join(self.work_dir, "segments")
        os.makedirs(out, exist_ok=True)
        seg_dir = SegmentBuilder(schema, SegmentGeneratorConfig()).build(
            cols, out, op["segmentName"])
        self.conn.admin.upload_segment(table, seg_dir)

    def _query_op(self, op: Dict[str, Any], base: str) -> None:
        queries = [q.strip() for q in
                   _read(base, op["queryFile"]).splitlines() if q.strip()]
        expected = [json.loads(line) for line in
                    _read(base, op["expectedResultsFile"]).splitlines()
                    if line.strip()]
        if len(queries) != len(expected):
            raise OpFailure(f"{len(queries)} queries vs {len(expected)} expected rows")
        for sql, want in zip(queries, expected):
            got = self._query_with_retry(sql, want.get("rows"))
            if _norm_rows(got) != _norm_rows(want.get("rows", [])):
                raise OpFailure(f"{sql!r}: got {got}, want {want.get('rows')}")

    def _query_with_retry(self, sql: str, want) -> List[List[Any]]:
        """Segment loads / catalog mirrors converge asynchronously after an
        upload — retry until match or timeout, mirroring the reference's
        post-op wait loops."""
        deadline = time.time() + self.query_timeout_s
        got: List[List[Any]] = []
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            try:
                got = self.conn.execute(sql).rows
                last_err = None
            except Exception as e:
                # table metadata mirrors converge asynchronously — an "unknown
                # table" right after CREATE is a not-yet, not a failure
                last_err = e
                time.sleep(0.2)
                continue
            if want is None or _norm_rows(got) == _norm_rows(want):
                return got
            time.sleep(0.2)
        if last_err is not None:
            raise OpFailure(str(last_err))
        return got

    def _stream_op(self, op: Dict[str, Any], base: str) -> None:
        action = op.get("op", "PRODUCE").upper()
        if action != "PRODUCE":
            raise OpFailure(f"unknown streamOp {action!r}")
        topic = op["streamTopic"]
        partition = int(op.get("partition", 0))
        rows = [line for line in _read(base, op["inputDataFile"]).splitlines()
                if line.strip()]
        # route by the table's stream plugin: kafkalite produces over TCP (works
        # against separately-running cluster processes); the in-memory stream is
        # process-local and only meaningful when the cluster shares this process
        # (in-proc test enclosures)
        stream_cfg = self._table_stream_config(op["tableName"])
        stype = stream_cfg.get("streamType", "memory")
        try:
            if stype == "kafkalite":
                from ..ingest.kafkalite import LogBrokerClient
                client = LogBrokerClient(stream_cfg["properties"]["bootstrap"])
                try:
                    try:
                        client.create_topic(topic, partition + 1)
                    except RuntimeError:
                        pass  # already exists
                    for line in rows:
                        client.produce(topic, line, partition=partition)
                finally:
                    client.close()
            else:
                from ..ingest.stream import MemoryStream
                stream = MemoryStream.create(topic, partition + 1)  # get-or-create
                for line in rows:
                    stream.produce(line, partition=partition)
        except (IndexError, KeyError, OSError) as e:
            raise OpFailure(f"produce to {stype}:{topic}[{partition}] failed: {e}"
                            ) from e
        want_count = op.get("recordCount")
        if want_count is not None:
            raw = op["tableName"].rsplit("_", 1)[0]
            deadline = time.time() + self.query_timeout_s
            n = -1
            while time.time() < deadline:
                try:
                    n = self.conn.execute(f"SELECT COUNT(*) FROM {raw}").rows[0][0]
                except Exception:
                    n = -1  # table not routable yet on this broker mirror
                if n >= int(want_count):
                    return
                time.sleep(0.2)
            raise OpFailure(f"consumed {n} rows, wanted >= {want_count}")


    def _table_stream_config(self, table: str) -> Dict[str, Any]:
        from ..cluster.process import get_json
        try:
            cfg = get_json(f"{self.conn.admin.url}/tables/{table}",
                           token=self.conn.admin.token)
            return cfg.get("streamConfig", {}) or {}
        except Exception:
            return {}


def _read(base: str, rel: str) -> str:
    with open(os.path.join(base, rel)) as f:
        return f.read()


def _load_json(base: str, rel: str) -> Dict[str, Any]:
    return json.loads(_read(base, rel))


def _norm_rows(rows) -> List[tuple]:
    def norm_v(v):
        if isinstance(v, bool):
            return float(v)
        if isinstance(v, (int, float)):
            return round(float(v), 6)
        return v
    return sorted((tuple(norm_v(v) for v in r) for r in rows or []), key=repr)
