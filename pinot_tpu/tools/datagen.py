"""Synthetic data generation + data anonymization tools.

Analogs of the reference CLI commands:
- `GenerateDataCommand` (pinot-tools/.../command/GenerateDataCommand.java):
  schema-driven synthetic rows to CSV/JSONL, with per-column cardinality control
  — for quickstarts, benchmarks, and capacity planning.
- `AnonymizeDataCommand` (pinot-tools/.../command/AnonymizeDataCommand.java +
  tools/anonymizer/): rewrite sensitive column values with generated tokens while
  preserving the properties queries depend on — equality (one consistent mapping
  per column), sort order (tokens sort like the originals, so range predicates
  and ORDER BY behave identically), and null-ness. Numeric columns are
  rank-mapped for the same reason.
"""

from __future__ import annotations

import csv
import json
import math
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..schema import DataType, FieldRole, Schema

_WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
          "hotel", "india", "juliet", "kilo", "lima", "mike", "november",
          "oscar", "papa", "quebec", "romeo", "sierra", "tango"]


# -- generation --------------------------------------------------------------

def generate_columns(schema: Schema, num_rows: int, seed: int = 0,
                     cardinalities: Optional[Dict[str, int]] = None
                     ) -> Dict[str, list]:
    """Column dict of `num_rows` synthetic values per schema field.

    Dimensions draw from a per-column vocabulary of `cardinality` distinct
    values (default 20); metrics are uniform numerics; DATE_TIME columns are
    an increasing epoch-ms walk so time pruning/retention behave naturally."""
    rng = np.random.default_rng(seed)
    cards = cardinalities or {}
    out: Dict[str, list] = {}
    for f in schema.fields:
        card = max(1, int(cards.get(f.name, 20)))
        if f.role == FieldRole.DATE_TIME:
            start = 1_600_000_000_000
            steps = rng.integers(0, 60_000, num_rows)
            vals = (start + np.cumsum(steps)).astype(np.int64)
            if f.data_type in (DataType.INT,):
                vals = (vals // 86_400_000).astype(np.int32)  # day buckets
            out[f.name] = vals.tolist()
        elif f.data_type == DataType.STRING:
            vocab = [f"{_WORDS[i % len(_WORDS)]}_{i}" for i in range(card)]
            out[f.name] = [vocab[i] for i in rng.integers(0, card, num_rows)]
        elif f.data_type in (DataType.INT, DataType.LONG):
            if f.role == FieldRole.DIMENSION:
                out[f.name] = rng.integers(0, card, num_rows).tolist()
            else:
                out[f.name] = rng.integers(0, 10_000, num_rows).tolist()
        elif f.data_type in (DataType.FLOAT, DataType.DOUBLE):
            out[f.name] = np.round(rng.uniform(0, 1000, num_rows), 3).tolist()
        elif f.data_type == DataType.BOOLEAN:
            out[f.name] = (rng.integers(0, 2, num_rows) == 1).tolist()
        elif f.data_type == DataType.JSON:
            out[f.name] = [json.dumps({"k": _WORDS[i % len(_WORDS)],
                                       "n": int(i)})
                           for i in rng.integers(0, card, num_rows)]
        else:
            out[f.name] = [None] * num_rows
    return out


def columns_to_rows(cols: Dict[str, list]) -> List[Dict[str, Any]]:
    names = list(cols)
    n = len(cols[names[0]]) if names else 0
    return [{c: cols[c][i] for c in names} for i in range(n)]


def write_csv(path: str, cols: Dict[str, list]) -> None:
    names = list(cols)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(names)
        for row in zip(*[cols[c] for c in names]):
            w.writerow(["" if v is None else v for v in row])


def write_jsonl(path: str, cols: Dict[str, list]) -> None:
    with open(path, "w") as f:
        for row in columns_to_rows(cols):
            f.write(json.dumps(row) + "\n")


# -- anonymization -----------------------------------------------------------

def _maybe_numeric(values: List[str]) -> list:
    """int column if every non-empty cell parses as int, else float column if
    every cell parses as float, else strings. Empty cells become None."""
    vals = [None if v == "" else v for v in values]
    present = [v for v in vals if v is not None]
    for cast in (int, float):
        try:
            converted = [cast(v) for v in present]
        except (TypeError, ValueError):
            continue
        it = iter(converted)
        return [None if v is None else next(it) for v in vals]
    return vals


class ColumnAnonymizer:
    """One consistent, order-preserving mapping for a column's values.

    Strings map to fixed-width tokens assigned in sorted order
    (`<col>_000000`...), so `a < b` iff `anon(a) < anon(b)`; numerics map to
    their rank. Equality, joins across files anonymized with the same
    instance, GROUP BY cardinality, and range/ORDER BY semantics all
    survive; the values themselves do not."""

    def __init__(self, name: str):
        self.name = name
        self._mapping: Dict[Any, Any] = {}

    def fit(self, values: Iterable[Any]) -> "ColumnAnonymizer":
        distinct = {v for v in values if v is not None}
        numeric = all(isinstance(v, (int, float)) and not isinstance(v, bool)
                      and not (isinstance(v, float) and math.isnan(v))
                      for v in distinct)
        width = max(6, len(str(len(distinct))))
        for rank, v in enumerate(sorted(distinct, key=lambda x: (str(type(x)), x))
                                 if not numeric else sorted(distinct)):
            self._mapping[v] = rank if numeric else f"{self.name}_{rank:0{width}d}"
        return self

    def apply(self, values: Sequence[Any]) -> List[Any]:
        return [None if v is None else self._mapping.get(v, v) for v in values]


def anonymize_columns(cols: Dict[str, list], columns: Sequence[str],
                      anonymizers: Optional[Dict[str, ColumnAnonymizer]] = None
                      ) -> Dict[str, list]:
    """Anonymize the named columns; pass the same `anonymizers` dict across
    multiple files to keep mappings (and joins) consistent. Order preservation
    is guaranteed within one fitted file set; values first seen in later files
    keep equality/join semantics but may sort after earlier tokens."""
    anonymizers = anonymizers if anonymizers is not None else {}
    out = dict(cols)
    for c in columns:
        if c not in cols:
            continue
        anon = anonymizers.get(c)
        if anon is None:
            anon = anonymizers[c] = ColumnAnonymizer(c).fit(cols[c])
        else:
            # extend the mapping with values unseen in earlier files
            missing = [v for v in cols[c]
                       if v is not None and v not in anon._mapping]
            if missing:
                refit = ColumnAnonymizer(c)
                refit.fit(list(anon._mapping) + missing)
                # keep already-issued tokens stable; only add new ones
                for v, tok in refit._mapping.items():
                    anon._mapping.setdefault(v, tok)
        out[c] = anon.apply(cols[c])
    return out


def anonymize_file(in_path: str, out_path: str, columns: Sequence[str],
                   anonymizers: Optional[Dict[str, ColumnAnonymizer]] = None
                   ) -> None:
    """CSV/JSONL in -> same format out with the named columns anonymized."""
    if in_path.endswith(".csv"):
        with open(in_path, newline="") as f:
            rows = list(csv.DictReader(f))
        names = list(rows[0]) if rows else []
        # CSV reads everything as strings — restore numerics first, or numeric
        # columns would be token-mapped lexicographically ('10' < '9'),
        # breaking order preservation and re-ingestability
        cols = {c: _maybe_numeric([r[c] for r in rows]) for c in names}
        out = anonymize_columns(cols, columns, anonymizers)
        write_csv(out_path, out)
    else:
        with open(in_path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        names = list(rows[0]) if rows else []
        cols = {c: [r.get(c) for r in rows] for c in names}
        out = anonymize_columns(cols, columns, anonymizers)
        write_jsonl(out_path, out)


# -- schema inference (reference: JsonToPinotSchema / AvroSchemaToPinotSchema
# CLI commands — derive a Schema from sample data) ----------------------------

def infer_schema(path: str, table_name: Optional[str] = None,
                 time_column: Optional[str] = None) -> "object":
    """Infer a Schema from a CSV/JSONL sample: int/float columns become
    metrics, strings become dimensions, lists become multi-value dimensions,
    and a column named like a timestamp (or passed as `time_column`) becomes
    the DATE_TIME field."""
    from ..schema import DataType, FieldRole, FieldSpec, Schema
    if path.endswith(".csv"):
        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
        cols = {c: _maybe_numeric([r[c] for r in rows]) for c in (rows[0] if rows else [])}
    else:
        with open(path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
        names: List[str] = []
        for r in rows:  # union over ALL rows: later-appearing fields count too
            for c in r:
                if c not in names:
                    names.append(c)
        cols = {c: [r.get(c) for r in rows] for c in names}

    def looks_time(name: str) -> bool:
        n = name.lower()
        return n in ("ts", "time", "timestamp", "date", "datetime") \
            or n.endswith(("_ts", "_time", "_at", "_date", "timemillis"))

    if time_column is not None and time_column not in cols:
        raise ValueError(f"time column {time_column!r} not found in {path}")
    fields = []
    for name, vals in cols.items():
        present = [v for v in vals if v is not None]
        if name == time_column and not (
                present and all(isinstance(v, int) and not isinstance(v, bool)
                                for v in present)):
            raise ValueError(
                f"time column {time_column!r} must be integer epoch values; "
                f"got {type(present[0]).__name__ if present else 'no values'} — "
                "convert before inference (DATE_TIME columns are epoch-typed)")
        if any(isinstance(v, list) for v in present):
            inner = [x for v in present if isinstance(v, list) for x in v]
            dt = DataType.INT if all(isinstance(x, int) for x in inner) \
                else DataType.DOUBLE if all(isinstance(x, (int, float))
                                            for x in inner) else DataType.STRING
            fields.append(FieldSpec(name, dt, FieldRole.DIMENSION,
                                    single_value=False))
            continue
        if all(isinstance(v, bool) for v in present) and present:
            fields.append(FieldSpec(name, DataType.BOOLEAN, FieldRole.METRIC))
        elif all(isinstance(v, int) and not isinstance(v, bool)
                 for v in present) and present:
            big = max(abs(v) for v in present) > (1 << 31) - 1
            dt = DataType.LONG if big else DataType.INT
            if name == time_column or (time_column is None and looks_time(name)):
                fields.append(FieldSpec(name, DataType.LONG, FieldRole.DATE_TIME))
            else:
                fields.append(FieldSpec(name, dt, FieldRole.METRIC))
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool)
                 for v in present) and present:
            fields.append(FieldSpec(name, DataType.DOUBLE, FieldRole.METRIC))
        else:
            fields.append(FieldSpec(name, DataType.STRING, FieldRole.DIMENSION))
    return Schema(table_name or os.path.splitext(os.path.basename(path))[0],
                  fields)
