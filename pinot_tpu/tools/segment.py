"""Segment tools: dump (metadata + sample rows) and verify (integrity check).

Analog of the reference's segment tooling (`pinot-tools/.../SegmentDumpTool.java`,
`ValidateSegmentCommand` / `CrcUtils`): inspect what a segment directory holds
and prove it loads, decodes, and matches its recorded CRC.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..segment import format as fmt
from ..segment.reader import load_segment


def dump_segment(seg_dir: str, max_rows: int = 10) -> Dict[str, Any]:
    """Human-oriented summary: metadata, per-column stats, first rows."""
    seg = load_segment(seg_dir)
    cols: Dict[str, Any] = {}
    for name in seg.column_names:
        r = seg.column(name)
        cols[name] = {
            "dataType": r.data_type.value,
            "hasDictionary": r.has_dictionary,
            "cardinality": r.cardinality,
            "sorted": r.is_sorted,
            "multiValue": getattr(r, "is_multi_value", False),
            "indexes": r.index_types,
            "minValue": _js(r.min_value),
            "maxValue": _js(r.max_value),
            "hasNulls": bool(r.meta.get("hasNulls", False)),
        }
    n = min(max_rows, seg.num_docs)
    sample_cols = {c: _head_values(seg.column(c), n) for c in seg.column_names}
    rows = [[_js(sample_cols[c][i]) for c in seg.column_names] for i in range(n)]
    return {
        "segmentName": seg.name,
        "tableName": seg.metadata.get("tableName"),
        "totalDocs": seg.num_docs,
        "formatVersion": seg.metadata.get("formatVersion"),
        "crc": fmt.read_json(os.path.join(seg_dir, fmt.CREATION_META_FILE))["crc"],
        "columns": cols,
        "sampleColumns": seg.column_names,
        "sampleRows": rows,
        "starTrees": len(seg.star_trees),
    }


def _head_values(reader, n: int) -> List[Any]:
    """First n decoded values WITHOUT materializing the whole column — dumping
    10 sample rows of a 10M-doc segment must not decode 10M values."""
    if getattr(reader, "is_multi_value", False):
        off = np.asarray(reader.mv_offsets)[:n + 1]
        flat = reader.dictionary.take(
            np.asarray(reader.fwd[:off[-1]]).astype(np.int64))
        return [flat[off[i]:off[i + 1]] for i in range(n)]
    head = np.asarray(reader.fwd[:n])
    if not reader.has_dictionary:
        return list(head)
    return list(reader.dictionary.take(head.astype(np.int64)))


def verify_segment(seg_dir: str) -> Dict[str, Any]:
    """Integrity checks; returns {ok, checks: [{name, ok, detail}]}.

    Checks: metadata parse, CRC match, every column's forward index loads with
    the advertised row count, dictionaries decode every id, MV offsets are
    monotonic and cover the flat index, null bitmaps sized right.
    """
    checks: List[Dict[str, Any]] = []

    def check(name: str, fn) -> bool:
        try:
            detail = fn()
            checks.append({"name": name, "ok": True, "detail": detail or ""})
            return True
        except Exception as e:
            checks.append({"name": name, "ok": False,
                           "detail": f"{type(e).__name__}: {e}"})
            return False

    seg_holder: Dict[str, Any] = {}

    def load():
        seg_holder["seg"] = load_segment(seg_dir)
        return f"{seg_holder['seg'].num_docs} docs"
    if not check("load", load):
        return {"ok": False, "checks": checks}
    seg = seg_holder["seg"]

    def crc():
        recorded = fmt.read_json(
            os.path.join(seg_dir, fmt.CREATION_META_FILE))["crc"]
        actual = fmt.segment_crc(seg_dir)
        if recorded != actual:
            raise ValueError(f"recorded {recorded} != actual {actual}")
        return f"crc {actual}"
    check("crc", crc)

    for name in seg.column_names:
        def col_check(name=name):
            r = seg.column(name)
            if getattr(r, "is_multi_value", False):
                off = np.asarray(r.mv_offsets)
                if len(off) != r.num_docs + 1:
                    raise ValueError(f"mv offsets length {len(off)}")
                if (np.diff(off) < 0).any():
                    raise ValueError("mv offsets not monotonic")
                if off[-1] != len(r.fwd):
                    raise ValueError(f"mv offsets end {off[-1]} != flat {len(r.fwd)}")
            elif len(r.fwd) != r.num_docs:
                raise ValueError(f"fwd rows {len(r.fwd)} != docs {r.num_docs}")
            if r.has_dictionary:
                ids = np.asarray(r.fwd)
                if len(ids) and int(ids.max()) >= r.cardinality:
                    raise ValueError(f"dict id {int(ids.max())} out of range")
                r.dictionary.take(np.asarray([0, max(0, r.cardinality - 1)],
                                             dtype=np.int64))
            nb = r.null_bitmap
            if nb is not None and len(nb) != r.num_docs:
                raise ValueError(f"null bitmap length {len(nb)}")
            return "ok"
        check(f"column:{name}", col_check)

    return {"ok": all(c["ok"] for c in checks), "checks": checks}


def _js(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.hex()
    return v
