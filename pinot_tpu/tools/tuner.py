"""Table config tuner: recommend indexing/encoding from observed data shape.

Analog of the reference's config recommendation engine
(`pinot-controller/.../recommender/`): given a built segment (the data's
statistical profile) and optionally the query shapes, propose an
IndexingConfig — which columns want inverted/range/bloom indexes, which
metrics should skip the dictionary, where a star-tree pays off.

Heuristics mirror the reference's rules engine, adapted to THIS engine's cost
model: dictionary LUT filters are nearly free on the device (id-interval
compares), so inverted indexes matter mainly for very selective host-path
lookups; no-dictionary raw encoding matters for high-cardinality numerics
(dict adds an indirection the device path must host-materialize anyway).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..segment.reader import ImmutableSegment, load_segment
from ..table import IndexingConfig


def analyze_segment(seg_or_dir) -> Dict[str, Dict[str, Any]]:
    """Per-column profile: cardinality ratio, type, encoding, MV-ness."""
    seg: ImmutableSegment = (seg_or_dir if isinstance(seg_or_dir, ImmutableSegment)
                             else load_segment(seg_or_dir))
    out: Dict[str, Dict[str, Any]] = {}
    n = max(seg.num_docs, 1)
    for name in seg.column_names:
        r = seg.column(name)
        card = r.cardinality if r.has_dictionary else None
        out[name] = {
            "dataType": r.data_type.value,
            "numeric": r.data_type.is_numeric,
            "hasDictionary": r.has_dictionary,
            "cardinality": card,
            "cardinalityRatio": (card / n) if card is not None else 1.0,
            "multiValue": getattr(r, "is_multi_value", False),
            "sorted": r.is_sorted,
            "indexes": list(r.index_types),
        }
    return out


def recommend(seg_or_dir, filter_columns: Optional[List[str]] = None,
              group_by_columns: Optional[List[str]] = None,
              agg_columns: Optional[List[str]] = None) -> Dict[str, Any]:
    """IndexingConfig proposal + per-recommendation rationale.

    `filter_columns`/`group_by_columns`/`agg_columns` describe the workload
    (the reference feeds query patterns into its rules engine); omitted, every
    dimension is assumed filterable.
    """
    profile = analyze_segment(seg_or_dir)
    filt = set(filter_columns if filter_columns is not None else
               [c for c, p in profile.items() if not p["numeric"]])
    group = set(group_by_columns or [])
    aggs = set(agg_columns or [])

    cfg = IndexingConfig()
    why: List[str] = []
    for col, p in profile.items():
        ratio = p["cardinalityRatio"]
        if p["numeric"] and not p["multiValue"] and ratio > 0.7 \
                and col not in group:
            cfg.no_dictionary_columns.append(col)
            why.append(f"{col}: cardinality ratio {ratio:.2f} > 0.7 — raw "
                       f"encoding (dictionary adds indirection without reuse); "
                       f"range predicates ride device compares + min/max "
                       f"metadata pruning (range indexes need dict ids)")
            if col in filt:
                cfg.bloom_filter_columns.append(col)
                why.append(f"{col}: raw + filtered — bloom filter folds "
                           f"absent-value EQ to constant false at plan time")
            continue
        if col in filt and p["hasDictionary"] and p["numeric"] \
                and not p["multiValue"] and 0.1 <= ratio <= 0.7:
            cfg.range_index_columns.append(col)
            why.append(f"{col}: dict-encoded filtered numeric — range index "
                       f"for selective host-path range predicates")
        if col in filt and p["hasDictionary"]:
            if p["cardinality"] is not None and p["cardinality"] <= 10_000 \
                    and ratio < 0.1:
                cfg.inverted_index_columns.append(col)
                why.append(f"{col}: low-cardinality filtered dimension — "
                           f"inverted index for very selective host lookups "
                           f"(device LUT filters stay free either way)")
    # star-tree: a few low-cardinality group dimensions + numeric aggregations
    st_dims = [c for c in group
               if profile.get(c, {}).get("cardinality") is not None
               and profile[c]["cardinality"] <= 1000
               and not profile[c]["multiValue"]]
    if st_dims and aggs:
        pairs = [f"SUM__{a}" for a in sorted(aggs)
                 if profile.get(a, {}).get("numeric")]
        if pairs:
            cfg.star_tree_configs.append({
                "dimensionsSplitOrder": sorted(st_dims),
                "functionColumnPairs": pairs,
                "maxLeafRecords": 10_000,
            })
            why.append(f"star-tree over {sorted(st_dims)}: repeated group-bys "
                       f"with bounded key space pre-aggregate well")
    return {"indexing": cfg.to_json(), "rationale": why, "profile": profile}
