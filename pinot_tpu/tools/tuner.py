"""Table config tuner: recommend indexing/encoding from observed data shape.

Analog of the reference's config recommendation engine
(`pinot-controller/.../recommender/`): given a built segment (the data's
statistical profile) and optionally the query shapes, propose an
IndexingConfig — which columns want inverted/range/bloom indexes, which
metrics should skip the dictionary, where a star-tree pays off.

Heuristics mirror the reference's rules engine, adapted to THIS engine's cost
model: dictionary LUT filters are nearly free on the device (id-interval
compares), so inverted indexes matter mainly for very selective host-path
lookups; no-dictionary raw encoding matters for high-cardinality numerics
(dict adds an indirection the device path must host-materialize anyway).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..segment.reader import ImmutableSegment, load_segment
from ..table import IndexingConfig


def analyze_segment(seg_or_dir) -> Dict[str, Dict[str, Any]]:
    """Per-column profile: cardinality ratio, type, encoding, MV-ness."""
    seg: ImmutableSegment = (seg_or_dir if isinstance(seg_or_dir, ImmutableSegment)
                             else load_segment(seg_or_dir))
    out: Dict[str, Dict[str, Any]] = {}
    n = max(seg.num_docs, 1)
    for name in seg.column_names:
        r = seg.column(name)
        card = r.cardinality if r.has_dictionary else None
        out[name] = {
            "dataType": r.data_type.value,
            "numeric": r.data_type.is_numeric,
            "hasDictionary": r.has_dictionary,
            "cardinality": card,
            "cardinalityRatio": (card / n) if card is not None else 1.0,
            "multiValue": getattr(r, "is_multi_value", False),
            "sorted": r.is_sorted,
            "indexes": list(r.index_types),
        }
    return out


def recommend(seg_or_dir, filter_columns: Optional[List[str]] = None,
              group_by_columns: Optional[List[str]] = None,
              agg_columns: Optional[List[str]] = None) -> Dict[str, Any]:
    """IndexingConfig proposal + per-recommendation rationale.

    `filter_columns`/`group_by_columns`/`agg_columns` describe the workload
    (the reference feeds query patterns into its rules engine); omitted, every
    dimension is assumed filterable.
    """
    profile = analyze_segment(seg_or_dir)
    filt = set(filter_columns if filter_columns is not None else
               [c for c, p in profile.items() if not p["numeric"]])
    group = set(group_by_columns or [])
    aggs = set(agg_columns or [])

    cfg = IndexingConfig()
    why: List[str] = []
    for col, p in profile.items():
        ratio = p["cardinalityRatio"]
        if p["numeric"] and not p["multiValue"] and ratio > 0.7 \
                and col not in group:
            cfg.no_dictionary_columns.append(col)
            why.append(f"{col}: cardinality ratio {ratio:.2f} > 0.7 — raw "
                       f"encoding (dictionary adds indirection without reuse); "
                       f"range predicates ride device compares + min/max "
                       f"metadata pruning (range indexes need dict ids)")
            if col in filt:
                cfg.bloom_filter_columns.append(col)
                why.append(f"{col}: raw + filtered — bloom filter folds "
                           f"absent-value EQ to constant false at plan time")
            continue
        if col in filt and p["hasDictionary"] and p["numeric"] \
                and not p["multiValue"] and 0.1 <= ratio <= 0.7:
            cfg.range_index_columns.append(col)
            why.append(f"{col}: dict-encoded filtered numeric — range index "
                       f"for selective host-path range predicates")
        if col in filt and p["hasDictionary"]:
            if p["cardinality"] is not None and p["cardinality"] <= 10_000 \
                    and ratio < 0.1:
                cfg.inverted_index_columns.append(col)
                why.append(f"{col}: low-cardinality filtered dimension — "
                           f"inverted index for very selective host lookups "
                           f"(device LUT filters stay free either way)")
    # star-tree: a few low-cardinality group dimensions + numeric aggregations
    st_dims = [c for c in group
               if profile.get(c, {}).get("cardinality") is not None
               and profile[c]["cardinality"] <= 1000
               and not profile[c]["multiValue"]]
    if st_dims and aggs:
        pairs = [f"SUM__{a}" for a in sorted(aggs)
                 if profile.get(a, {}).get("numeric")]
        if pairs:
            cfg.star_tree_configs.append({
                "dimensionsSplitOrder": sorted(st_dims),
                "functionColumnPairs": pairs,
                "maxLeafRecords": 10_000,
            })
            why.append(f"star-tree over {sorted(st_dims)}: repeated group-bys "
                       f"with bounded key space pre-aggregate well")
    return {"indexing": cfg.to_json(), "rationale": why, "profile": profile}


# ---------------------------------------------------------------------------
# workload-driven advisors (reference: the recommender's rules engine inputs —
# schema + query patterns + throughput numbers,
# pinot-controller/.../recommender/rules/impl/*.java)
# ---------------------------------------------------------------------------

def analyze_workload(queries: List[str]) -> Dict[str, Any]:
    """Parse representative queries into per-column usage stats (the
    reference's `QueryWithWeightAndRules` input): EQ/IN filter hits, range
    filter hits, group-by hits, aggregation args, JSON_MATCH/TEXT_MATCH use."""
    from ..sql.ast import Function, Identifier, walk
    from ..sql.parser import parse_query

    stats: Dict[str, Dict[str, int]] = {}

    def bump(col: str, kind: str) -> None:
        stats.setdefault(col, {"eq": 0, "range": 0, "group": 0, "agg": 0,
                               "json": 0, "text": 0})[kind] += 1

    for sql in queries:
        stmt = parse_query(sql)
        if stmt.where is not None:
            for node in walk(stmt.where):
                if not isinstance(node, Function):
                    continue
                args = node.args
                col = (args[0].name if args and isinstance(args[0], Identifier)
                       else None)
                if col is None:
                    continue
                if node.name in ("eq", "in", "in_id_set"):
                    bump(col, "eq")
                elif node.name in ("gt", "gte", "lt", "lte", "between"):
                    bump(col, "range")
                elif node.name == "json_match":
                    bump(col, "json")
                elif node.name == "text_match":
                    bump(col, "text")
        for e in stmt.group_by:
            if isinstance(e, Identifier):
                bump(e.name, "group")
        for e, _alias in stmt.select:
            if isinstance(e, Function):
                for a in e.args:
                    if isinstance(a, Identifier) and a.name != "*":
                        bump(a.name, "agg")
    return stats


def recommend_partitioning(seg_or_dir, queries: List[str],
                           num_servers: int = 2,
                           target_qps: float = 0.0) -> Dict[str, Any]:
    """Partition-column + count advice (reference: PinotTablePartitionRule /
    KafkaPartitionRule): the best partition column is the most EQ-filtered
    column whose cardinality comfortably exceeds the partition count — then
    every EQ query prunes to 1/N of segments, multiplying broker QPS."""
    from ..sql.ast import Function, Identifier, walk
    from ..sql.parser import parse_query
    profile = analyze_segment(seg_or_dir)
    # per-QUERY presence (not predicate hits): the score is "what fraction of
    # queries would prune on this column" — a query EQ-filtering the column
    # five times still prunes exactly once
    queries_with_eq: Dict[str, int] = {}
    for sql in queries:
        stmt = parse_query(sql)
        cols = set()
        if stmt.where is not None:
            for node in walk(stmt.where):
                if isinstance(node, Function) \
                        and node.name in ("eq", "in", "in_id_set") \
                        and node.args and isinstance(node.args[0], Identifier):
                    cols.add(node.args[0].name)
        for c in cols:
            queries_with_eq[c] = queries_with_eq.get(c, 0) + 1
    total_q = max(len(queries), 1)
    # one partition per server core-equivalent; pow2 for stable hashing
    num_partitions = 1
    while num_partitions < num_servers * 4:
        num_partitions *= 2
    best, best_score = None, 0.0
    for col, nq in queries_with_eq.items():
        p = profile.get(col)
        if p is None or p["multiValue"]:
            continue
        card = p["cardinality"] if p["cardinality"] is not None else 1 << 30
        if card < num_partitions * 4:
            continue   # skewed partitions: too few distinct values
        score = nq / total_q
        if score > best_score:
            best, best_score = col, score
    out: Dict[str, Any] = {"numPartitions": num_partitions, "rationale": []}
    if best is None or best_score < 0.2:
        out["partitionColumn"] = None
        out["rationale"].append(
            "no column is EQ-filtered in >=20% of queries with enough "
            "cardinality — partitioning would not prune, skip it")
    else:
        out["partitionColumn"] = best
        out["rationale"].append(
            f"{best}: EQ-filtered in {best_score:.0%} of queries with "
            f"cardinality {profile[best]['cardinality']} >= "
            f"4x{num_partitions} partitions — EQ queries prune to "
            f"1/{num_partitions} of segments")
        if target_qps:
            out["rationale"].append(
                f"at {target_qps:.0f} qps, pruned fan-out cuts per-server "
                f"query load ~{num_partitions}x on the partitioned column")
    return out


# measured single-partition realtime consume rate of THIS engine
# (bench.py ingest_rows_per_sec: kafkalite fetch->decode->MutableSegment.index)
ENGINE_CONSUME_ROWS_PER_SEC = 25_000.0


def recommend_realtime_provisioning(events_per_sec: float, avg_row_bytes: int,
                                    retention_hours: int = 72,
                                    host_memory_gb: float = 16.0,
                                    num_hosts: int = 2,
                                    flush_target_mb: int = 200
                                    ) -> Dict[str, Any]:
    """Realtime provisioning advice (reference: RealtimeProvisioningRule +
    MemoryEstimator): stream partitions from the consume-rate budget,
    flush threshold from the target completed-segment size, per-host memory
    from consuming + retained completed segments."""
    partitions = max(1, -(-int(events_per_sec) //
                          int(ENGINE_CONSUME_ROWS_PER_SEC)))
    flush_rows = max(10_000, int(flush_target_mb * (1 << 20) /
                                 max(avg_row_bytes, 1)))
    # consuming memory: the mutable segment holds flush_rows rows (+indexes,
    # ~2x raw) per partition; partitions spread across hosts. Completed
    # segments live on DISK; what stays memory-resident is the scan-hot
    # working set (stacked device/HBM columns — SegmentSetBlock), estimated
    # as a fraction of retained bytes.
    HOT_FRACTION = 0.2
    consuming_mb = (flush_rows * avg_row_bytes * 2) / (1 << 20)
    parts_per_host = -(-partitions // max(num_hosts, 1))
    retained_rows = events_per_sec * retention_hours * 3600
    retained_mb = retained_rows * avg_row_bytes / (1 << 20)
    per_host_mb = (parts_per_host * consuming_mb
                   + retained_mb * HOT_FRACTION / max(num_hosts, 1))
    fits = per_host_mb < host_memory_gb * 1024 * 0.7
    out = {
        "numPartitions": partitions,
        "flushThresholdRows": flush_rows,
        "consumingMemoryMbPerPartition": round(consuming_mb, 1),
        "estimatedPerHostMb": round(per_host_mb, 1),
        "retainedDiskMbPerHost": round(retained_mb / max(num_hosts, 1), 1),
        "fitsInMemory": fits,
        "rationale": [
            f"{partitions} partitions: {events_per_sec:.0f} events/s over a "
            f"measured ~{ENGINE_CONSUME_ROWS_PER_SEC:.0f} rows/s per-partition "
            f"consume rate",
            f"flush at {flush_rows} rows: completed segments land near "
            f"{flush_target_mb}MB ({avg_row_bytes}B/row)",
        ],
    }
    if not fits:
        need = -(-per_host_mb * num_hosts //
                 int(host_memory_gb * 1024 * 0.7))
        out["recommendedNumHosts"] = int(need)
        out["rationale"].append(
            f"estimated {per_host_mb:.0f}MB/host (consuming + ~"
            f"{HOT_FRACTION:.0%} hot working set of retained data) exceeds "
            f"70% of {host_memory_gb:.0f}GB — scale to ~{int(need)} hosts, "
            f"shorten retention, or tier old segments")
    return out


def recommend_from_workload(seg_or_dir, queries: List[str],
                            num_servers: int = 2,
                            target_qps: float = 0.0) -> Dict[str, Any]:
    """Full workload-driven recommendation: index advice (bloom/inverted/
    range/no-dictionary/json/star-tree) from PARSED query patterns + the
    partition advisor, one report (reference: the recommender endpoint taking
    schema + queriesWithWeights)."""
    usage = analyze_workload(queries)
    filt = [c for c, u in usage.items()
            if u["eq"] or u["range"] or u["json"] or u["text"]]
    group = [c for c, u in usage.items() if u["group"]]
    aggs = [c for c, u in usage.items() if u["agg"]]
    rec = recommend(seg_or_dir, filter_columns=filt, group_by_columns=group,
                    agg_columns=aggs)
    profile = rec["profile"]
    # JSON index rule (reference: JsonIndexRule): JSON_MATCH-ed columns
    for col, u in usage.items():
        if u["json"] and col in profile \
                and col not in rec["indexing"]["jsonIndexColumns"]:
            rec["indexing"]["jsonIndexColumns"].append(col)
            rec["rationale"].append(
                f"{col}: JSON_MATCH in the workload — json index turns the "
                f"path predicate into a posting-list lookup")
        if u["text"] and col in profile \
                and col not in rec["indexing"]["textIndexColumns"]:
            rec["indexing"]["textIndexColumns"].append(col)
            rec["rationale"].append(
                f"{col}: TEXT_MATCH in the workload — text index required")
    # sorted column rule (reference: InvertedSortedIndexJointRule): the most
    # EQ-filtered low-ratio column pays for sorting at build time
    eq_cols = sorted((u["eq"], c) for c, u in usage.items()
                     if u["eq"] and c in profile
                     and not profile[c]["multiValue"]
                     and profile[c]["cardinalityRatio"] < 0.5)
    if eq_cols and rec["indexing"].get("sortedColumn") is None:
        rec["indexing"]["sortedColumn"] = eq_cols[-1][1]
        rec["rationale"].append(
            f"{eq_cols[-1][1]}: most EQ-filtered column — sorting makes its "
            f"EQ/range predicates contiguous doc ranges (no index needed)")
    rec["partitioning"] = recommend_partitioning(
        seg_or_dir, queries, num_servers=num_servers, target_qps=target_qps)
    return rec
