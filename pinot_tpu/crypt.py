"""Segment crypter SPI: encryption at rest for deep-store segment blobs.

Analog of the reference's `PinotCrypter`
(`pinot-spi/src/main/java/org/apache/pinot/spi/crypt/PinotCrypter.java` +
`PinotCrypterFactory`): a named, config-instantiated codec applied when a
segment tar is written to the deep store and reversed on fetch. The seam is
`EncryptedFS`, a DeepStoreFS wrapper — every producer/consumer (controller
upload, completion commit, peer heal, server/minion fetch through the
controller proxy) goes through the deep-store interface, so wrapping it once
encrypts the entire at-rest surface.

Config (controller): `deepstore.crypter=<name>` + `deepstore.crypter.key=...`.
Built-ins: `noop`, and `xor` — a stand-in proving the SPI seam (NOT
cryptographically secure; production deployments register a real cipher via
`register_crypter`, exactly like the reference's plugin factory).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Type

from .cluster.deepstore import DeepStoreFS

_MAGIC = b"PCRY"


class SegmentCrypter:
    """SPI: codec for deep-store blobs.

    The STREAM methods are the contract EncryptedFS uses (segment tars can
    be GBs; the deep store's constant-memory invariant must hold through
    encryption). The default implementations chunk through encrypt/decrypt,
    which is only correct for codecs whose output is chunk-independent at
    `chunk_size()` boundaries — stateful ciphers override the stream pair."""

    name = ""
    CHUNK = 8 << 20

    def __init__(self, config: Optional[Dict[str, str]] = None):
        self.config = config or {}

    def encrypt(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, data: bytes) -> bytes:
        raise NotImplementedError

    def chunk_size(self) -> int:
        return self.CHUNK

    def encrypt_stream(self, src, dst) -> None:
        n = self.chunk_size()
        while True:
            block = src.read(n)
            if not block:
                return
            dst.write(self.encrypt(block))

    def decrypt_stream(self, src, dst) -> None:
        n = self.chunk_size()
        while True:
            block = src.read(n)
            if not block:
                return
            dst.write(self.decrypt(block))


class NoOpCrypter(SegmentCrypter):
    name = "noop"

    def encrypt(self, data: bytes) -> bytes:
        return data

    def decrypt(self, data: bytes) -> bytes:
        return data


class XorCrypter(SegmentCrypter):
    """Keyed byte-XOR stand-in: proves the encrypt/decrypt seam end to end
    (the at-rest blob is not a readable tar) without a crypto dependency."""

    name = "xor"

    def __init__(self, config: Optional[Dict[str, str]] = None):
        super().__init__(config)
        key = (self.config.get("key") or "pinot-tpu").encode()
        self._key = key

    def chunk_size(self) -> int:
        # chunk-independent XOR requires chunks aligned to the key length
        # (each chunk restarts the key stream)
        return max(self.CHUNK - self.CHUNK % len(self._key), len(self._key))

    def _xor(self, data: bytes) -> bytes:
        import numpy as np
        k = np.frombuffer((self._key * (len(data) // len(self._key) + 1))
                          [:len(data)], dtype=np.uint8)
        return (np.frombuffer(data, dtype=np.uint8) ^ k).tobytes()

    def encrypt(self, data: bytes) -> bytes:
        return self._xor(data)

    def decrypt(self, data: bytes) -> bytes:
        return self._xor(data)


_CRYPTERS: Dict[str, Type[SegmentCrypter]] = {}


def register_crypter(cls: Type[SegmentCrypter]) -> None:
    _CRYPTERS[cls.name] = cls


register_crypter(NoOpCrypter)
register_crypter(XorCrypter)


def create_crypter(name: str,
                   config: Optional[Dict[str, str]] = None) -> SegmentCrypter:
    cls = _CRYPTERS.get(name)
    if cls is None:
        raise KeyError(f"unknown crypter {name!r} "
                       f"(registered: {sorted(_CRYPTERS)})")
    return cls(config)


class EncryptedFS(DeepStoreFS):
    """DeepStoreFS wrapper applying the crypter on write and fetch.

    Blobs are framed `PCRY | u8 name-len | name | ciphertext` so a fetch of a
    legacy plaintext blob (pre-encryption uploads) passes through unchanged,
    and a blob encrypted under a crypter this process doesn't know fails
    LOUDLY instead of untarring garbage."""

    scheme = "encrypted"

    def __init__(self, inner: DeepStoreFS, crypter: SegmentCrypter):
        self.inner = inner
        self.crypter = crypter

    def upload(self, local_path: str, uri: str) -> None:
        import tempfile
        name = self.crypter.name.encode()
        # private temp file (mkstemp): concurrent uploads of the same source
        # path must not share a temp, and the source dir may be read-only
        fd, tmp = tempfile.mkstemp(suffix=".enc")
        try:
            with open(local_path, "rb") as src, os.fdopen(fd, "wb") as dst:
                dst.write(_MAGIC + bytes([len(name)]) + name)
                self.crypter.encrypt_stream(src, dst)  # constant memory
            self.inner.upload(tmp, uri)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def download(self, uri: str, local_path: str) -> None:
        import tempfile
        self.inner.download(uri, local_path)
        with open(local_path, "rb") as f:
            head = f.read(5)
            if not head.startswith(_MAGIC):
                return  # legacy plaintext blob: pass through
            name = f.read(head[4]).decode()
            if name != self.crypter.name:
                raise ValueError(
                    f"blob {uri!r} encrypted with {name!r}, this process has "
                    f"{self.crypter.name!r}")
            # same dir as the destination: os.replace must not cross devices
            fd, tmp = tempfile.mkstemp(
                suffix=".dec", dir=os.path.dirname(local_path) or ".")
            try:
                with os.fdopen(fd, "wb") as dst:
                    self.crypter.decrypt_stream(f, dst)  # constant memory
            except Exception:
                os.remove(tmp)
                raise
        os.replace(tmp, local_path)

    # metadata ops pass straight through (ciphertext moves/deletes like any blob)
    def delete(self, uri: str) -> None:
        self.inner.delete(uri)

    def exists(self, uri: str) -> bool:
        return self.inner.exists(uri)

    def move(self, src: str, dst: str) -> None:
        self.inner.move(src, dst)

    def listdir(self, uri: str):
        return self.inner.listdir(uri)


def wrap_deepstore_from_config(fs: DeepStoreFS, cfg) -> DeepStoreFS:
    """Apply `deepstore.crypter` config to a freshly created deep store."""
    name = cfg.get_str("deepstore.crypter")
    if not name or name == "noop":
        return fs
    crypter = create_crypter(name, {"key": cfg.get_str("deepstore.crypter.key")
                                    or ""})
    return EncryptedFS(fs, crypter)
