"""Native (C) runtime helpers, built on demand with the system compiler.

The compute path is jax/XLA/pallas; THIS package is the native runtime layer
around it (the reference's equivalent hot helpers are JVM intrinsics /
off-heap utilities). Sources compile once per source-hash into a cached
shared object loaded via ctypes — no pip, no pybind11, and a pure-Python
fallback keeps every feature working when no compiler is available.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_CACHE_DIR = os.environ.get("PINOT_TPU_NATIVE_CACHE",
                            os.path.join(tempfile.gettempdir(),
                                         "pinot_tpu_native"))
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    src = os.path.join(_DIR, "crc32c.c")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so_path = os.path.join(_CACHE_DIR, f"pinot_native_{digest}.so")
    if not os.path.exists(so_path):
        tmp = f"{so_path}.tmp.{os.getpid()}"
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True, timeout=60)
                os.replace(tmp, so_path)   # atomic: racers see whole files
                break
            except (OSError, subprocess.SubprocessError):
                continue
        else:
            return None
    lib = ctypes.CDLL(so_path)
    lib.pinot_crc32c.argtypes = (ctypes.c_char_p, ctypes.c_size_t,
                                 ctypes.c_uint32)
    lib.pinot_crc32c.restype = ctypes.c_uint32
    LL = ctypes.POINTER(ctypes.c_longlong)
    lib.pinot_decode_records.argtypes = (
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_long, LL, LL, LL, LL, LL, LL)
    lib.pinot_decode_records.restype = ctypes.c_long
    lib.pinot_splice_values.argtypes = (
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_longlong, ctypes.c_long,
        ctypes.c_longlong, ctypes.c_ubyte, ctypes.c_char_p, ctypes.c_size_t,
        LL, LL)
    lib.pinot_splice_values.restype = ctypes.c_long
    lib.pinot_json_columns.argtypes = (
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_long,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long), ctypes.c_long,
        ctypes.POINTER(ctypes.c_double), LL,
        ctypes.POINTER(ctypes.c_ubyte), LL, LL, LL, LL,
        ctypes.POINTER(ctypes.c_ubyte))
    lib.pinot_json_columns.restype = ctypes.c_long
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None when no compiler
    works (callers keep their pure-Python fallback)."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is None and not _build_failed:
            try:
                _lib = _build()
            except Exception:
                _lib = None
            if _lib is None:
                _build_failed = True
    return _lib


def crc32c(data: bytes, crc: int = 0) -> Optional[int]:
    """Native CRC-32C, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    return lib.pinot_crc32c(data, len(data), crc)


def splice_values(records_section: bytes, base_offset: int, count: int,
                  min_offset: int, sep: bytes = b","):
    """Native value splice: "v0<sep>v1<sep>..." over records >= min_offset.
    Returns (bytes, n, last_offset) or None (no native lib / malformed) —
    zero per-record Python work; the caller runs ONE batch parse over the
    spliced payload (the realtime consume hot path)."""
    lib = get_lib()
    if lib is None or count <= 0:
        return None
    if count > len(records_section) // 7 + 1:
        return None  # hostile count: bound allocations (see decode_records)
    import numpy as np
    cap = len(records_section) + count + 1
    # np.empty, not create_string_buffer: no zero-fill of the multi-MB
    # scratch, and the tail copy below is out_len bytes, not cap (this
    # wrapper sits on the realtime consume hot path)
    out = np.empty(cap, dtype=np.uint8)
    out_len = ctypes.c_longlong(0)
    last = ctypes.c_longlong(-1)
    n = lib.pinot_splice_values(records_section, len(records_section),
                                base_offset, count, min_offset, sep[0],
                                out.ctypes.data_as(ctypes.c_char_p), cap,
                                ctypes.byref(out_len), ctypes.byref(last))
    if n < 0:
        return None
    return out[:out_len.value].tobytes(), n, last.value


def json_columns(data: bytes, n_records: int, col_names):
    """Schema-directed flat-JSON columnar decode of n_records spliced
    objects. Returns (nums f64[C,N], lints i64[C,N], types u8[C,N],
    str_off i64[C,N], str_len i64[C,N], rec_ranges i64[N,2], bad bool[N])
    as NUMPY views, or None (no native lib / outer structure malformed —
    callers run the whole-batch Python parse instead).

    Cell types: 0 missing, 1 double, 2 string, 3 true, 4 false, 5 null,
    6 escaped string (re-decode the raw range), 8 int64. `bad` rows carry
    a nested value under a schema key or an out-of-int64 number — the
    caller re-parses just those record ranges."""
    import numpy as np
    lib = get_lib()
    if lib is None or n_records <= 0:
        return None
    C = len(col_names)
    name_bytes = [n.encode("utf-8") for n in col_names]
    blob = b"".join(name_bytes)
    offs = (ctypes.c_long * C)()
    lens = (ctypes.c_long * C)()
    o = 0
    for i, nb in enumerate(name_bytes):
        offs[i] = o
        lens[i] = len(nb)
        o += len(nb)
    cells = C * n_records
    nums = np.empty(cells, dtype=np.float64)
    lints = np.empty(cells, dtype=np.int64)
    types = np.zeros(cells, dtype=np.uint8)
    str_off = np.empty(cells, dtype=np.int64)
    str_len = np.empty(cells, dtype=np.int64)
    rec_off = np.empty(n_records, dtype=np.int64)
    rec_len = np.empty(n_records, dtype=np.int64)
    bad = np.zeros(n_records, dtype=np.uint8)
    LLP = ctypes.POINTER(ctypes.c_longlong)
    n = lib.pinot_json_columns(
        data, len(data), n_records, blob, offs, lens, C,
        nums.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        lints.ctypes.data_as(LLP),
        types.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        str_off.ctypes.data_as(LLP), str_len.ctypes.data_as(LLP),
        rec_off.ctypes.data_as(LLP), rec_len.ctypes.data_as(LLP),
        bad.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)))
    if n != n_records:
        return None
    shape = (C, n_records)
    return (nums.reshape(shape), lints.reshape(shape), types.reshape(shape),
            str_off.reshape(shape), str_len.reshape(shape),
            np.stack([rec_off, rec_len], axis=1), bad.astype(bool))


def decode_records(records_section: bytes, base_offset: int, first_ts: int,
                   count: int):
    """Native v2 record-section walk -> [(offset, ts, key|None, value)], or
    None when the native library is unavailable or the input is malformed
    (callers keep the pure-Python walk as the fallback/authority)."""
    lib = get_lib()
    if lib is None or count <= 0:
        return None
    # the count field is producer-controlled (its CRC is the producer's own);
    # every record is >= 7 bytes, so a count beyond that bound is malformed —
    # clamp BEFORE sizing allocations or a hostile batch OOMs the consumer
    if count > len(records_section) // 7 + 1:
        return None
    arr = (ctypes.c_longlong * count)
    offs, ts, koff, klen, voff, vlen = (arr(), arr(), arr(), arr(), arr(),
                                        arr())
    n = lib.pinot_decode_records(records_section, len(records_section),
                                 base_offset, first_ts, count,
                                 offs, ts, koff, klen, voff, vlen)
    if n != count:
        return None
    out = []
    for i in range(count):
        key = (None if koff[i] < 0
               else records_section[koff[i]:koff[i] + klen[i]])
        out.append((offs[i], ts[i],
                    key, records_section[voff[i]:voff[i] + vlen[i]]))
    return out
