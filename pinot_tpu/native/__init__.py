"""Native (C) runtime helpers, built on demand with the system compiler.

The compute path is jax/XLA/pallas; THIS package is the native runtime layer
around it (the reference's equivalent hot helpers are JVM intrinsics /
off-heap utilities). Sources compile once per source-hash into a cached
shared object loaded via ctypes — no pip, no pybind11, and a pure-Python
fallback keeps every feature working when no compiler is available.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_CACHE_DIR = os.environ.get("PINOT_TPU_NATIVE_CACHE",
                            os.path.join(tempfile.gettempdir(),
                                         "pinot_tpu_native"))
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    src = os.path.join(_DIR, "crc32c.c")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so_path = os.path.join(_CACHE_DIR, f"pinot_native_{digest}.so")
    if not os.path.exists(so_path):
        tmp = f"{so_path}.tmp.{os.getpid()}"
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True, timeout=60)
                os.replace(tmp, so_path)   # atomic: racers see whole files
                break
            except (OSError, subprocess.SubprocessError):
                continue
        else:
            return None
    lib = ctypes.CDLL(so_path)
    lib.pinot_crc32c.argtypes = (ctypes.c_char_p, ctypes.c_size_t,
                                 ctypes.c_uint32)
    lib.pinot_crc32c.restype = ctypes.c_uint32
    LL = ctypes.POINTER(ctypes.c_longlong)
    lib.pinot_decode_records.argtypes = (
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_long, LL, LL, LL, LL, LL, LL)
    lib.pinot_decode_records.restype = ctypes.c_long
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None when no compiler
    works (callers keep their pure-Python fallback)."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is None and not _build_failed:
            try:
                _lib = _build()
            except Exception:
                _lib = None
            if _lib is None:
                _build_failed = True
    return _lib


def crc32c(data: bytes, crc: int = 0) -> Optional[int]:
    """Native CRC-32C, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    return lib.pinot_crc32c(data, len(data), crc)


def decode_records(records_section: bytes, base_offset: int, first_ts: int,
                   count: int):
    """Native v2 record-section walk -> [(offset, ts, key|None, value)], or
    None when the native library is unavailable or the input is malformed
    (callers keep the pure-Python walk as the fallback/authority)."""
    lib = get_lib()
    if lib is None or count <= 0:
        return None
    # the count field is producer-controlled (its CRC is the producer's own);
    # every record is >= 7 bytes, so a count beyond that bound is malformed —
    # clamp BEFORE sizing allocations or a hostile batch OOMs the consumer
    if count > len(records_section) // 7 + 1:
        return None
    arr = (ctypes.c_longlong * count)
    offs, ts, koff, klen, voff, vlen = (arr(), arr(), arr(), arr(), arr(),
                                        arr())
    n = lib.pinot_decode_records(records_section, len(records_section),
                                 base_offset, first_ts, count,
                                 offs, ts, koff, klen, voff, vlen)
    if n != count:
        return None
    out = []
    for i in range(count):
        key = (None if koff[i] < 0
               else records_section[koff[i]:koff[i] + klen[i]])
        out.append((offs[i], ts[i],
                    key, records_section[voff[i]:voff[i] + vlen[i]]))
    return out
