/* CRC-32C (Castagnoli), slice-by-8 — the kafka record-batch checksum.
 *
 * Native analog of the reference's org.apache.kafka.common.utils.Crc32C
 * (JVM intrinsic in the JVM); the pure-Python table walk tops out near 1 MB/s,
 * which bottlenecked the whole realtime consume path.  Built on demand by
 * pinot_tpu/native/__init__.py with the system cc; ~GB/s.
 */
#include <stddef.h>
#include <stdint.h>

static uint32_t TBL[8][256];

/* eager init at library load: a lazy `initialized` flag would race under
 * concurrent first use (the flag store can become visible before the table
 * stores, yielding wrong CRCs nondeterministically at startup) */
__attribute__((constructor)) static void init_tables(void) {
    const uint32_t poly = 0x82F63B78u; /* reflected CRC-32C polynomial */
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
        TBL[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = TBL[0][i];
        for (int s = 1; s < 8; s++) {
            c = TBL[0][c & 0xFF] ^ (c >> 8);
            TBL[s][i] = c;
        }
    }
}

uint32_t pinot_crc32c(const uint8_t *buf, size_t len, uint32_t crc) {
    crc ^= 0xFFFFFFFFu;
    while (len && ((uintptr_t)buf & 7)) {          /* align to 8 bytes */
        crc = TBL[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
        len--;
    }
    while (len >= 8) {
        uint32_t lo = crc ^ ((uint32_t)buf[0] | ((uint32_t)buf[1] << 8) |
                             ((uint32_t)buf[2] << 16) | ((uint32_t)buf[3] << 24));
        uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8) |
                      ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
        crc = TBL[7][lo & 0xFF] ^ TBL[6][(lo >> 8) & 0xFF] ^
              TBL[5][(lo >> 16) & 0xFF] ^ TBL[4][lo >> 24] ^
              TBL[3][hi & 0xFF] ^ TBL[2][(hi >> 8) & 0xFF] ^
              TBL[1][(hi >> 16) & 0xFF] ^ TBL[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len--) crc = TBL[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

/* v2 record-section decoder: walks `count` records from the byte span after
 * the batch header's count field, emitting per-record offset/timestamp and
 * key/value byte ranges.  Returns records decoded, or -1 on malformed input.
 * The Python wire module slices keys/values out of the original buffer —
 * the per-record varint walk was the realtime consume path's hot loop. */

static int read_varint(const uint8_t *buf, size_t len, size_t *pos,
                       int64_t *out) {
    uint64_t acc = 0;
    int shift = 0;
    while (*pos < len) {
        uint8_t b = buf[(*pos)++];
        acc |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = (int64_t)(acc >> 1) ^ -((int64_t)(acc & 1));
            return 0;
        }
        shift += 7;
        if (shift > 70) return -1;
    }
    return -1;
}

long pinot_decode_records(const uint8_t *buf, size_t len,
                          long long base_offset, long long first_ts,
                          long max_records,
                          long long *offsets, long long *ts,
                          long long *key_off, long long *key_len,
                          long long *val_off, long long *val_len) {
    size_t pos = 0;
    long n = 0;
    while (n < max_records && pos < len) {
        int64_t rec_len, ts_delta, off_delta, klen, vlen, hdrs;
        if (read_varint(buf, len, &pos, &rec_len) || rec_len < 0) return -1;
        size_t rec_end = pos + (size_t)rec_len;
        if (rec_end > len) return -1;
        if (pos >= rec_end) return -1;
        pos++; /* record attributes */
        if (read_varint(buf, rec_end, &pos, &ts_delta)) return -1;
        if (read_varint(buf, rec_end, &pos, &off_delta)) return -1;
        if (read_varint(buf, rec_end, &pos, &klen)) return -1;
        if (klen >= 0) {
            if (pos + (size_t)klen > rec_end) return -1;
            key_off[n] = (long long)pos;
            key_len[n] = klen;
            pos += (size_t)klen;
        } else {
            key_off[n] = -1;
            key_len[n] = -1;
        }
        if (read_varint(buf, rec_end, &pos, &vlen)) return -1;
        if (vlen >= 0) {
            if (pos + (size_t)vlen > rec_end) return -1;
            val_off[n] = (long long)pos;
            val_len[n] = vlen;
            pos += (size_t)vlen;
        } else {
            val_off[n] = -1;
            val_len[n] = 0;
        }
        /* headers: count then (key varint+bytes, value varint+bytes) each;
         * zigzag on the count mirrors the encoder's uvarint(0) == varint 0 */
        if (read_varint(buf, rec_end, &pos, &hdrs)) return -1;
        if (hdrs < 0) hdrs = 0;
        for (int64_t h = 0; h < hdrs; h++) {
            int64_t hk, hv;
            if (read_varint(buf, rec_end, &pos, &hk) || hk < 0) return -1;
            pos += (size_t)hk;
            if (read_varint(buf, rec_end, &pos, &hv)) return -1;
            if (hv > 0) pos += (size_t)hv;
            if (pos > rec_end) return -1;
        }
        offsets[n] = base_offset + off_delta;
        ts[n] = first_ts + ts_delta;
        n++;
        pos = rec_end;
    }
    return n;
}
