/* CRC-32C (Castagnoli), slice-by-8 — the kafka record-batch checksum.
 *
 * Native analog of the reference's org.apache.kafka.common.utils.Crc32C
 * (JVM intrinsic in the JVM); the pure-Python table walk tops out near 1 MB/s,
 * which bottlenecked the whole realtime consume path.  Built on demand by
 * pinot_tpu/native/__init__.py with the system cc; ~GB/s.
 */
#include <stddef.h>
#include <string.h>
#include <stdlib.h>
#include <stdint.h>

static uint32_t TBL[8][256];

/* eager init at library load: a lazy `initialized` flag would race under
 * concurrent first use (the flag store can become visible before the table
 * stores, yielding wrong CRCs nondeterministically at startup) */
__attribute__((constructor)) static void init_tables(void) {
    const uint32_t poly = 0x82F63B78u; /* reflected CRC-32C polynomial */
    for (int i = 0; i < 256; i++) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
        TBL[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
        uint32_t c = TBL[0][i];
        for (int s = 1; s < 8; s++) {
            c = TBL[0][c & 0xFF] ^ (c >> 8);
            TBL[s][i] = c;
        }
    }
}

uint32_t pinot_crc32c(const uint8_t *buf, size_t len, uint32_t crc) {
    crc ^= 0xFFFFFFFFu;
    while (len && ((uintptr_t)buf & 7)) {          /* align to 8 bytes */
        crc = TBL[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
        len--;
    }
    while (len >= 8) {
        uint32_t lo = crc ^ ((uint32_t)buf[0] | ((uint32_t)buf[1] << 8) |
                             ((uint32_t)buf[2] << 16) | ((uint32_t)buf[3] << 24));
        uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8) |
                      ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
        crc = TBL[7][lo & 0xFF] ^ TBL[6][(lo >> 8) & 0xFF] ^
              TBL[5][(lo >> 16) & 0xFF] ^ TBL[4][lo >> 24] ^
              TBL[3][hi & 0xFF] ^ TBL[2][(hi >> 8) & 0xFF] ^
              TBL[1][(hi >> 16) & 0xFF] ^ TBL[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len--) crc = TBL[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

/* v2 record-section decoder: walks `count` records from the byte span after
 * the batch header's count field, emitting per-record offset/timestamp and
 * key/value byte ranges.  Returns records decoded, or -1 on malformed input.
 * The Python wire module slices keys/values out of the original buffer —
 * the per-record varint walk was the realtime consume path's hot loop. */

static int read_varint(const uint8_t *buf, size_t len, size_t *pos,
                       int64_t *out) {
    uint64_t acc = 0;
    int shift = 0;
    while (*pos < len) {
        uint8_t b = buf[(*pos)++];
        acc |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = (int64_t)(acc >> 1) ^ -((int64_t)(acc & 1));
            return 0;
        }
        shift += 7;
        if (shift > 70) return -1;
    }
    return -1;
}

/* Splice record VALUES from a v2 records section into `out` separated by
 * `sep` (one byte), skipping records below min_offset: "v0<sep>v1<sep>v2".
 * The caller wraps with prefix/suffix (e.g. '[' ... ']') and hands the
 * result to ONE C-level parse — zero per-record Python objects on the
 * realtime consume hot path. Returns the record count spliced, or -1 on
 * malformed input / insufficient out_cap; *out_len gets the bytes written,
 * *last_offset the highest absolute offset spliced. */
long pinot_splice_values(const uint8_t *buf, size_t len,
                         long long base_offset, long max_records,
                         long long min_offset, uint8_t sep,
                         uint8_t *out, size_t out_cap,
                         long long *out_len, long long *last_offset) {
    size_t pos = 0, opos = 0;
    long n = 0;
    while (n < max_records && pos < len) {
        int64_t rec_len, ts_delta, off_delta, klen, vlen;
        if (read_varint(buf, len, &pos, &rec_len) || rec_len < 0) return -1;
        size_t rec_end = pos + (size_t)rec_len;
        if (rec_end > len) return -1;
        if (pos >= rec_end) return -1;
        pos++; /* record attributes */
        if (read_varint(buf, rec_end, &pos, &ts_delta)) return -1;
        if (read_varint(buf, rec_end, &pos, &off_delta)) return -1;
        if (read_varint(buf, rec_end, &pos, &klen)) return -1;
        if (klen > 0) {
            if (pos + (size_t)klen > rec_end) return -1;
            pos += (size_t)klen;
        }
        if (read_varint(buf, rec_end, &pos, &vlen)) return -1;
        if (vlen < 0) vlen = 0;
        if (pos + (size_t)vlen > rec_end) return -1;
        if (base_offset + off_delta >= min_offset) {
            size_t need = (size_t)vlen + (n ? 1 : 0);
            if (opos + need > out_cap) return -1;
            if (n) out[opos++] = sep;
            memcpy(out + opos, buf + pos, (size_t)vlen);
            opos += (size_t)vlen;
            *last_offset = base_offset + off_delta;
            n++;
        }
        pos = rec_end;
    }
    *out_len = (long long)opos;
    return n;
}

/* ------------------------------------------------------------------ */
/* Schema-directed flat-JSON columnar decode.
 *
 * Input: `buf` holds n_records comma-separated FLAT json objects (the
 * output of pinot_splice_values).  For each record r and schema column c
 * the decoder fills the COLUMN-MAJOR cell c*n_records + r:
 *   types:   0 missing | 1 double (nums) | 2 string (str_off/str_len,
 *            escape-free) | 3 true | 4 false | 5 null | 6 string with
 *            escapes (raw range incl. backslashes; caller re-decodes the
 *            cell) | 8 int64 (lints)
 * Unknown keys are skipped (scalar values and balanced nested values
 * alike).  A record whose KNOWN key holds a nested value, or whose
 * structure the decoder cannot walk, sets bad[r]=1 and the caller
 * re-parses just that record range (rec_off/rec_len) in Python.
 * Returns records walked, or -1 when the outer structure is malformed
 * (caller falls back to a whole-batch Python parse). */

static void skip_ws(const uint8_t *b, size_t len, size_t *p) {
    while (*p < len && (b[*p] == ' ' || b[*p] == '\t' || b[*p] == '\n' ||
                        b[*p] == '\r'))
        (*p)++;
}

/* scan a JSON string body starting AFTER the opening quote; returns 0 and
 * sets *end to the closing quote, *esc if any backslash was seen */
static int scan_string(const uint8_t *b, size_t len, size_t *p, int *esc) {
    *esc = 0;
    while (*p < len) {
        uint8_t c = b[(*p)++];
        if (c == '\\') {
            *esc = 1;
            if (*p < len) (*p)++;
        } else if (c == '"') {
            return 0;
        }
    }
    return -1;
}

/* skip one JSON value of any shape (nested ok); returns 0 on success */
static int skip_value(const uint8_t *b, size_t len, size_t *p) {
    int depth = 0;
    skip_ws(b, len, p);
    do {
        if (*p >= len) return -1;
        uint8_t c = b[*p];
        if (c == '"') {
            int esc;
            (*p)++;
            if (scan_string(b, len, p, &esc)) return -1;
        } else if (c == '{' || c == '[') {
            depth++;
            (*p)++;
        } else if (c == '}' || c == ']') {
            depth--;
            (*p)++;
        } else if (c == ',' && depth == 0) {
            return 0;
        } else {
            (*p)++;
        }
        if (depth == 0) {
            /* scalar done when next non-ws is , } or end */
            size_t q = *p;
            skip_ws(b, len, &q);
            if (q >= len || b[q] == ',' || b[q] == '}' || b[q] == ']') {
                *p = q;
                return 0;
            }
        }
    } while (depth > 0 || *p < len);
    return 0;
}

long pinot_json_columns(const uint8_t *buf, size_t len, long n_records,
                        const uint8_t *names, const long *name_off,
                        const long *name_len, long ncols,
                        double *nums, long long *lints, uint8_t *types,
                        long long *str_off, long long *str_len,
                        long long *rec_off, long long *rec_len,
                        uint8_t *bad) {
    size_t p = 0;
    for (long r = 0; r < n_records; r++) {
        skip_ws(buf, len, &p);
        rec_off[r] = (long long)p;
        bad[r] = 0;
        if (p >= len || buf[p] != '{') return -1;
        p++;
        int first = 1;
        for (;;) {
            skip_ws(buf, len, &p);
            if (p >= len) return -1;
            if (buf[p] == '}') { p++; break; }
            if (!first) {
                if (buf[p] != ',') return -1;
                p++;
                skip_ws(buf, len, &p);
            }
            first = 0;
            if (p >= len || buf[p] != '"') return -1;
            p++;
            size_t kstart = p;
            int kesc;
            if (scan_string(buf, len, &p, &kesc)) return -1;
            size_t kend = p - 1; /* closing quote */
            long col = -1;
            if (kesc) {
                /* an escaped KEY could name a schema column once unescaped
                 * (e.g. "clic\u006bs"): this decoder matches raw bytes
                 * only, so the record must be python-re-parsed — skipping
                 * it as unknown would silently null the column */
                bad[r] = 1;
            } else {
                long klen = (long)(kend - kstart);
                for (long c = 0; c < ncols; c++) {
                    if (name_len[c] == klen &&
                        memcmp(names + name_off[c], buf + kstart,
                               (size_t)klen) == 0) {
                        col = c;
                        break;
                    }
                }
            }
            skip_ws(buf, len, &p);
            if (p >= len || buf[p] != ':') return -1;
            p++;
            skip_ws(buf, len, &p);
            if (p >= len) return -1;
            if (col < 0) {
                if (skip_value(buf, len, &p)) return -1;
                continue;
            }
            size_t cell = (size_t)col * (size_t)n_records + (size_t)r;
            uint8_t c0 = buf[p];
            if (c0 == '"') {
                p++;
                size_t vstart = p;
                int esc;
                if (scan_string(buf, len, &p, &esc)) return -1;
                str_off[cell] = (long long)vstart;
                str_len[cell] = (long long)(p - 1 - vstart);
                types[cell] = esc ? 6 : 2;
            } else if (c0 == 't') {
                if (p + 4 > len || memcmp(buf + p, "true", 4)) return -1;
                p += 4;
                types[cell] = 3;
            } else if (c0 == 'f') {
                if (p + 5 > len || memcmp(buf + p, "false", 5)) return -1;
                p += 5;
                types[cell] = 4;
            } else if (c0 == 'n') {
                if (p + 4 > len || memcmp(buf + p, "null", 4)) return -1;
                p += 4;
                types[cell] = 5;
            } else if (c0 == '-' || (c0 >= '0' && c0 <= '9')) {
                /* number: parse int64 while it stays integral + in range,
                 * fall back to double on '.', exponent, or overflow */
                int neg = (c0 == '-');
                size_t q = p + (neg ? 1 : 0);
                size_t digits_from = q;
                long long iv = 0;
                int overflow = 0;
                size_t dstart = p;
                while (q < len && buf[q] >= '0' && buf[q] <= '9') {
                    if (iv >= (long long)922337203685477580LL) overflow = 1;
                    if (!overflow) iv = iv * 10 + (buf[q] - '0');
                    q++;
                }
                if (q == digits_from) {
                    bad[r] = 1; /* bare '-' etc: python re-parse raises */
                    p = q;
                    types[cell] = 0;
                } else if (q < len && (buf[q] == '.' || buf[q] == 'e' ||
                                buf[q] == 'E')) {
                    /* double: let strtod do the rest from dstart */
                    char tmp[64];
                    size_t dl = 0;
                    while (dstart + dl < len && dl < 63) {
                        uint8_t ch = buf[dstart + dl];
                        if (!((ch >= '0' && ch <= '9') || ch == '.' ||
                              ch == 'e' || ch == 'E' || ch == '+' ||
                              ch == '-'))
                            break;
                        tmp[dl] = (char)ch;
                        dl++;
                    }
                    tmp[dl] = 0;
                    char *endp = 0;
                    nums[cell] = strtod(tmp, &endp);
                    if (endp == tmp) return -1;
                    p = dstart + (size_t)(endp - tmp);
                    types[cell] = 1;
                } else if (overflow) {
                    bad[r] = 1; /* precision beyond int64: python decodes */
                    p = q;
                    types[cell] = 0;
                } else {
                    lints[cell] = neg ? -iv : iv;
                    p = q;
                    types[cell] = 8;
                }
            } else {
                /* nested value under a KNOWN key: python re-parses record */
                bad[r] = 1;
                if (skip_value(buf, len, &p)) return -1;
            }
        }
        rec_len[r] = (long long)p - rec_off[r];
        skip_ws(buf, len, &p);
        if (r + 1 < n_records) {
            if (p >= len || buf[p] != ',') return -1;
            p++;
        }
    }
    /* the record count is transport metadata, not producer-validated JSON:
     * trailing bytes mean a value smuggled extra top-level objects — the
     * whole batch is rejected so the caller's per-message decode isolates
     * the bad record instead of silently dropping/duplicating rows */
    skip_ws(buf, len, &p);
    if (p != len) return -1;
    return n_records;
}

long pinot_decode_records(const uint8_t *buf, size_t len,
                          long long base_offset, long long first_ts,
                          long max_records,
                          long long *offsets, long long *ts,
                          long long *key_off, long long *key_len,
                          long long *val_off, long long *val_len) {
    size_t pos = 0;
    long n = 0;
    while (n < max_records && pos < len) {
        int64_t rec_len, ts_delta, off_delta, klen, vlen, hdrs;
        if (read_varint(buf, len, &pos, &rec_len) || rec_len < 0) return -1;
        size_t rec_end = pos + (size_t)rec_len;
        if (rec_end > len) return -1;
        if (pos >= rec_end) return -1;
        pos++; /* record attributes */
        if (read_varint(buf, rec_end, &pos, &ts_delta)) return -1;
        if (read_varint(buf, rec_end, &pos, &off_delta)) return -1;
        if (read_varint(buf, rec_end, &pos, &klen)) return -1;
        if (klen >= 0) {
            if (pos + (size_t)klen > rec_end) return -1;
            key_off[n] = (long long)pos;
            key_len[n] = klen;
            pos += (size_t)klen;
        } else {
            key_off[n] = -1;
            key_len[n] = -1;
        }
        if (read_varint(buf, rec_end, &pos, &vlen)) return -1;
        if (vlen >= 0) {
            if (pos + (size_t)vlen > rec_end) return -1;
            val_off[n] = (long long)pos;
            val_len[n] = vlen;
            pos += (size_t)vlen;
        } else {
            val_off[n] = -1;
            val_len[n] = 0;
        }
        /* headers: count then (key varint+bytes, value varint+bytes) each;
         * zigzag on the count mirrors the encoder's uvarint(0) == varint 0 */
        if (read_varint(buf, rec_end, &pos, &hdrs)) return -1;
        if (hdrs < 0) hdrs = 0;
        for (int64_t h = 0; h < hdrs; h++) {
            int64_t hk, hv;
            if (read_varint(buf, rec_end, &pos, &hk) || hk < 0) return -1;
            pos += (size_t)hk;
            if (read_varint(buf, rec_end, &pos, &hv)) return -1;
            if (hv > 0) pos += (size_t)hv;
            if (pos > rec_end) return -1;
        }
        offsets[n] = base_offset + off_delta;
        ts[n] = first_ts + ts_delta;
        n++;
        pos = rec_end;
    }
    return n;
}
