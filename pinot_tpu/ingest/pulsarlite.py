"""PulsarLite: Pulsar binary-protocol TCP broker + the stream plugin for it.

The reference ships a Pulsar consumer plugin
(`pinot-plugins/pinot-stream-ingestion/pinot-pulsar/src/main/java/org/apache/
pinot/plugin/stream/pulsar/PulsarPartitionLevelConsumer.java`) against an
external Pulsar cluster; this module provides both halves so the stream SPI
is proven against a REAL socket boundary speaking Pulsar's ACTUAL binary
framing (the public PulsarApi.proto / binary protocol spec):

* frames: `[totalSize u32][commandSize u32][BaseCommand protobuf]`, and for
  SEND/MESSAGE the payload form
  `... [magic 0x0e01][crc32c u32][metadataSize u32][MessageMetadata][payload]`
  with CRC-32C over metadataSize..payload (the same checksum the kafka wire
  uses — shared native implementation);
* commands: CONNECT/CONNECTED, PRODUCER/PRODUCER_SUCCESS, SEND/SEND_RECEIPT,
  SUBSCRIBE/SUCCESS, FLOW (permit-based push), MESSAGE, SEEK,
  GET_LAST_MESSAGE_ID, CLOSE_*, PING/PONG, ERROR;
* BaseCommand protobuf encoded/decoded with this package's own wire codec
  (`ingest/proto.py` primitives) — no pulsar-client dependency.

The consumption model is Pulsar's: a non-durable (reader-style) subscription
positioned with SEEK, FLOW permits pulling pushed MESSAGE frames — mapped
onto the pull-based `PartitionGroupConsumer` SPI exactly like the reference
plugin maps its Reader (`PulsarPartitionLevelConsumer.fetchMessages` seeks
to the start MessageId and drains up to maxCount). Offsets are entry ids in
ledger 0 of the stub's single-ledger topic log.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .kafka_wire import crc32c
from .proto import iter_fields, read_uvarint
from .stream import (MessageBatch, PartitionGroupConsumer,
                     StreamConsumerFactory, StreamMessage,
                     StreamMetadataProvider, register_stream_factory)

MAGIC = b"\x0e\x01"

# BaseCommand.Type values (public PulsarApi.proto); the BaseCommand field
# number carrying each command's sub-message equals its enum value
CONNECT = 2
CONNECTED = 3
SUBSCRIBE = 4
PRODUCER = 5
SEND = 6
SEND_RECEIPT = 7
SEND_ERROR = 8
MESSAGE = 9
ACK = 10
FLOW = 11
UNSUBSCRIBE = 12
SUCCESS = 13
ERROR = 14
CLOSE_PRODUCER = 15
CLOSE_CONSUMER = 16
PRODUCER_SUCCESS = 17
PING = 18
PONG = 19
SEEK = 28
GET_LAST_MESSAGE_ID = 29
GET_LAST_MESSAGE_ID_RESPONSE = 30


# ---------------------------------------------------------------------------
# minimal protobuf writers (proto2 wire format; readers come from proto.py)
# ---------------------------------------------------------------------------

def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(num: int, v: int) -> bytes:
    # negatives encode as 64-bit two's complement (proto int32/int64 varint
    # semantics; an unmasked negative would loop _uvarint forever)
    return _uvarint(num << 3) + _uvarint(v & 0xFFFFFFFFFFFFFFFF)


def _field_bytes(num: int, v: bytes) -> bytes:
    return _uvarint((num << 3) | 2) + _uvarint(len(v)) + v


def _field_str(num: int, s: str) -> bytes:
    return _field_bytes(num, s.encode("utf-8"))


def _msg(fields: Dict[int, Any]) -> bytes:
    """{field_num: int | str | bytes | dict (sub-message) | list} -> body."""
    out = b""
    for num, v in fields.items():
        if v is None:
            continue
        for item in (v if isinstance(v, list) else [v]):
            if isinstance(item, dict):
                out += _field_bytes(num, _msg(item))
            elif isinstance(item, bytes):
                out += _field_bytes(num, item)
            elif isinstance(item, str):
                out += _field_str(num, item)
            else:
                out += _field_varint(num, int(item))
    return out


def _decode(data: bytes) -> Dict[int, List[Any]]:
    """Generic field-number -> values decode (nested messages stay bytes)."""
    out: Dict[int, List[Any]] = {}
    for num, _wt, v in iter_fields(data):
        out.setdefault(num, []).append(v)
    return out


def _one(d: Dict[int, List[Any]], num: int, default=None):
    vs = d.get(num)
    return vs[0] if vs else default


def _signed(v: int) -> int:
    """Varint -> signed int64 (proto int64 negatives arrive as 2^64-n)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _base_command(cmd_type: int, body: Optional[Dict[int, Any]] = None) -> bytes:
    fields: Dict[int, Any] = {1: cmd_type}
    if body is not None:
        fields[cmd_type] = body
    return _msg(fields)


def _message_id(ledger: int, entry: int) -> Dict[int, Any]:
    return {1: ledger, 2: entry}


def encode_frame(command: bytes, metadata: Optional[bytes] = None,
                 payload: bytes = b"") -> bytes:
    """Simple or payload frame per the Pulsar binary protocol."""
    if metadata is None:
        total = 4 + len(command)
        return struct.pack(">II", total, len(command)) + command
    meta_part = struct.pack(">I", len(metadata)) + metadata + payload
    crc = crc32c(meta_part)
    rest = MAGIC + struct.pack(">I", crc) + meta_part
    total = 4 + len(command) + len(rest)
    return struct.pack(">II", total, len(command)) + command + rest


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def parse_frame(body: bytes):
    """Decode one complete frame body (everything after totalSize)."""
    (cmd_size,) = struct.unpack(">I", body[:4])
    cmd = _decode(body[4:4 + cmd_size])
    rest = body[4 + cmd_size:]
    if not rest:
        return cmd, None, None
    if rest[:2] != MAGIC:
        raise ValueError("bad payload magic")
    (crc,) = struct.unpack(">I", rest[2:6])
    meta_part = rest[6:]
    if crc32c(meta_part) != crc:
        raise ValueError("pulsar frame CRC mismatch")
    (meta_size,) = struct.unpack(">I", meta_part[:4])
    metadata = _decode(meta_part[4:4 + meta_size])
    payload = meta_part[4 + meta_size:]
    return cmd, metadata, payload


def read_frame(sock: socket.socket):
    """-> (BaseCommand fields, metadata fields|None, payload|None) or None
    on EOF. Blocking frame-at-a-time variant for the broker's serve loop;
    clients read through PulsarLiteClient's buffer instead."""
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (total,) = struct.unpack(">I", head)
    body = _recv_exact(sock, total)
    if body is None:
        return None
    return parse_frame(body)


# ---------------------------------------------------------------------------
# stub broker
# ---------------------------------------------------------------------------

class _TopicLog:
    """Single-ledger topic partition: entry id == offset."""

    def __init__(self):
        self.entries: List[Tuple[bytes, int]] = []  # (payload, publish_ms)
        self.lock = threading.Lock()

    def append(self, payload: bytes, ts: int) -> int:
        with self.lock:
            self.entries.append((payload, ts))
            return len(self.entries) - 1


class PulsarLiteBroker:
    """In-repo Pulsar-wire broker: CONNECT/PRODUCER/SEND/SUBSCRIBE/FLOW/
    SEEK/GET_LAST_MESSAGE_ID over real TCP sockets. Permit-based push: a
    subscription delivers MESSAGE frames only while it holds FLOW permits,
    exactly the Pulsar flow-control model."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.topics: Dict[str, _TopicLog] = {}
        self._lock = threading.Lock()
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True,
                                          name="pulsarlite-accept")
        self._acceptor.start()

    @property
    def service_url(self) -> str:
        return f"pulsar://{self.host}:{self.port}"

    def topic(self, name: str) -> _TopicLog:
        with self._lock:
            if name not in self.topics:
                self.topics[name] = _TopicLog()
            return self.topics[name]

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        # closing the listener raises OSError in accept(), so the join is
        # quick; per-connection threads die with their sockets (daemon)
        self._acceptor.join(timeout=2.0)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            # graftcheck: ignore[thread-no-join] -- per-connection daemon
            # thread, bounded by the client socket's lifetime
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="pulsarlite-conn")
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        producers: Dict[int, str] = {}          # producer_id -> topic
        consumers: Dict[int, Dict[str, Any]] = {}  # consumer_id -> state
        try:
            while not self._stop.is_set():
                frame = read_frame(conn)
                if frame is None:
                    return
                cmd, metadata, payload = frame
                ctype = _one(cmd, 1)
                if ctype == CONNECT:
                    conn.sendall(encode_frame(_base_command(
                        CONNECTED, {1: "pulsarlite", 2: 21})))
                elif ctype == PING:
                    conn.sendall(encode_frame(_base_command(PONG, {})))
                elif ctype == PRODUCER:
                    d = _decode(_one(cmd, PRODUCER))
                    topic = _one(d, 1).decode()
                    pid, req = _one(d, 2, 0), _one(d, 3, 0)
                    producers[pid] = topic
                    self.topic(topic)
                    conn.sendall(encode_frame(_base_command(
                        PRODUCER_SUCCESS,
                        {1: req, 2: f"p-{pid}", 3: -1})))
                elif ctype == SEND:
                    d = _decode(_one(cmd, SEND))
                    pid, seq = _one(d, 1, 0), _one(d, 2, 0)
                    log = self.topic(producers[pid])
                    ts = _one(metadata, 3, 0) if metadata else 0
                    # store the RAW metadata+payload frame tail so redelivery
                    # is byte-identical (single-message batches only)
                    entry = log.append(payload or b"", int(ts))
                    conn.sendall(encode_frame(_base_command(
                        SEND_RECEIPT,
                        {1: pid, 2: seq, 3: _message_id(0, entry)})))
                elif ctype == SUBSCRIBE:
                    d = _decode(_one(cmd, SUBSCRIBE))
                    topic = _one(d, 1).decode()
                    cid, req = _one(d, 4, 0), _one(d, 5, 0)
                    start = _decode(_one(d, 9)) if d.get(9) else None
                    cursor = _one(start, 2, 0) if start else 0
                    consumers[cid] = {"topic": topic, "cursor": cursor,
                                      "permits": 0}
                    self.topic(topic)
                    conn.sendall(encode_frame(_base_command(SUCCESS,
                                                            {1: req})))
                elif ctype == SEEK:
                    d = _decode(_one(cmd, SEEK))
                    cid, req = _one(d, 1, 0), _one(d, 2, 0)
                    mid = _decode(_one(d, 3)) if d.get(3) else None
                    if cid in consumers and mid is not None:
                        consumers[cid]["cursor"] = _one(mid, 2, 0)
                        consumers[cid]["permits"] = 0
                    conn.sendall(encode_frame(_base_command(SUCCESS,
                                                            {1: req})))
                elif ctype == FLOW:
                    d = _decode(_one(cmd, FLOW))
                    cid = _one(d, 1, 0)
                    state = consumers.get(cid)
                    if state is None:
                        continue
                    state["permits"] += _one(d, 2, 0)
                    self._deliver(conn, cid, state)
                elif ctype == GET_LAST_MESSAGE_ID:
                    d = _decode(_one(cmd, GET_LAST_MESSAGE_ID))
                    cid, req = _one(d, 1, 0), _one(d, 2, 0)
                    state = consumers.get(cid)
                    log = self.topic(state["topic"]) if state else None
                    last = len(log.entries) - 1 if log else -1
                    conn.sendall(encode_frame(_base_command(
                        GET_LAST_MESSAGE_ID_RESPONSE,
                        {1: _message_id(0, last), 2: req})))
                elif ctype in (CLOSE_PRODUCER, CLOSE_CONSUMER):
                    d = _decode(_one(cmd, ctype))
                    req = _one(d, 2, 0)
                    conn.sendall(encode_frame(_base_command(SUCCESS,
                                                            {1: req})))
                elif ctype == ACK:
                    pass  # reader-style consumption: cursor is client-driven
        except (OSError, ValueError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _deliver(self, conn: socket.socket, cid: int,
                 state: Dict[str, Any]) -> None:
        log = self.topic(state["topic"])
        while state["permits"] > 0:
            with log.lock:
                if state["cursor"] >= len(log.entries):
                    return
                payload, ts = log.entries[state["cursor"]]
                entry = state["cursor"]
            metadata = _msg({1: "p", 2: entry, 3: ts})
            conn.sendall(encode_frame(
                _base_command(MESSAGE, {1: cid, 2: _message_id(0, entry)}),
                metadata, payload))
            state["cursor"] = entry + 1
            state["permits"] -= 1


# ---------------------------------------------------------------------------
# client + stream plugin
# ---------------------------------------------------------------------------

def partition_topic(topic: str, partition: int) -> str:
    return f"persistent://public/default/{topic}-partition-{partition}"


class PulsarLiteClient:
    """One connection: CONNECT handshake + request/response command helpers.

    ALL reads go through a receive buffer (`read_frame_timeout`): a short
    poll that expires MID-FRAME keeps the partial bytes buffered instead of
    desyncing the stream — discarding them once wedged a consumer forever
    when the broker's push landed across a fetch's poll deadline."""

    def __init__(self, service_url: str):
        assert service_url.startswith("pulsar://"), service_url
        host, port = service_url[len("pulsar://"):].split(":")
        self.sock = socket.create_connection((host, int(port)), timeout=30)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._req = 0
        self._rbuf = bytearray()
        self.sock.sendall(encode_frame(_base_command(
            CONNECT, {1: "pinot-tpu-pulsarlite", 4: 21})))
        cmd, _, _ = self.read_frame_blocking()
        if _one(cmd, 1) != CONNECTED:
            raise ConnectionError(f"pulsar handshake failed: {cmd}")

    def read_frame_timeout(self, timeout_s: float):
        """One complete frame, or None when `timeout_s` expires first.
        Partial bytes stay buffered for the next call; the socket timeout is
        RESTORED on every exit so later sendall calls (SEND payloads, FLOW)
        never run under the 50ms poll — a sendall cut short mid-frame would
        desync the wire for good."""
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                if len(self._rbuf) >= 4:
                    (total,) = struct.unpack(">I", bytes(self._rbuf[:4]))
                    if len(self._rbuf) >= 4 + total:
                        body = bytes(self._rbuf[4:4 + total])
                        del self._rbuf[:4 + total]
                        return parse_frame(body)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                # block for the full remaining budget: the client is
                # single-threaded with nothing to service between frames,
                # so a short poll here would only add wakeup churn (fetch's
                # drain passes its own short timeout_s when it wants one)
                self.sock.settimeout(remaining)
                try:
                    chunk = self.sock.recv(1 << 16)
                except (socket.timeout, TimeoutError):
                    continue
                if not chunk:
                    raise ConnectionError("pulsar connection closed")
                self._rbuf.extend(chunk)
        finally:
            self.sock.settimeout(30)

    def read_frame_blocking(self, timeout_s: float = 30.0):
        frame = self.read_frame_timeout(timeout_s)
        if frame is None:
            raise ConnectionError("pulsar exchange timed out")
        return frame

    def next_req(self) -> int:
        self._req += 1
        return self._req

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class PulsarLiteProducer:
    def __init__(self, service_url: str, topic: str, partition: int = 0):
        self.client = PulsarLiteClient(service_url)
        self.producer_id = 1
        self._seq = 0
        self.client.sock.sendall(encode_frame(_base_command(PRODUCER, {
            1: partition_topic(topic, partition), 2: self.producer_id,
            3: self.client.next_req()})))
        cmd, _, _ = self.client.read_frame_blocking()
        if _one(cmd, 1) != PRODUCER_SUCCESS:
            raise ConnectionError(f"producer create failed: {cmd}")

    def send(self, payload: bytes, ts: Optional[int] = None) -> int:
        """Send one message; returns the assigned entry id (offset)."""
        self._seq += 1
        metadata = _msg({1: "p", 2: self._seq,
                         3: ts if ts is not None else int(time.time() * 1000)})
        self.client.sock.sendall(encode_frame(
            _base_command(SEND, {1: self.producer_id, 2: self._seq}),
            metadata, payload))
        cmd, _, _ = self.client.read_frame_blocking()
        if _one(cmd, 1) != SEND_RECEIPT:
            raise RuntimeError(f"send failed: {cmd}")
        receipt = _decode(_one(cmd, SEND_RECEIPT))
        mid = _decode(_one(receipt, 3))
        return _one(mid, 2, -1)

    def close(self) -> None:
        self.client.close()


class PulsarLiteConsumer(PartitionGroupConsumer):
    """Reader-style consumer: non-durable subscription, SEEK to the fetch
    offset when the cursor diverges, FLOW permits for exactly the batch
    (reference: PulsarPartitionLevelConsumer.fetchMessages draining a
    Reader positioned at startMessageId)."""

    def __init__(self, service_url: str, topic: str, partition: int):
        self.client = PulsarLiteClient(service_url)
        self.consumer_id = 1
        self._cursor: Optional[int] = None
        self.client.sock.sendall(encode_frame(_base_command(SUBSCRIBE, {
            1: partition_topic(topic, partition),
            2: "pinot-tpu-reader", 3: 0, 4: self.consumer_id,
            5: self.client.next_req(), 8: 0,
            9: _message_id(0, 0)})))
        cmd, _, _ = self.client.read_frame_blocking()
        if _one(cmd, 1) != SUCCESS:
            raise ConnectionError(f"subscribe failed: {cmd}")
        self._cursor = 0

    def _seek(self, offset: int) -> None:
        self.client.sock.sendall(encode_frame(_base_command(SEEK, {
            1: self.consumer_id, 2: self.client.next_req(),
            3: _message_id(0, offset)})))
        # MESSAGE frames already in flight may precede the SUCCESS; they are
        # stale (pre-seek cursor) and dropped here
        while True:
            cmd, _, _ = self.client.read_frame_blocking()
            if _one(cmd, 1) == SUCCESS:
                break
        self._cursor = offset

    def fetch(self, start_offset: int, max_messages: int,
              timeout_ms: int = 0) -> MessageBatch:
        if self._cursor != start_offset:
            self._seek(start_offset)
        self.client.sock.sendall(encode_frame(_base_command(FLOW, {
            1: self.consumer_id, 2: max_messages})))
        msgs: List[StreamMessage] = []
        deadline = time.monotonic() + max(timeout_ms, 50) / 1000.0
        while len(msgs) < max_messages:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            # buffered read: a poll expiring MID-FRAME keeps partial bytes
            # for the next call instead of desyncing the stream (a raw
            # socket-timeout read dropped them and wedged the consumer when
            # the broker's push landed across the deadline under load)
            try:
                frame = self.client.read_frame_timeout(
                    0.02 if msgs else remaining)
            except ConnectionError:
                break   # broker EOF mid-fetch: return what was drained
            if frame is None:
                if msgs:
                    break  # drained what the broker had
                continue
            cmd, metadata, payload = frame
            if _one(cmd, 1) != MESSAGE:
                continue
            d = _decode(_one(cmd, MESSAGE))
            mid = _decode(_one(d, 2))
            entry = _one(mid, 2, 0)
            if entry < start_offset:
                continue  # stale pre-seek delivery
            ts = _one(metadata, 3, 0) if metadata else 0
            msgs.append(StreamMessage(
                value=(payload or b"").decode("utf-8", "surrogateescape"),
                offset=entry, key=None, timestamp_ms=int(ts)))
        next_offset = msgs[-1].offset + 1 if msgs else start_offset
        self._cursor = next_offset
        return MessageBatch(msgs, next_offset)

    def latest_offset(self) -> int:
        self.client.sock.sendall(encode_frame(_base_command(
            GET_LAST_MESSAGE_ID,
            {1: self.consumer_id, 2: self.client.next_req()})))
        while True:
            cmd, _, _ = self.client.read_frame_blocking()
            if _one(cmd, 1) == GET_LAST_MESSAGE_ID_RESPONSE:
                d = _decode(_one(cmd, GET_LAST_MESSAGE_ID_RESPONSE))
                mid = _decode(_one(d, 1))
                return _signed(_one(mid, 2, -1)) + 1

    def close(self) -> None:
        self.client.close()


class PulsarLiteFactory(StreamConsumerFactory):
    """Stream plugin factory, type "pulsar"; properties: serviceUrl."""

    def __init__(self, topic: str, properties: Optional[Dict[str, Any]] = None):
        props = properties or {}
        self.topic = topic
        self.service_url = props.get("serviceUrl") or props.get("endpoint", "")

    def create_consumer(self, topic: str, partition: int
                        ) -> PartitionGroupConsumer:
        return PulsarLiteConsumer(self.service_url, topic or self.topic,
                                  partition)

    def metadata_provider(self) -> StreamMetadataProvider:
        # partitioned-topic metadata: the controller supplies the partition
        # count at table creation; each partition is its own
        # "<topic>-partition-N" broker topic (the Pulsar naming scheme)
        return StreamMetadataProvider()


register_stream_factory("pulsar", PulsarLiteFactory)
