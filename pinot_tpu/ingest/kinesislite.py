"""KinesisLite: the AWS Kinesis JSON API as a stream plugin + in-repo stub.

Analog of the reference's Kinesis plugin
(`pinot-plugins/pinot-stream-ingestion/pinot-kinesis/src/main/java/org/
apache/pinot/plugin/stream/kinesis/KinesisConsumer.java` +
`KinesisStreamMetadataProvider.java`): shard-partitioned streams consumed
through GetShardIterator/GetRecords. Both halves live here so the stream SPI
is proven against Kinesis's ACTUAL wire shape — JSON-RPC POSTs with the
`X-Amz-Target: Kinesis_20131202.<Action>` header (CreateStream, PutRecord,
PutRecords, DescribeStream, GetShardIterator, GetRecords), base64 record
Data, per-shard monotone sequence numbers, and millisBehindLatest. Pointing
the consumer at real Kinesis/localstack is an endpoint + sigv4 config away
(the S3 module already provides `sign_request`); the stub optionally
verifies sigv4 with the same shared-secret scheme as `S3StubServer`.

Offsets: the FSM's integer offsets ARE the sequence numbers (Kinesis
sequence numbers are opaque strings on the wire; the stub issues stringified
integers and the consumer parses them back — the AT_SEQUENCE_NUMBER iterator
re-anchors any replay, exactly like the reference's checkpointing).
"""

from __future__ import annotations

import base64
import json
import threading
import time
# graftcheck: ignore[transport-bypass] -- external Kinesis endpoint, not the
# cluster data plane; signed one-shot API calls, no pooling to gain
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .stream import (MessageBatch, PartitionGroupConsumer,
                     StreamConsumerFactory, StreamMessage,
                     StreamMetadataProvider, register_stream_factory)

_TARGET_PREFIX = "Kinesis_20131202."


class KinesisError(RuntimeError):
    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}")
        self.code = code


# ---------------------------------------------------------------------------
# stub server (the wire-seam proof; reference analog: Kinesis itself)
# ---------------------------------------------------------------------------

class KinesisStub:
    """Minimal Kinesis JSON endpoint: shard-partitioned logs with sequence
    numbers and shard iterators; optional sigv4 verification; an `outage`
    switch for chaos tests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        # stream -> [shard logs]; each log is a list of (seq, ts_ms, data, pk)
        self._streams: Dict[str, List[List[Tuple[int, int, bytes, str]]]] = {}
        self._lock = threading.Lock()
        self.outage = False
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b"{}"
                target = self.headers.get("X-Amz-Target", "")
                try:
                    if stub.outage:
                        raise KinesisError("ServiceUnavailable", "outage")
                    if not stub._authorized(self.headers, body, self.path):
                        raise KinesisError("AccessDeniedException",
                                           "bad signature")
                    if not target.startswith(_TARGET_PREFIX):
                        raise KinesisError("UnknownOperationException", target)
                    action = target[len(_TARGET_PREFIX):]
                    out = stub._dispatch(action, json.loads(body.decode()))
                    payload = json.dumps(out).encode()
                    status = 200
                except KinesisError as e:
                    payload = json.dumps({"__type": e.code,
                                          "message": str(e)}).encode()
                    status = 400 if e.code != "ServiceUnavailable" else 503
                except Exception as e:
                    # malformed body / missing field / bad iterator: answer
                    # the AWS ValidationException envelope like real Kinesis,
                    # never a dropped connection
                    payload = json.dumps({"__type": "ValidationException",
                                          "message": f"{type(e).__name__}: "
                                                     f"{e}"}).encode()
                    status = 400
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/x-amz-json-1.1")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        class _Server(ThreadingHTTPServer):
            request_queue_size = 64

        self._server = _Server((host, port), Handler)
        self._server.daemon_threads = True
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="kinesis-stub")
        self._thread.start()

    # -- auth ----------------------------------------------------------------
    def _authorized(self, headers, body: bytes, path: str) -> bool:
        if not self.access_key:
            return True
        from ..cluster.s3store import sigv4_verify
        return sigv4_verify(headers, "POST", path, "", body,
                            self.access_key, self.secret_key, self.region,
                            service="kinesis")

    # -- actions -------------------------------------------------------------
    def _shards(self, stream: str):
        shards = self._streams.get(stream)
        if shards is None:
            raise KinesisError("ResourceNotFoundException", stream)
        return shards

    def _dispatch(self, action: str, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if action == "CreateStream":
                name = req["StreamName"]
                if name not in self._streams:
                    self._streams[name] = [
                        [] for _ in range(int(req.get("ShardCount", 1)))]
                return {}
            if action == "DescribeStream":
                shards = self._shards(req["StreamName"])
                return {"StreamDescription": {
                    "StreamName": req["StreamName"],
                    "StreamStatus": "ACTIVE",
                    "Shards": [{"ShardId": f"shardId-{i:012d}"}
                               for i in range(len(shards))]}}
            if action in ("PutRecord", "PutRecords"):
                return self._put(action, req)
            if action == "GetShardIterator":
                return self._iterator(req)
            if action == "GetRecords":
                return self._get_records(req)
        raise KinesisError("UnknownOperationException", action)

    def _shard_index(self, stream: str, shard_id: str) -> int:
        return int(shard_id.rsplit("-", 1)[-1])

    def _put_one(self, shards, data_b64: str, pk: str) -> Dict[str, Any]:
        import zlib
        idx = zlib.crc32(pk.encode()) % len(shards)
        log = shards[idx]
        seq = len(log)
        log.append((seq, int(time.time() * 1000),
                    base64.b64decode(data_b64), pk))
        return {"ShardId": f"shardId-{idx:012d}",
                "SequenceNumber": str(seq)}

    def _put(self, action: str, req: Dict[str, Any]) -> Dict[str, Any]:
        shards = self._shards(req["StreamName"])
        if action == "PutRecord":
            return self._put_one(shards, req["Data"], req["PartitionKey"])
        records = [self._put_one(shards, r["Data"], r["PartitionKey"])
                   for r in req["Records"]]
        return {"FailedRecordCount": 0, "Records": records}

    def _iterator(self, req: Dict[str, Any]) -> Dict[str, Any]:
        shards = self._shards(req["StreamName"])
        idx = self._shard_index(req["StreamName"], req["ShardId"])
        if not 0 <= idx < len(shards):
            raise KinesisError("ResourceNotFoundException", req["ShardId"])
        it_type = req["ShardIteratorType"]
        if it_type == "TRIM_HORIZON":
            seq = 0
        elif it_type == "LATEST":
            seq = len(shards[idx])
        elif it_type in ("AT_SEQUENCE_NUMBER", "AFTER_SEQUENCE_NUMBER"):
            seq = int(req["StartingSequenceNumber"])
            if it_type == "AFTER_SEQUENCE_NUMBER":
                seq += 1
        else:
            raise KinesisError("InvalidArgumentException", it_type)
        return {"ShardIterator":
                json.dumps({"s": req["StreamName"], "i": idx, "q": seq})}

    def _get_records(self, req: Dict[str, Any]) -> Dict[str, Any]:
        it = json.loads(req["ShardIterator"])
        shards = self._shards(it["s"])
        log = shards[it["i"]]
        limit = int(req.get("Limit", 10000))
        out = []
        seq = it["q"]
        for rec_seq, ts, data, pk in log[seq:seq + limit]:
            out.append({"SequenceNumber": str(rec_seq),
                        "ApproximateArrivalTimestamp": ts / 1000.0,
                        "Data": base64.b64encode(data).decode(),
                        "PartitionKey": pk})
        nxt = seq + len(out)
        behind = (len(log) - nxt) * 1000   # ms-behind proxy like the real API
        return {"Records": out,
                "NextShardIterator":
                    json.dumps({"s": it["s"], "i": it["i"], "q": nxt}),
                "MillisBehindLatest": behind}

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# client + stream plugin
# ---------------------------------------------------------------------------

class KinesisClient:
    """JSON-API client (the aws-sdk analog the plugin consumes through)."""

    def __init__(self, endpoint: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout_s: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout_s = timeout_s

    def call(self, action: str, req: Dict[str, Any]) -> Dict[str, Any]:
        body = json.dumps(req).encode()
        headers = {"Content-Type": "application/x-amz-json-1.1",
                   "X-Amz-Target": _TARGET_PREFIX + action}
        if self.access_key:
            from ..cluster.s3store import sign_request
            headers.update(sign_request("POST", self.endpoint + "/", body,
                                        self.access_key, self.secret_key,
                                        self.region, service="kinesis"))
        # ride the keep-alive pool: the consume FSM polls this per shard in
        # its hot loop, and a fresh TCP handshake per call costs ~40ms under
        # Nagle/delayed-ACK (see cluster/http_service._ConnPool)
        from ..cluster.http_service import HttpError, _pooled_request
        try:
            data = _pooled_request("POST", self.endpoint + "/", body, headers,
                                   self.timeout_s)
            return json.loads(data.decode())
        except HttpError as e:
            msg = str(e).split(": ", 1)[-1]
            try:
                d = json.loads(msg or "{}")
            except ValueError:
                d = {}
            raise KinesisError(d.get("__type", f"HTTP{e.status}"),
                               d.get("message", "")) from None

    # convenience wrappers
    def create_stream(self, name: str, shards: int) -> None:
        self.call("CreateStream", {"StreamName": name, "ShardCount": shards})

    def put_record(self, stream: str, data, partition_key: str) -> Dict:
        raw = data if isinstance(data, bytes) else str(data).encode()
        return self.call("PutRecord", {
            "StreamName": stream, "PartitionKey": partition_key,
            "Data": base64.b64encode(raw).decode()})

    def put_records(self, stream: str, items) -> Dict:
        recs = [{"PartitionKey": pk,
                 "Data": base64.b64encode(
                     d if isinstance(d, bytes) else str(d).encode()).decode()}
                for pk, d in items]
        return self.call("PutRecords", {"StreamName": stream,
                                        "Records": recs})

    def shard_count(self, stream: str) -> int:
        d = self.call("DescribeStream", {"StreamName": stream})
        return len(d["StreamDescription"]["Shards"])


class KinesisConsumer(PartitionGroupConsumer):
    """PartitionGroupConsumer over one shard: integer FSM offsets anchor an
    AT_SEQUENCE_NUMBER iterator, GetRecords pages forward (reference:
    KinesisConsumer.getRecords + checkpointed KinesisPartitionGroupOffset)."""

    def __init__(self, client: KinesisClient, stream: str, shard: int):
        self.client = client
        self.stream = stream
        self.shard = shard
        # (expected next offset, opaque NextShardIterator from the previous
        # GetRecords) — reused so steady-state polling is ONE RPC per fetch;
        # real Kinesis throttles GetShardIterator at 5/s/shard
        self._cached: Optional[Tuple[int, str]] = None

    def _iterator(self, seq: int) -> str:
        if self._cached is not None and self._cached[0] == seq:
            return self._cached[1]
        return self.client.call("GetShardIterator", {
            "StreamName": self.stream,
            "ShardId": f"shardId-{self.shard:012d}",
            "ShardIteratorType": "AT_SEQUENCE_NUMBER",
            "StartingSequenceNumber": str(seq)})["ShardIterator"]

    def fetch(self, start_offset: int, max_messages: int,
              timeout_ms: int = 0) -> MessageBatch:
        d = self.client.call("GetRecords", {
            "ShardIterator": self._iterator(start_offset),
            "Limit": max_messages})
        msgs = [StreamMessage(
            value=base64.b64decode(r["Data"]).decode("utf-8",
                                                     "surrogateescape"),
            offset=int(r["SequenceNumber"]),
            key=r.get("PartitionKey"),
            timestamp_ms=int(r.get("ApproximateArrivalTimestamp", 0) * 1000))
            for r in d.get("Records", [])]
        next_offset = msgs[-1].offset + 1 if msgs else start_offset
        nxt = d.get("NextShardIterator")
        self._cached = (next_offset, nxt) if nxt else None
        return MessageBatch(msgs, next_offset)

    # NOTE: no latest_offset() override — Kinesis has no latest-sequence
    # query and NextShardIterator is an OPAQUE token (parsing it would only
    # work against the stub); nothing in the consumption FSM requires it


class KinesisFactory(StreamConsumerFactory):
    """Stream plugin factory; properties: endpoint (+ accessKey/secretKey/
    region for signed requests against real Kinesis/localstack)."""

    def __init__(self, topic: str, properties: Optional[Dict[str, Any]] = None):
        props = properties or {}
        self.topic = topic
        self.client = KinesisClient(
            props.get("endpoint", ""),
            access_key=props.get("accessKey", ""),
            secret_key=props.get("secretKey", ""),
            region=props.get("region", "us-east-1"))

    def create_consumer(self, topic: str, partition: int
                        ) -> PartitionGroupConsumer:
        return KinesisConsumer(self.client, topic or self.topic, partition)

    def metadata_provider(self) -> StreamMetadataProvider:
        factory = self

        class _Meta(StreamMetadataProvider):
            def partition_count(self, topic: str) -> int:
                return factory.client.shard_count(topic or factory.topic)

        return _Meta()


register_stream_factory("kinesis", KinesisFactory)
