"""Vectorized ingest plane: columnar block wire codec + array-native decode.

The consume->index path treats ingestion like the scan path treats queries: a
bandwidth problem (PIMDAL framing, PAPERS.md). Three pieces live here:

* the **PCB1 columnar block codec** — one stream message carries a whole block
  of rows in columnar form: dictionary-encoded strings (block-local dict +
  narrow ids) and frame-of-reference narrow integers (base + u1/u2/u4
  deltas), the standard columnar compressions (Parquet/Arrow do the same).
  Per-record kafka framing, splice and decode costs amortize to ~zero and the
  wire carries ~2-3x fewer bytes than raw fixed-width rows.
* `decode_columnar_blocks` — walks a transport-spliced buffer of blocks with
  `np.frombuffer` VIEWS (no per-record copies) into `ColumnarBatch`es, the
  index-ready typed-array form `DeviceMutableSegment.index_arrays` consumes.
* `columnar_batch_from_json` — the JSON lane's array-native upgrade: the
  native `json_columns` walk already produces typed arrays; this keeps them
  as arrays (string columns dict-encode via one vectorized fixed-width
  `np.unique`) instead of `.tolist()`-ing into python lists per row.

Column representations inside a `ColumnarBatch` (plain tuples):

* ``("num", arr, base, nulls)`` — numeric values; ``arr`` may be a narrow
  frame-of-reference array with integer ``base`` (``base is None`` for
  floats / already-wide arrays). Null rows hold the spec's null fill.
* ``("dict", values, ids, nulls)`` — dict-encoded: ``values`` is the
  block-local value list, ``ids`` index into it. Null rows hold the id of
  the spec's null fill value.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import DataType, FieldSpec, Schema

#: 1-byte separator the transport splices between blocks (the native splicer
#: requires one; blocks are length-self-describing so the walk skips it)
BLOCK_SEP = b"\n"

_MAGIC = b"PCB1"
_K_RAW, _K_DICT, _K_FOR = 0, 1, 2
_F_NULLS = 1

#: wire-eligible types (single-value): fixed-width numerics + strings
_INT_TYPES = (DataType.INT, DataType.LONG, DataType.BOOLEAN, DataType.TIMESTAMP)
_FLOAT_TYPES = (DataType.FLOAT, DataType.DOUBLE)
_STR_TYPES = (DataType.STRING,)


class ColumnarBatch:
    """One decoded block: typed column arrays ready for O(batch) indexing."""

    __slots__ = ("n", "cols")

    def __init__(self, n: int, cols: Dict[str, tuple]):
        self.n = n
        self.cols = cols

    def max_of(self, name: str) -> Optional[float]:
        """Max non-null numeric value of a column (event-time freshness)."""
        rep = self.cols.get(name)
        if rep is None or self.n == 0:
            return None
        if rep[0] == "num":
            _, arr, base, nulls = rep
            if nulls is not None:
                if nulls.all():
                    return None
                arr = arr[~nulls]
            m = arr.max()
            return float(m) + (base or 0)
        return None

    def to_lists(self, schema: Schema) -> Dict[str, List[Any]]:
        """Python column lists with None at null rows — the
        `TransformPipeline.apply` / `index_batch` fallback shape (used when a
        table configures filters/transforms the array path can't run)."""
        out: Dict[str, List[Any]] = {}
        for spec in schema.fields:
            rep = self.cols.get(spec.name)
            if rep is None:
                out[spec.name] = [None] * self.n
                continue
            if rep[0] == "num":
                _, arr, base, nulls = rep
                wide = widen_num(arr, base, spec.data_type)
                vals = wide.tolist()
            else:
                _, values, ids, nulls = rep
                vals = [values[i] for i in ids.tolist()]
            if nulls is not None and nulls.any():
                for i in np.nonzero(nulls)[0].tolist():
                    vals[i] = None
            out[spec.name] = vals
        return out


def widen_num(arr: np.ndarray, base: Optional[int],
              data_type: DataType) -> np.ndarray:
    """Materialize a (possibly frame-of-reference) numeric array to the wide
    canonical dtype (int64 / float64 — the same widths the list-based host
    path carries until segment write, so both paths round identically)."""
    wide = np.int64 if np.dtype(data_type.numpy_dtype).kind in "iu" \
        else np.float64
    if base:
        return np.add(arr, base, dtype=wide)
    if arr.dtype == wide:
        return arr
    return arr.astype(wide)


def _narrow_int(arr: np.ndarray) -> Tuple[int, np.ndarray]:
    """Frame-of-reference encode: (base, narrowest unsigned delta array)."""
    if not len(arr):
        return 0, arr.astype("<u1")
    base = int(arr.min())
    spread = int(arr.max()) - base
    for ch, bits in (("<u1", 8), ("<u2", 16), ("<u4", 32)):
        if spread < (1 << bits):
            return base, (arr - base).astype(ch)
    return 0, arr.astype("<i8")


def _null_fill(spec: FieldSpec, vals) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """(object value array with nulls filled, null mask or None)."""
    arr = np.empty(len(vals), dtype=object)
    arr[:] = vals
    nulls = np.array([v is None or (isinstance(v, float) and v != v)
                      for v in vals], dtype=bool)
    if not nulls.any():
        return arr, None
    arr[nulls] = spec.null_value
    return arr, nulls


def encode_columnar_block(schema: Schema, cols: Dict[str, Sequence[Any]]
                          ) -> bytes:
    """Producer-edge encoder: column lists/arrays -> one PCB1 block message.

    Null rows are represented as None in the input lists; the encoder fills
    them with the spec's null value and carries a packed bitmap, so decode
    needs no fix-up pass. Raises on multi-value or BYTES/JSON fields — those
    schemas produce row-JSON instead (the codec is the fast lane, not the
    only lane)."""
    n = len(next(iter(cols.values()))) if cols else 0
    specs = [s for s in schema.fields]
    for s in specs:
        if not s.single_value or (s.data_type not in _INT_TYPES
                                  and s.data_type not in _FLOAT_TYPES
                                  and s.data_type not in _STR_TYPES):
            raise ValueError(f"column {s.name}: {s.data_type.value}"
                             f"{'' if s.single_value else ' MV'} is not "
                             "wire-codec eligible (produce JSON rows instead)")
    parts = [_MAGIC, struct.pack("<IH", n, len(specs))]
    for spec in specs:
        vals = cols.get(spec.name)
        if vals is None:
            vals = [None] * n
        obj, nulls = _null_fill(spec, list(vals))
        flags = _F_NULLS if nulls is not None else 0
        name_b = spec.name.encode("utf-8")
        parts.append(struct.pack("<B", len(name_b)) + name_b)
        if spec.data_type in _STR_TYPES:
            uniq, inv = np.unique(obj.astype("U"), return_inverse=True)
            blob = "\x00".join(uniq.tolist()).encode("utf-8")
            _, ids = _narrow_int(inv.astype(np.int64))
            parts.append(struct.pack("<BB", _K_DICT, flags))
            if nulls is not None:
                parts.append(np.packbits(nulls, bitorder="little").tobytes())
            parts.append(struct.pack("<IIB", len(uniq), len(blob),
                                     ord(ids.dtype.char)))
            parts.append(blob)
            parts.append(ids.tobytes())
        elif spec.data_type in _INT_TYPES:
            coerce = spec.data_type.coerce
            try:
                arr = obj.astype(np.int64)
            except (TypeError, ValueError):
                arr = np.array([coerce(v) for v in obj], dtype=np.int64)
            base, narrow = _narrow_int(arr)
            parts.append(struct.pack("<BB", _K_FOR, flags))
            if nulls is not None:
                parts.append(np.packbits(nulls, bitorder="little").tobytes())
            parts.append(struct.pack("<Bq", ord(narrow.dtype.char), base))
            parts.append(narrow.tobytes())
        else:
            arr = obj.astype("<f8")
            parts.append(struct.pack("<BB", _K_RAW, flags))
            if nulls is not None:
                parts.append(np.packbits(nulls, bitorder="little").tobytes())
            parts.append(struct.pack("<B", ord(arr.dtype.char)))
            parts.append(arr.tobytes())
    return b"".join(parts)


def _decode_one(mv: memoryview, pos: int) -> Tuple[ColumnarBatch, int]:
    """Decode the block starting at `pos`; returns (batch, end position).
    Array columns are zero-copy `frombuffer` views into the fetch buffer."""
    if bytes(mv[pos:pos + 4]) != _MAGIC:
        raise ValueError("bad columnar block magic")
    n, ncols = struct.unpack_from("<IH", mv, pos + 4)
    p = pos + 10
    nb = (n + 7) // 8
    cols: Dict[str, tuple] = {}
    for _ in range(ncols):
        (nl,) = struct.unpack_from("<B", mv, p)
        p += 1
        name = bytes(mv[p:p + nl]).decode("utf-8")
        p += nl
        kind, flags = struct.unpack_from("<BB", mv, p)
        p += 2
        nulls = None
        if flags & _F_NULLS:
            nulls = np.unpackbits(
                np.frombuffer(mv, dtype=np.uint8, count=nb, offset=p),
                count=n, bitorder="little").astype(bool)
            p += nb
        if kind == _K_DICT:
            card, blob_len, idc = struct.unpack_from("<IIB", mv, p)
            p += 9
            blob = bytes(mv[p:p + blob_len]).decode("utf-8")
            p += blob_len
            values = blob.split("\x00") if blob_len else ([""] if card else [])
            dt = np.dtype("<" + chr(idc))
            ids = np.frombuffer(mv, dtype=dt, count=n, offset=p)
            p += dt.itemsize * n
            if len(values) != card:
                raise ValueError(f"column {name}: dict count drift")
            cols[name] = ("dict", values, ids, nulls)
        elif kind == _K_FOR:
            ch, base = struct.unpack_from("<Bq", mv, p)
            p += 9
            dt = np.dtype("<" + chr(ch))
            arr = np.frombuffer(mv, dtype=dt, count=n, offset=p)
            p += dt.itemsize * n
            cols[name] = ("num", arr, base, nulls)
        elif kind == _K_RAW:
            (ch,) = struct.unpack_from("<B", mv, p)
            p += 1
            dt = np.dtype("<" + chr(ch))
            arr = np.frombuffer(mv, dtype=dt, count=n, offset=p)
            p += dt.itemsize * n
            cols[name] = ("num", arr, None, nulls)
        else:
            raise ValueError(f"column {name}: unknown block kind {kind}")
    return ColumnarBatch(n, cols), p


def decode_columnar_block(data) -> ColumnarBatch:
    """One block message -> ColumnarBatch."""
    batch, _end = _decode_one(memoryview(_as_bytes(data)), 0)
    return batch


def decode_columnar_blocks(data: bytes, n_msgs: int) -> List[ColumnarBatch]:
    """Walk a transport-spliced buffer of `n_msgs` blocks (1-byte separators
    between them — see BLOCK_SEP) with zero per-block copies."""
    mv = memoryview(data)
    out: List[ColumnarBatch] = []
    pos = 0
    # graftcheck: ignore[row-loop-in-ingest] -- per-BLOCK walk: each append
    # is one whole ColumnarBatch (thousands of rows), O(messages) not O(rows)
    for _ in range(n_msgs):
        batch, pos = _decode_one(mv, pos)
        pos += 1  # separator byte (absent after the last block: harmless)
        out.append(batch)
    return out


class ColumnarBlockDecoder:
    """Block-decoder SPI object for "columnar" streams (see
    stream.get_block_decoder): `sep` is the transport splice separator,
    `decode_spliced` walks a whole spliced fetch, `decode_one` a single
    message value (non-splicing transports)."""

    sep = BLOCK_SEP

    @staticmethod
    def decode_spliced(data: bytes, n_msgs: int) -> List[ColumnarBatch]:
        return decode_columnar_blocks(data, n_msgs)

    @staticmethod
    def decode_one(value) -> ColumnarBatch:
        return decode_columnar_block(value)


def columnar_rows_decoder(value) -> Dict[str, Any]:
    """Per-message SPI decoder for "columnar" streams. A block holds MANY
    rows, which the one-row SPI cannot express — per-row consumers
    (dedup/upsert) are rejected at consumer construction instead; this stub
    keeps `get_decoder("columnar")` resolvable for config validation."""
    raise ValueError("columnar block streams decode whole blocks; "
                     "per-row decode is not supported")


def _as_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, memoryview):
        return bytes(v)
    return str(v).encode("utf-8", "surrogateescape")


#: schema types eligible for the array-native JSON decode (matches
#: transform.columns_from_spliced_json's eligibility)
_JSON_OK = ("INT", "LONG", "FLOAT", "DOUBLE", "STRING")


def columnar_batch_from_json(data: bytes, n: int, schema: Schema
                             ) -> Optional[ColumnarBatch]:
    """Array-native columnar decode of n spliced flat-JSON records: the same
    native `json_columns` walk as `transform.columns_from_spliced_json`, but
    the output STAYS typed arrays (ColumnarBatch) — no `.tolist()`, no python
    value churn. String columns dict-encode with one vectorized fixed-width
    `np.unique` over a [n, max_len] byte matrix instead of a per-row intern
    loop.

    Returns None when any column needs the per-cell slow path (mixed cell
    types, escaped strings, flagged rows) — callers fall back to the
    list-based `columns_from_spliced_json`, which handles those exactly."""
    from ..native import json_columns
    fields = list(schema.fields)
    if any(not f.single_value or f.data_type.value not in _JSON_OK
           for f in fields):
        return None
    out = json_columns(data, n, [f.name for f in fields])
    if out is None:
        return None
    nums, lints, types, str_off, str_len, rec_ranges, bad = out
    if bad.any():
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    cols: Dict[str, tuple] = {}
    for c, f in enumerate(fields):
        t = types[c]
        dt = f.data_type.value
        null_mask = (t == 0) | (t == 5)
        nulls = null_mask if null_mask.any() else None
        if dt in ("INT", "LONG"):
            ok = (t == 8) | null_mask
            f_mask = t == 1
            if not (ok | f_mask).any() or not (ok | f_mask).all():
                return None
            vals = lints[c].copy() if (f_mask.any() or nulls is not None) \
                else lints[c]
            if f_mask.any():
                fvals = nums[c][f_mask]
                if not (np.isfinite(fvals).all()
                        and (np.abs(fvals) < float(1 << 62)).all()):
                    return None  # out-of-int64 doubles: exact per-cell path
                vals[f_mask] = fvals.astype(np.int64)
            if nulls is not None:
                vals[nulls] = f.null_value
            cols[f.name] = ("num", vals, None, nulls)
        elif dt in ("FLOAT", "DOUBLE"):
            i_mask = t == 8
            if not (i_mask | (t == 1) | null_mask).all():
                return None
            vals = nums[c].copy()
            if i_mask.any():
                vals[i_mask] = lints[c][i_mask].astype(np.float64)
            if nulls is not None:
                vals[nulls] = f.null_value
            cols[f.name] = ("num", vals, None, nulls)
        else:  # STRING
            if not ((t == 2) | null_mask).all():
                return None  # escaped/mixed cells: slow path
            # offsets/lengths are only written for t==2 rows — null/missing
            # slots hold uninitialized memory and must be zeroed before use
            s_mask = t == 2
            so = np.where(s_mask, str_off[c], 0)
            sl = np.where(s_mask, str_len[c], 0)
            w = int(sl.max()) if n else 0
            if w > 256:
                return None  # pathological widths: the intern loop wins
            # [n, w] byte matrix gathered straight from the fetch buffer,
            # viewed as fixed-width bytes then uniqued in one C pass
            mat = np.zeros((n, max(w, 1)), dtype=np.uint8)
            idx = so[:, None] + np.arange(w, dtype=so.dtype)[None, :]
            mask = np.arange(w, dtype=sl.dtype)[None, :] < sl[:, None]
            np.copyto(mat[:, :w], buf[np.minimum(idx, len(buf) - 1)],
                      where=mask)
            fixed = mat.view(f"S{max(w, 1)}").ravel()
            if nulls is not None:
                fixed = fixed.copy()
                fixed[nulls] = np.bytes_(str(f.null_value).encode("utf-8"))
            uniq, inv = np.unique(fixed, return_inverse=True)
            try:
                values = [u.decode("utf-8") for u in uniq.tolist()]
            except UnicodeDecodeError:
                return None  # multi-byte chars split by width: slow path
            cols[f.name] = ("dict", values, inv.astype(np.int64), nulls)
    return ColumnarBatch(n, cols)
