"""Server-side realtime consumption: per-partition consumers + consumption FSM.

Analog of the reference's `LLRealtimeSegmentDataManager`
(`pinot-core/.../data/manager/realtime/LLRealtimeSegmentDataManager.java:101-140`): a
per-partition consumer drives `consumeLoop` (`:389`) indexing decoded rows into the
mutable segment, hits end criteria (row/time thresholds), then walks the completion
protocol against the controller (`segmentConsumed` -> HOLD/CATCHUP/COMMIT/...,
`buildSegmentForCommit:699`, `commitSegment:705`). States mirror the reference's FSM:

    INITIAL_CONSUMING -> CATCHING_UP -> HOLDING -> COMMITTING -> COMMITTED
                                     \\-> DISCARDED (lost the race; download instead)
                                      \\-> RETAINED (KEEP: local build adopted)
                                       \\-> ERROR

Tests drive `pump()` / `maybe_complete()` deterministically (no hidden threads); a
background thread mode (`start_loop`) covers the production shape.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..query.context import QueryContext
from ..query.reduce import SegmentResult
from ..segment.mutable import MutableSegment
from ..segment.reader import load_segment
from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig
from ..table import TableConfig
from ..utils.faults import fault_point
from .stream import get_decoder, get_stream_factory
from .transform import TransformPipeline

# consumer states (reference: LLRealtimeSegmentDataManager.State:101-140)
INITIAL_CONSUMING = "INITIAL_CONSUMING"
CATCHING_UP = "CATCHING_UP"
HOLDING = "HOLDING"
COMMITTING = "COMMITTING"
COMMITTED = "COMMITTED"
DISCARDED = "DISCARDED"
RETAINED = "RETAINED"
ERROR = "ERROR"


class ConsumerLagTracker:
    """Per-partition ingestion lag/freshness accounting (reference:
    `IngestionDelayTracker` + the `ServerGauge` realtime offset-lag family:
    REALTIME_INGESTION_DELAY_MS, LLC_PARTITION_CONSUMING, ...).

    One tracker per consuming partition; `pump()` feeds it per batch. Offset
    lag (latest stream offset vs last consumed) is computed on demand from
    the stream SPI so a PAUSED consumer's lag keeps growing while the
    producer runs — exactly the signal the controller's ingestion status
    check alerts on. Event times are epoch millis (the table's time column
    convention everywhere else: SegmentMeta start/end_time_ms)."""

    #: EWMA smoothing for the rows/s consumption rate (one batch = one sample)
    EWMA_ALPHA = 0.3

    def __init__(self, table: str, partition: int):
        self.table = table
        self.partition = partition
        self.rows_indexed = 0
        self.rows_filtered = 0        # fetched but dropped (filter/dedup)
        self.errors = 0
        self.last_consumed_ms: Optional[int] = None   # wall ms of last fetch>0
        self.last_event_time_ms: Optional[float] = None  # max indexed event-time
        self.rows_per_s = 0.0
        self._last_batch_t: Optional[float] = None
        self._lock = threading.Lock()

    def on_batch(self, fetched: int, indexed: int,
                 max_event_time_ms: Optional[float]) -> None:
        now = time.time()
        with self._lock:
            self.rows_indexed += indexed
            self.rows_filtered += max(fetched - indexed, 0)
            if fetched:
                self.last_consumed_ms = int(now * 1000)
            if max_event_time_ms is not None:
                self.last_event_time_ms = max(self.last_event_time_ms or 0.0,
                                              float(max_event_time_ms))
            if self._last_batch_t is not None:
                dt = max(now - self._last_batch_t, 1e-6)
                self.rows_per_s = (self.EWMA_ALPHA * (indexed / dt)
                                   + (1 - self.EWMA_ALPHA) * self.rows_per_s)
            self._last_batch_t = now

    def on_error(self) -> None:
        with self._lock:
            self.errors += 1


#: gauge families lag_status() exports per (table, partition); listed once so
#: the manager's teardown can remove exactly this set (stale-series hygiene)
_LAG_GAUGES = (
    "pinot_server_realtime_offset_lag",
    "pinot_server_realtime_freshness_lag_ms",
    "pinot_server_realtime_rows_per_s",
    "pinot_server_realtime_last_consumed_ts_ms",
)


class RealtimePartitionConsumer:
    """One consuming segment on one server (reference: LLRealtimeSegmentDataManager)."""

    def __init__(self, segment_name: str, table_cfg: TableConfig, schema,
                 start_offset: int, server_id: str, completion, data_dir: str,
                 pipeline: Optional[TransformPipeline] = None,
                 upsert=None, dedup=None, partial_rows: Optional[dict] = None):
        self.segment_name = segment_name
        self.table_cfg = table_cfg
        self.schema = schema
        self.server_id = server_id
        self.completion = completion            # LLCSegmentManager (or HTTP proxy)
        self.data_dir = data_dir
        self.state = INITIAL_CONSUMING
        props = table_cfg.stream.properties or {}
        # consuming-segment class selection: the chunked columnar store
        # (segment/mutable_device.py) is the default; per-row machinery
        # (upsert/dedup/realtime text+inverted indexes) needs the row-append
        # MutableSegment. `realtime.ingest.vectorized=false` opts out.
        vectorized_ok = (
            str(props.get("realtime.ingest.vectorized", "true")).lower()
            != "false"
            and upsert is None and dedup is None
            and not any(schema.has_column(c)
                        for c in table_cfg.indexing.text_index_columns)
            and not any(schema.has_column(c)
                        for c in table_cfg.indexing.inverted_index_columns))
        if vectorized_ok:
            from ..segment.mutable_device import DeviceMutableSegment
            self.mutable = DeviceMutableSegment(
                segment_name, schema,
                device_staging=str(props.get(
                    "realtime.ingest.device.staging", "false")).lower()
                == "true")
        else:
            self.mutable = MutableSegment(
                segment_name, schema,
                text_index_columns=table_cfg.indexing.text_index_columns,
                inverted_index_columns=table_cfg.indexing.inverted_index_columns)
        # per-pump fetch budget (messages); one columnar block message is
        # ~thousands of rows, so the default covers both framings
        try:
            self._batch_size = int(props.get("realtime.ingest.batch.size",
                                             10_000))
        except (TypeError, ValueError):
            self._batch_size = 10_000
        self.pipeline = pipeline or TransformPipeline(schema)
        self.upsert = upsert                    # TableUpsertMetadataManager or None
        self.dedup = dedup                      # PartitionDedupMetadataManager or None
        self.partial_rows = partial_rows if partial_rows is not None else {}
        stream_cfg = table_cfg.stream
        from ..cluster.completion import parse_llc_name
        self.partition = parse_llc_name(segment_name)["partition"]
        self._factory = get_stream_factory(stream_cfg.stream_type, stream_cfg.topic,
                                           stream_cfg.properties)
        # consumer creation is retried lazily from pump(): the topic may not
        # exist yet (producer races table creation) and a transient failure
        # here must not wedge the CONSUMING transition (reference: consumer
        # creation retries in LLRealtimeSegmentDataManager)
        try:
            self.consumer = self._factory.create_consumer(stream_cfg.topic,
                                                          self.partition)
        except Exception:
            self.consumer = None
        self.decoder = get_decoder(stream_cfg.decoder)
        # columnar fast path: raw-bytes fetch + one-shot batch decode
        # (stream.get_batch_decoder), used when the consumer supports
        # fetch_raw and no per-row machinery (dedup/upsert) is configured
        from .stream import get_batch_decoder, get_block_decoder
        self.batch_decoder = get_batch_decoder(stream_cfg.decoder)
        # columnar BLOCK streams (one message = one typed-column block of
        # rows, ingest/vectorized.py): whole-batch array indexing, no row
        # form ever exists — per-row machinery cannot run on this framing
        self.block_decoder = get_block_decoder(stream_cfg.decoder)
        if self.block_decoder is not None and (upsert is not None
                                               or dedup is not None):
            raise ValueError(
                f"table {table_cfg.name}: upsert/dedup need per-row decode "
                "and offsets; they cannot consume a columnar block stream")
        self.offset = start_offset
        self.start_consume_time = time.time()
        self.lag = ConsumerLagTracker(table_cfg.name, self.partition)
        self.catchup_target: Optional[int] = None
        # halt fence: on_segment_online sets `halted` and takes `pump_lock`
        # before the offset check + adoption build, so a background loop
        # thread's in-flight pump can never index rows past the committed end
        # offset into a segment about to be adopted (duplication with the
        # successor would follow)
        self.halted = False
        # controller-requested pause (reference: pauseConsumption): stop
        # fetching, and force-commit if rows are already held
        self.pause_requested = False
        self.pump_lock = threading.Lock()
        self._commit_done = threading.Event()  # set when _commit returns
        # observability: which decode strategy the last pump took
        # ("columnar" | "spliced" | "raw" | "batch" | "row" | None)
        self.last_decode_path: Optional[str] = None
        # set on the first fetch_spliced that reports no native splicer:
        # retrying it every pump would double-fetch every batch forever
        self._no_native_splice = False

    # -- consume loop ------------------------------------------------------
    def pump(self, max_messages: Optional[int] = None) -> int:
        """Fetch + decode + transform + index one batch; returns rows indexed
        (reference: consumeLoop one iteration).

        The network fetch runs OUTSIDE pump_lock (a stalled broker socket must
        not block catalog state transitions waiting on the lock); indexing and
        the offset publish re-check `halted` under the lock, so an adoption
        fence still discards any in-flight batch."""
        if self.halted or self.pause_requested or \
                self.state not in (INITIAL_CONSUMING, CATCHING_UP, HOLDING):
            return 0
        if self.consumer is None:
            try:
                self.consumer = self._factory.create_consumer(
                    self.table_cfg.stream.topic, self.partition)
            except Exception:
                return 0  # stream still unavailable; retry next tick
        limit = max_messages if max_messages is not None else self._batch_size
        if self.catchup_target is not None:
            limit = min(limit, self.catchup_target - self.offset)
            if limit <= 0:
                return 0
        fetch_from = self.offset
        # graftfault: stall = a slow upstream broker (the fetch runs outside
        # pump_lock, so a stall never blocks state transitions); lost = the
        # partition dies mid-consume — FaultInjected propagates to the consume
        # loop's error path (counted, backed off, retried from self.offset, so
        # recovery is exactly-once by construction)
        fault_point("stream.stall")
        fault_point("stream.partition.lost")
        batch_ok = self.dedup is None and self.upsert is None
        # Decode strategy, fastest available first (all fetches run OUTSIDE
        # pump_lock):
        #   1. SPLICED: transport joins raw values in C, ONE parse call
        #      (kafkalite fetch_spliced + a decoder with the spliced proto)
        #   2. COLUMNAR: raw value bytes list + one batch-decoder call
        #   3. BATCH: StreamMessage batch, per-message decode, one index_batch
        #   4. PER-ROW: dedup/upsert need per-row offsets/keys
        rows = None          # decoded row dicts (paths 1-2)
        cols = None          # index-ready columns (path 0, native columnar)
        cbatch = None        # ColumnarBatch (path 0 array-native upgrade)
        cbatches = None      # columnar BLOCK stream batches (path -1)
        batch = None         # MessageBatch (paths 3-4)
        next_offset = fetch_from
        rows_path = None
        if self.block_decoder is not None:
            # path -1: columnar block stream — every message is already a
            # typed-column block; decode is frombuffer views, indexing is
            # O(columns) chunk appends (ingest/vectorized.py)
            cbatches, next_offset = self._fetch_blocks(fetch_from, limit)
        elif batch_ok and self.batch_decoder is not None:
            spliced = getattr(self.batch_decoder, "spliced", None)
            fetch_spliced = None if self._no_native_splice else \
                getattr(self.consumer, "fetch_spliced", None)
            if spliced is not None and fetch_spliced is not None:
                prefix, sep, suffix, parse = spliced
                out = fetch_spliced(fetch_from, limit, sep=sep)
                if out is None:
                    self._no_native_splice = True
                else:
                    data, n, next_offset = out
                    if n == 0:
                        rows = []
                    elif (self.table_cfg.stream.decoder == "json"
                          and self.pipeline.filter_expr is None
                          and not self.pipeline.column_transforms):
                        # path 0: ONE C walk decodes straight to typed
                        # column ARRAYS when the segment can index them
                        # (vectorized.columnar_batch_from_json), else to
                        # coerced column lists
                        if hasattr(self.mutable, "index_arrays"):
                            from .vectorized import columnar_batch_from_json
                            try:
                                cbatch = columnar_batch_from_json(
                                    data, n, self.schema)
                            except Exception:
                                cbatch = None
                        if cbatch is None:
                            from .transform import columns_from_spliced_json
                            try:
                                cols = columns_from_spliced_json(
                                    data, n, self.schema)
                            except Exception:
                                cols = None
                    if n and cbatch is None and cols is None and rows is None:
                        try:
                            rows = parse(prefix + data + suffix)
                            rows_path = "spliced"
                        except Exception:
                            rows = None  # malformed member: isolate below
                        if rows is not None and len(rows) != n:
                            # a value smuggled top-level separators: the
                            # count is the transport's, the rows are the
                            # payload's — never index a drifted batch
                            # (offsets/flush thresholds would skew); the
                            # per-message path below isolates the culprit
                            rows = None
            if rows is None and cols is None and cbatch is None:
                fetch_raw = getattr(self.consumer, "fetch_raw", None)
                if fetch_raw is not None:
                    raw_values, next_offset = fetch_raw(fetch_from, limit)
                    if raw_values:
                        rows_path = "raw"
                        try:
                            rows = self.batch_decoder(raw_values)
                            if len(rows) != len(raw_values):
                                raise ValueError("batch decode row drift")
                        except Exception:
                            # one bad payload fails the whole-batch decode:
                            # per-message decode isolates it (json.loads
                            # accepts the raw bytes)
                            rows = [self.decoder(v) for v in raw_values]
                    else:
                        rows = []
        if rows is None and cols is None and cbatch is None \
                and cbatches is None:
            batch = self.consumer.fetch(fetch_from, limit)
            next_offset = batch.next_offset
        indexed = 0
        fetched = 0
        max_event: Optional[float] = None
        with self.pump_lock:
            if self.halted or self.offset != fetch_from:
                # adopted mid-fetch, or a CONCURRENT pump indexed this range
                # already (two drivers double-indexing the same batch would
                # duplicate rows): drop the batch, offset untouched
                return 0
            if cbatches is not None:
                self.last_decode_path = "blocks"
                tc = self.table_cfg.time_column
                # arrays index directly unless the table configured row-level
                # transforms/filters (then blocks round-trip through lists)
                direct = (hasattr(self.mutable, "index_arrays")
                          and self.pipeline.filter_expr is None
                          and not self.pipeline.column_transforms)
                for cb in cbatches:
                    fetched += cb.n
                    if tc:
                        ev = cb.max_of(tc)
                        if ev is not None and (max_event is None
                                               or ev > max_event):
                            max_event = ev
                    if direct:
                        indexed += self.mutable.index_arrays(cb)
                    else:
                        indexed += self.mutable.index_batch(
                            self.pipeline.apply(cb.to_lists(self.schema)),
                            coerced=True)
            elif cbatch is not None:
                self.last_decode_path = "columnar-array"
                fetched = cbatch.n
                tc = self.table_cfg.time_column
                max_event = cbatch.max_of(tc) if tc else None
                indexed = self.mutable.index_arrays(cbatch)
            elif cols is not None:
                self.last_decode_path = "columnar"
                fetched = len(next(iter(cols.values()))) if cols else 0
                max_event = self._max_event_time(cols=cols)
                indexed = self.mutable.index_batch(cols, coerced=True)
            elif rows is not None:
                if rows:
                    self.last_decode_path = rows_path
                    fetched = len(rows)
                    max_event = self._max_event_time(rows=rows)
                    from .transform import rows_to_all_columns
                    indexed = self.mutable.index_batch(
                        self.pipeline.apply(rows_to_all_columns(rows)),
                        coerced=True)
            elif batch_ok and batch.messages:
                self.last_decode_path = "batch"
                # batch path: decode the whole batch, run the transform
                # pipeline ONCE over it (vectorized filter + coercion), and
                # append column-wise — per-row dict/array churn dominates the
                # consume rate otherwise (reference: MessageBatch-granular
                # indexing in LLRealtimeSegmentDataManager.processStreamEvents)
                from .transform import rows_to_all_columns
                decoded = [self.decoder(m.value) for m in batch.messages]
                fetched = len(decoded)
                max_event = self._max_event_time(rows=decoded)
                indexed = self.mutable.index_batch(
                    self.pipeline.apply(rows_to_all_columns(decoded)),
                    coerced=True)
            else:
                self.last_decode_path = "row"
                fetched = len(batch.messages)
                decoded = [self.decoder(m.value) for m in batch.messages]
                max_event = self._max_event_time(rows=decoded)
                for row, msg in zip(decoded, batch.messages):
                    row = self.pipeline.apply_row(row)
                    if row is not None and self._index_row(row, msg.offset):
                        indexed += 1
            self.offset = next_offset
        self.lag.on_batch(fetched, indexed, max_event)
        if indexed or fetched:
            from ..utils.metrics import get_registry
            reg = get_registry()
            if indexed:  # ServerMeter REALTIME_ROWS_CONSUMED analog
                reg.counter("pinot_server_realtime_rows_consumed",
                            {"table": self.table_cfg.name}).inc(indexed)
            if fetched > indexed:  # filter/dedup drops (ROWS_FILTERED analog)
                reg.counter("pinot_server_realtime_rows_filtered",
                            {"table": self.table_cfg.name}).inc(fetched - indexed)
        return indexed

    def _fetch_blocks(self, fetch_from: int, limit: int):
        """Fetch + decode one columnar-block batch (runs OUTSIDE pump_lock).
        Returns (List[ColumnarBatch], next_offset). Prefers the transport's
        native splice (one buffer, frombuffer column views), falls back to
        raw value lists, then to the generic MessageBatch fetch."""
        bd = self.block_decoder
        fetch_spliced = None if self._no_native_splice else \
            getattr(self.consumer, "fetch_spliced", None)
        if fetch_spliced is not None:
            out = fetch_spliced(fetch_from, limit, sep=bd.sep)
            if out is None:
                self._no_native_splice = True
            else:
                data, n_msgs, next_offset = out
                batches = bd.decode_spliced(data, n_msgs) if n_msgs else []
                return batches, next_offset
        fetch_raw = getattr(self.consumer, "fetch_raw", None)
        if fetch_raw is not None:
            raw_values, next_offset = fetch_raw(fetch_from, limit)
            return [bd.decode_one(v) for v in raw_values], next_offset
        batch = self.consumer.fetch(fetch_from, limit)
        return ([bd.decode_one(m.value) for m in batch.messages],
                batch.next_offset)

    def query_segment(self):
        """The segment object queries should execute against: a frozen
        point-in-time view when the store provides one (cached per num_docs,
        optionally device-backed), else the mutable segment itself."""
        qv = getattr(self.mutable, "query_view", None)
        return qv() if qv is not None else self.mutable

    def _index_row(self, row: Dict, msg_offset: int) -> bool:
        """Index with dedup/upsert hooks (reference: MutableSegmentImpl.index
        upsert/dedup hooks at :498-541)."""
        pk_cols = self.schema.primary_key_columns
        pk = tuple(row.get(c) for c in pk_cols) if pk_cols else None

        if self.dedup is not None and pk is not None:
            if not self.dedup.check_and_add(pk):
                return False  # exact duplicate dropped at ingest

        if self.upsert is not None and pk is not None:
            up_cfg = self.table_cfg.upsert
            if up_cfg and up_cfg.mode == "PARTIAL":
                prev = self.partial_rows.get(pk)
                if prev is not None:
                    from ..upsert import merge_partial
                    merged = dict(prev)
                    for col, val in row.items():
                        if col in pk_cols:
                            continue
                        strategy = up_cfg.partial_strategies.get(col, "OVERWRITE")
                        merged[col] = merge_partial(strategy, prev.get(col), val)
                    row = merged
                self.partial_rows[pk] = dict(row)
            cmp_val = (row.get(up_cfg.comparison_column)
                       if up_cfg and up_cfg.comparison_column else msg_offset)
            doc_id = self.mutable.num_docs
            self.mutable.index(row)
            self.upsert.partition(self.partition).add_record(
                self.segment_name, doc_id, pk, cmp_val)
            return True

        self.mutable.index(row)
        return True

    def _max_event_time(self, rows=None, cols=None) -> Optional[float]:
        """Max event-time (epoch ms) in one decoded batch, from the table's
        time column; None when the table has no time column or the batch
        carries no usable values (freshness then falls back to consume
        wall-clock)."""
        tc = self.table_cfg.time_column
        if not tc:
            return None
        try:
            if cols is not None:
                vals = cols.get(tc)
                if vals is None or not len(vals):
                    return None
                best = max(v for v in vals if v is not None)
                return float(best)
            best = None
            for r in rows or ():
                v = r.get(tc)
                if v is not None and (best is None or v > best):
                    best = v
            return float(best) if best is not None else None
        except (TypeError, ValueError):
            return None  # non-numeric / all-null time values: no freshness signal

    # -- lag / freshness observability -------------------------------------
    def freshness_time_ms(self) -> int:
        """Timestamp of the freshest data this consumer serves (reference:
        consuming segment's latest ingestion time behind
        minConsumingFreshnessTimeMs): max indexed event-time, else last
        consume wall time, else when consumption started."""
        lt = self.lag.last_event_time_ms or self.lag.last_consumed_ms
        return int(lt if lt is not None else self.start_consume_time * 1000)

    def lag_status(self, export: bool = True) -> Dict[str, object]:
        """One consuming segment's lag snapshot (consumingSegmentsInfo row);
        also exports the pinot_server_realtime_* gauges unless told not to."""
        latest = None
        if self.consumer is not None:
            try:
                latest = int(self.consumer.latest_offset())
            except Exception:
                latest = None   # stream probe failed; lag unknown this round
        offset_lag = max(latest - self.offset, 0) if latest is not None else None
        fresh = self.freshness_time_ms()
        freshness_lag = max(int(time.time() * 1000) - fresh, 0)
        st = {"segment": self.segment_name, "partition": self.partition,
              "state": self.state, "paused": self.pause_requested,
              "currentOffset": self.offset, "latestStreamOffset": latest,
              "offsetLag": offset_lag, "freshnessTimeMs": fresh,
              "freshnessLagMs": freshness_lag,
              "rowsPerSecond": round(self.lag.rows_per_s, 3),
              "rowsIndexed": self.lag.rows_indexed,
              "rowsFiltered": self.lag.rows_filtered,
              "consumeErrors": self.lag.errors,
              "lastConsumedMs": self.lag.last_consumed_ms,
              "numDocs": self.mutable.num_docs}
        if export:
            from ..utils.metrics import get_registry
            reg = get_registry()
            labels = {"table": self.table_cfg.name,
                      "partition": str(self.partition)}
            if offset_lag is not None:
                reg.gauge(_LAG_GAUGES[0], labels).set(offset_lag)
            reg.gauge(_LAG_GAUGES[1], labels).set(freshness_lag)
            reg.gauge(_LAG_GAUGES[2], labels).set(self.lag.rows_per_s)
            reg.gauge(_LAG_GAUGES[3], labels).set(self.lag.last_consumed_ms or 0)
        return st

    def close(self) -> None:
        """Halt pumping and release the stream connection (idempotent)."""
        self.halted = True
        close_fn = getattr(self.consumer, "close", None)
        if close_fn is not None:
            try:
                close_fn()
            # graftcheck: ignore[exception-hygiene] -- idempotent teardown:
            # an already-closed consumer is the desired end state
            except Exception:
                pass  # already torn down / broker gone

    def end_criteria_reached(self) -> bool:
        """Reference: row-count / time thresholds (realtime.segment.flush.*)."""
        stream_cfg = self.table_cfg.stream
        if self.mutable.num_docs >= stream_cfg.flush_threshold_rows:
            return True
        return (time.time() - self.start_consume_time
                >= stream_cfg.flush_threshold_seconds and self.mutable.num_docs > 0)

    # -- completion protocol (reference: PartitionConsumer.run postConsume) -------
    def maybe_complete(self) -> str:
        """Run one protocol round-trip; returns the resulting consumer state."""
        if self.state in (COMMITTED, DISCARDED, RETAINED, ERROR):
            return self.state
        force = self.pause_requested and self.mutable.num_docs > 0
        if not force and not self.end_criteria_reached() \
                and self.catchup_target is None:
            return self.state

        resp = self.completion.segment_consumed(self.segment_name, self.server_id,
                                                self.offset)
        status = resp["status"]
        if status == "HOLD":
            self.state = HOLDING
        elif status == "CATCHUP":
            self.state = CATCHING_UP
            self.catchup_target = int(resp["offset"])
        elif status == "COMMIT":
            self._commit()
        elif status == "KEEP":
            self.state = RETAINED
        elif status == "DISCARD":
            self.state = DISCARDED
        else:
            self.state = ERROR
        return self.state

    def _commit(self) -> None:
        """Reference: buildSegmentForCommit (:699) + commitSegment (:705):
        commitStart -> build immutable -> upload -> commitEnd."""
        self.state = COMMITTING
        # in-proc clusters run catalog notifications (and thus the reconcile
        # that calls on_segment_online) ON THIS THREAD from inside commit_end;
        # the marker lets adoption recognize its own in-flight commit instead
        # of waiting for a state flip that cannot happen until we return
        self._commit_thread = threading.get_ident()
        try:
            if self.completion.segment_commit_start(self.segment_name,
                                                    self.server_id) \
                    != "COMMIT_CONTINUE":
                self.state = ERROR
                return
            seg_dir = self.build_immutable()
            resp = self.completion.segment_commit_end(
                self.segment_name, self.server_id, seg_dir, self.offset)
            self.state = COMMITTED if resp == "COMMIT_SUCCESS" else ERROR
        finally:
            self._commit_thread = None
            self._commit_done.set()
        if self.state == COMMITTED:
            from ..utils.metrics import get_registry
            get_registry().counter("pinot_server_realtime_segments_committed",
                                   {"table": self.table_cfg.name}).inc()

    def build_immutable(self) -> str:
        """Convert mutable -> immutable on disk (reference: RealtimeSegmentConverter)."""
        builder = SegmentBuilder(
            self.schema,
            SegmentGeneratorConfig.from_indexing(self.table_cfg.indexing))
        # already-columnar commit: the chunked store hands the builder typed
        # arrays directly (no python-list round trip) when it can
        snap_arrays = getattr(self.mutable, "snapshot_arrays", None)
        columns = snap_arrays() if snap_arrays is not None \
            else self.mutable.snapshot_columns()
        return builder.build(columns,
                             os.path.join(self.data_dir, "realtime_build"),
                             self.segment_name)


class RealtimeTableManager:
    """Per-(server, table) realtime coordinator (reference: RealtimeTableDataManager)."""

    def __init__(self, server, table: str, table_cfg: TableConfig, completion):
        self.server = server
        self.table = table
        self.table_cfg = table_cfg
        self.completion = completion
        self.consumers: Dict[str, RealtimePartitionConsumer] = {}
        self._lock = threading.RLock()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._pump_pool = None   # lazy per-partition pump lanes (pump_all)
        transforms = (table_cfg.stream.properties or {}).get("columnTransforms")
        filter_expr = (table_cfg.stream.properties or {}).get("filterExpr")
        schema = server.catalog.schema_for_table(table)
        self._pipeline = TransformPipeline(schema, filter_expr, transforms)
        from ..upsert import PartitionDedupMetadataManager, TableUpsertMetadataManager
        self.upsert = TableUpsertMetadataManager() if table_cfg.upsert else None
        self._dedup: Dict[int, PartitionDedupMetadataManager] = {}
        self.dedup_enabled = table_cfg.dedup_enabled
        self.partial_rows: Dict[tuple, dict] = {}
        # inherit an already-paused table's state for consumers started later
        self._paused = bool(server.catalog.get_property(f"pause/{table}"))

    # wired from ServerNode.reconcile on CONSUMING transitions
    def start_consuming(self, segment_name: str) -> None:
        with self._lock:
            if segment_name in self.consumers:
                return
            meta = self.server.catalog.segments.get(self.table, {}).get(segment_name)
            start_offset = int(meta.start_offset) if meta and meta.start_offset else 0
            schema = self.server.catalog.schema_for_table(self.table)
            from ..cluster.completion import parse_llc_name
            partition = parse_llc_name(segment_name)["partition"]
            from ..upsert import PartitionDedupMetadataManager
            dedup = None
            if self.dedup_enabled:
                dedup = self._dedup.setdefault(partition, PartitionDedupMetadataManager())
            consumer = RealtimePartitionConsumer(
                segment_name, self.table_cfg, schema, start_offset,
                self.server.instance_id, self.completion, self.server.data_dir,
                self._pipeline, upsert=self.upsert, dedup=dedup,
                partial_rows=self.partial_rows)
            consumer.pause_requested = self._paused
            self.consumers[segment_name] = consumer

    def stop_consuming(self, segment_name: str) -> Optional[RealtimePartitionConsumer]:
        with self._lock:
            consumer = self.consumers.pop(segment_name, None)
        if consumer is not None:
            # the partition's lag series dies with its consumer (a successor
            # segment re-exports it on the next status snapshot)
            self._remove_lag_gauges([consumer])
            self._release_device(consumer)
        return consumer

    def retire_consumer(self, segment_name: str) -> None:
        """Second half of the CONSUMING->ONLINE handoff: drop the retained
        post-commit consumer once the immutable copy is registered and
        serving. Until this call its mutable buffer keeps answering queries,
        so the segment is never unserved mid-handoff."""
        with self._lock:
            consumer = self.consumers.pop(segment_name, None)
        if consumer is not None:
            self._release_device(consumer)

    @staticmethod
    def _release_device(consumer) -> None:
        """Free a dropped consumer's device staging (and its memory-ledger
        entries) — DeviceMutableSegment only; plain MutableSegment holds no
        device arrays."""
        release = getattr(consumer.mutable, "release_device", None)
        if release is not None:
            try:
                release()
            # graftcheck: ignore[exception-hygiene] -- teardown best-effort:
            # a failed device free must not block the commit handoff
            except Exception:
                pass

    # -- segment transition handling --------------------------------------
    def on_segment_online(self, segment_name: str) -> Optional[str]:
        """CONSUMING -> ONLINE for this replica (reference:
        SegmentOnlineOfflineStateModelFactory.onBecomeOnlineFromConsuming:91): adopt the
        local build when committed here or offsets match (KEEP), else signal the caller
        to download the committed copy.

        The consumer STAYS registered (serving its mutable buffer to queries)
        until the caller registers the immutable copy and calls
        `retire_consumer` — popping it here would leave the segment unserved
        for the whole load/download window, and every query in that window
        would fail over to a replica whose consumer may be far behind
        (COUNT(*) visibly regressing mid-commit)."""
        with self._lock:
            consumer = self.consumers.get(segment_name)
        if consumer is None:
            return None
        # the committer usually arrives here while its commitEnd call is still
        # in flight (the controller publishes ONLINE before responding). Two
        # shapes: in-proc, THIS thread is the committer mid-call (the state
        # cannot flip until we return — recognize our own commit and adopt the
        # already-built dir); over HTTP, a different thread is committing —
        # wait briefly for the COMMITTING->COMMITTED flip instead of
        # re-downloading what this very server just uploaded.
        own_commit = (getattr(consumer, "_commit_thread", None)
                      == threading.get_ident())
        if not own_commit and consumer.state == COMMITTING:
            # bounded event wait (NOT a long poll): this runs on the server's
            # single catalog-watch thread, and every second spent here stalls
            # ALL state transitions — time out quickly and fall back to the
            # deep-store download, which is merely wasteful, never wrong
            consumer._commit_done.wait(2.0)
        # fence out the background consume loop BEFORE inspecting offsets: an
        # in-flight pump could otherwise index rows past the committed end
        # offset between the check and the build (duplicating them with the
        # successor segment)
        consumer.halted = True
        try:
            with consumer.pump_lock:
                if consumer.state == COMMITTED or \
                        (own_commit and consumer.state == COMMITTING):
                    seg_dir = os.path.join(consumer.data_dir, "realtime_build",
                                           segment_name)
                    if os.path.isdir(seg_dir):
                        return seg_dir
                if consumer.state in (INITIAL_CONSUMING, HOLDING, CATCHING_UP,
                                      RETAINED):
                    meta = self.server.catalog.segments.get(
                        self.table, {}).get(segment_name)
                    if meta is not None and meta.end_offset is not None \
                            and consumer.offset == int(meta.end_offset):
                        return consumer.build_immutable()
            return None  # caller downloads from deep store
        finally:
            consumer.close()  # the stream connection is done either way

    # -- query integration -------------------------------------------------
    def consuming_results(self, ctx: QueryContext,
                          segment_names: Optional[Sequence[str]] = None,
                          exclude: Sequence[str] = ()
                          ) -> Tuple[List[SegmentResult], List[str]]:
        """(results, served names) — BOTH from one locked snapshot: serve/not
        is decided once per segment, so the served list always matches what
        the results actually include. Deciding them separately would let a
        commit land in between, and the broker would retry a segment whose
        rows were already counted (double count), or vice versa.

        COMMITTED consumers keep serving their mutable buffer until
        `retire_consumer` swaps in the immutable copy — `exclude` (segments
        the caller already answered immutably in THIS query) prevents the
        one double-count window that creates. DISCARDED stays unserved: its
        rows lost the commit race and may disagree with the winning copy."""
        with self._lock:
            snapshot = [(name, c) for name, c in self.consumers.items()
                        if (segment_names is None or name in segment_names)
                        and c.state != DISCARDED and name not in exclude]
        served = [name for name, _ in snapshot]
        out = []
        for _, c in snapshot:
            if c.mutable.num_docs > 0:
                # frozen per-num_docs view when the store provides one: idle
                # consuming segments stop paying the O(rows) re-snapshot per
                # query, and device-staged stores serve from HBM buffers
                seg = c.query_segment()
                valid = (self.upsert.valid_mask(c.segment_name, seg.num_docs)
                         if self.upsert else None)
                out.append(self.server.executor.execute_segment(ctx, seg, valid))
        return out, served

    # -- ingestion health rollup (reference: consumingSegmentsInfo + the
    # tableIngestionStatus the controller aggregates) -----------------------
    def ingestion_status(self) -> Dict[str, object]:
        """Per-table rollup of every consuming segment's lag snapshot, plus
        the worst-case numbers the controller's verdict keys off."""
        with self._lock:
            consumers = list(self.consumers.items())
        segs = {name: c.lag_status() for name, c in consumers}
        offset_lags = [s["offsetLag"] for s in segs.values()
                       if s["offsetLag"] is not None]
        return {
            "table": self.table,
            "paused": self._paused,
            "numConsumingSegments": len(segs),
            "maxOffsetLag": max(offset_lags) if offset_lags else 0,
            "maxFreshnessLagMs": max((s["freshnessLagMs"] for s in segs.values()),
                                     default=0),
            "totalRowsPerSecond": round(sum(s["rowsPerSecond"]
                                            for s in segs.values()), 3),
            "errorSegments": sorted(n for n, s in segs.items()
                                    if s["state"] == ERROR),
            "segments": segs,
        }

    def min_freshness_ms(self, segment_names: Sequence[str]) -> Optional[int]:
        """Min freshness timestamp across the named consuming segments (the
        per-server contribution to minConsumingFreshnessTimeMs)."""
        with self._lock:
            consumers = [c for n, c in self.consumers.items()
                         if n in segment_names]
        if not consumers:
            return None
        return min(c.freshness_time_ms() for c in consumers)

    def _remove_lag_gauges(self, consumers: Sequence[RealtimePartitionConsumer]
                           ) -> None:
        """Drop this table's per-partition lag series (table drop/manager
        teardown) — same stale-gauge hygiene as the controller's status check."""
        from ..utils.metrics import get_registry
        reg = get_registry()
        for c in consumers:
            labels = {"table": self.table_cfg.name,
                      "partition": str(c.partition)}
            for g in _LAG_GAUGES:
                reg.remove_gauge(g, labels)

    # -- deterministic drive (tests) / background loop (production) ---------
    def pump_all(self, max_messages: Optional[int] = None) -> int:
        """Pump every consuming partition once. Partitions are independent
        lanes: each consumer has its own pump_lock and stream socket, so
        multi-partition tables pump CONCURRENTLY on the manager's pool —
        fetch waits and GIL-releasing numpy decode overlap across partitions
        instead of serializing behind one loop (the seed's 8p < 1p floor).
        The manager lock is held only to snapshot the consumer list."""
        with self._lock:
            consumers = list(self.consumers.values())
        if not consumers:
            return 0
        if len(consumers) == 1:
            return self._pump_one(consumers[0], max_messages)
        pool = self._pump_pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor
            pool = ThreadPoolExecutor(
                max_workers=8,
                thread_name_prefix=f"pump-{self.table}")
            self._pump_pool = pool
        futures = [pool.submit(self._pump_one, c, max_messages)
                   for c in consumers]
        # bounded collection: a wedged broker socket surfaces as a loop-level
        # error (start_loop backs off), never as a silently stuck pump thread
        return sum(f.result(timeout=60.0) for f in futures)

    def _pump_one(self, c: RealtimePartitionConsumer,
                  max_messages: Optional[int]) -> int:
        try:
            return c.pump(max_messages)
        except Exception:
            # per-partition attribution before the loop-level backoff
            # (start_loop meters + retries; tests see tracker.errors)
            c.lag.on_error()
            raise

    def complete_all(self) -> Dict[str, str]:
        with self._lock:
            consumers = list(self.consumers.items())
        return {name: c.maybe_complete() for name, c in consumers}

    def set_paused(self, paused: bool) -> None:
        """Controller pause/resume fan-in (reference: pause propagated to
        servers via ideal state; here via the catalog pause property). Paused
        consumers stop fetching; those already holding rows force-commit on
        the next completion tick."""
        with self._lock:
            self._paused = paused
            for c in self.consumers.values():
                c.pause_requested = paused

    def start_loop(self, interval_s: float = 0.1) -> None:
        def loop():
            import sys
            errors = 0
            while not self._stop.is_set():
                try:
                    self.pump_all()
                    self.complete_all()
                    errors = 0
                except Exception as e:
                    # a transient broker/controller error (socket hiccup,
                    # completion 5xx past its retries) or a poison message must
                    # not kill the consume thread forever — log the FIRST
                    # failure of a streak, meter every one, and back off
                    # exponentially so a wedged partition is a visible slow
                    # retry, not a silent 10 req/s hot loop
                    errors += 1
                    from ..utils.metrics import get_registry
                    get_registry().counter("pinot_server_consume_errors",
                                           {"table": self.table}).inc()
                    if errors == 1:
                        print(f"[pinot-tpu] consume error on {self.table}: "
                              f"{type(e).__name__}: {e} (backing off)",
                              file=sys.stderr)
                    self._stop.wait(min(interval_s * (2 ** min(errors, 6)), 5.0))
                self._stop.wait(interval_s)
        t = threading.Thread(target=loop, daemon=True,
                             name=f"consume-{self.server.instance_id}-{self.table}")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        pool = self._pump_pool
        if pool is not None:
            self._pump_pool = None
            pool.shutdown(wait=False)
        with self._lock:
            consumers = list(self.consumers.values())
            self.consumers.clear()
        for c in consumers:   # release stream sockets (kafkalite TCP etc.)
            c.close()
        self._remove_lag_gauges(consumers)
