"""KafkaLite: a Kafka-protocol-shaped TCP log broker + the stream plugin for it.

The reference ships a Kafka consumer plugin (`pinot-plugins/pinot-stream-ingestion/
pinot-kafka-2.0/.../KafkaPartitionLevelConsumer.java`) against an external Kafka
cluster; this module provides both halves so the stream SPI is proven against a REAL
socket boundary with Kafka's model intact:

* `LogBrokerServer` — partitioned, offset-addressed, append-only topic logs served
  over TCP. The wire protocol mirrors Kafka's shape: length-prefixed frames, an apiKey
  + correlationId header, and PRODUCE / FETCH / LIST_OFFSETS / METADATA /
  CREATE_TOPICS request types (JSON bodies instead of Kafka's binary encoding — the
  *protocol semantics*, long-polling FETCH included, are what the consumer exercises).
  Optional file-backed logs (JSONL per partition) survive broker restarts.
* `KafkaLiteConsumer` / `KafkaLiteFactory` — the plugin side: implements
  `PartitionGroupConsumer`/`StreamConsumerFactory` purely in terms of the socket
  client, registering as stream type "kafkalite". The realtime consumption FSM
  (`ingest/realtime.py`) runs against it UNCHANGED — the SPI claim the reference
  makes for its Kafka plugin, demonstrated end-to-end in tests/test_kafkalite.py.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from .stream import (MessageBatch, PartitionGroupConsumer, StreamConsumerFactory,
                     StreamMessage, StreamMetadataProvider, register_stream_factory)

# api keys (named after their Kafka counterparts)
PRODUCE = "Produce"
FETCH = "Fetch"
LIST_OFFSETS = "ListOffsets"
METADATA = "Metadata"
CREATE_TOPICS = "CreateTopics"


def _send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    return json.loads(payload.decode())


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _PartitionLog:
    """Append-only offset-addressed log, optionally file-backed (JSONL)."""

    def __init__(self, path: Optional[str]):
        self.records: List[Tuple[Any, Optional[str], int]] = []  # (value, key, ts)
        self.path = path
        if path and os.path.exists(path):
            with open(path) as f:
                for line in f:
                    d = json.loads(line)
                    self.records.append((d["v"], d.get("k"), d.get("t", 0)))
        self._file = open(path, "a") if path else None

    def append(self, value: Any, key: Optional[str], ts: int) -> int:
        offset = len(self.records)
        self.records.append((value, key, ts))
        if self._file:
            self._file.write(json.dumps({"v": value, "k": key, "t": ts}) + "\n")
            self._file.flush()
        return offset

    def close(self):
        if self._file:
            self._file.close()


class LogBrokerServer:
    """The broker process: accept loop + per-connection request threads."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 log_dir: Optional[str] = None):
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self._topics: Dict[str, List[_PartitionLog]] = {}
        self._lock = threading.RLock()
        self._data_arrived = threading.Condition(self._lock)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        if log_dir:
            self._load_existing_topics()
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="kafkalite-accept", daemon=True)
        self._acceptor.start()

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def _load_existing_topics(self) -> None:
        for topic in sorted(os.listdir(self.log_dir)):
            tdir = os.path.join(self.log_dir, topic)
            if not os.path.isdir(tdir):
                continue
            parts = sorted(int(p.split(".")[0]) for p in os.listdir(tdir))
            self._topics[topic] = [
                _PartitionLog(os.path.join(tdir, f"{p}.jsonl")) for p in parts]

    def create_topic(self, topic: str, num_partitions: int) -> None:
        with self._lock:
            if topic in self._topics:
                return
            paths = [None] * num_partitions
            if self.log_dir:
                tdir = os.path.join(self.log_dir, topic)
                os.makedirs(tdir, exist_ok=True)
                paths = [os.path.join(tdir, f"{p}.jsonl") for p in range(num_partitions)]
            self._topics[topic] = [_PartitionLog(p) for p in paths]

    # -- request handling ----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            th = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            th.start()
            self._threads.append(th)

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    req = _recv_frame(conn)
                except OSError:
                    return
                if req is None:
                    return
                resp = {"correlationId": req.get("correlationId")}
                try:
                    resp.update(self._handle(req))
                except Exception as e:
                    resp["error"] = f"{type(e).__name__}: {e}"
                try:
                    _send_frame(conn, resp)
                except OSError:
                    return

    def _handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        api = req["apiKey"]
        if api == CREATE_TOPICS:
            self.create_topic(req["topic"], int(req["numPartitions"]))
            return {}
        if api == METADATA:
            with self._lock:
                if req.get("topic"):
                    logs = self._topics.get(req["topic"])
                    if logs is None:
                        raise KeyError(f"unknown topic {req['topic']!r}")
                    return {"numPartitions": len(logs)}
                return {"topics": {t: len(ls) for t, ls in self._topics.items()}}
        if api == PRODUCE:
            with self._lock:
                logs = self._topics[req["topic"]]
                partition = req.get("partition")
                if partition is None:
                    key = req.get("key")
                    if key is not None:
                        # stable across processes/restarts (Python's hash() is
                        # salted per process and would break key->partition
                        # affinity over the file-backed logs)
                        import zlib
                        partition = zlib.crc32(str(key).encode()) % len(logs)
                    else:
                        partition = sum(len(l.records) for l in logs) % len(logs)
                offset = logs[partition].append(req["value"], req.get("key"),
                                                int(req.get("timestampMs", 0)))
                self._data_arrived.notify_all()
            return {"partition": partition, "offset": offset}
        if api == LIST_OFFSETS:
            with self._lock:
                log = self._topics[req["topic"]][req["partition"]]
                return {"earliest": 0, "latest": len(log.records)}
        if api == FETCH:
            start = int(req["offset"])
            max_messages = int(req.get("maxMessages", 500))
            timeout_ms = int(req.get("timeoutMs", 0))
            deadline = timeout_ms / 1000.0
            with self._lock:
                log = self._topics[req["topic"]][req["partition"]]
                if start >= len(log.records) and timeout_ms > 0:
                    # long-poll like Kafka's fetch.max.wait.ms
                    self._data_arrived.wait(deadline)
                records = log.records[start:start + max_messages]
            return {"messages": [{"v": v, "k": k, "t": t, "o": start + i}
                                 for i, (v, k, t) in enumerate(records)],
                    "nextOffset": start + len(records)}
        raise ValueError(f"unknown apiKey {api!r}")

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for logs in self._topics.values():
                for log in logs:
                    log.close()


class LogBrokerClient:
    """One TCP connection to the broker; thread-safe request/response."""

    def __init__(self, bootstrap: str, timeout_s: float = 30.0):
        host, port = bootstrap.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout_s)
        self._lock = threading.Lock()
        self._correlation = 0

    def request(self, api: str, **fields) -> Dict[str, Any]:
        with self._lock:
            self._correlation += 1
            cid = self._correlation
            _send_frame(self._sock, {"apiKey": api, "correlationId": cid, **fields})
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("broker closed the connection")
        if resp.get("correlationId") != cid:
            raise ConnectionError("correlation id mismatch")
        if resp.get("error"):
            raise RuntimeError(resp["error"])
        return resp

    def create_topic(self, topic: str, num_partitions: int) -> None:
        self.request(CREATE_TOPICS, topic=topic, numPartitions=num_partitions)

    def produce(self, topic: str, value: Any, partition: Optional[int] = None,
                key: Optional[str] = None, timestamp_ms: int = 0) -> int:
        resp = self.request(PRODUCE, topic=topic, value=value, partition=partition,
                            key=key, timestampMs=timestamp_ms)
        return resp["offset"]

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# -- the stream SPI plugin ----------------------------------------------------

class KafkaLiteConsumer(PartitionGroupConsumer):
    """PartitionGroupConsumer over the socket client (the
    KafkaPartitionLevelConsumer analog)."""

    def __init__(self, bootstrap: str, topic: str, partition: int):
        self.client = LogBrokerClient(bootstrap)
        self.topic = topic
        self.partition = partition

    def fetch(self, start_offset: int, max_messages: int, timeout_ms: int = 0) -> MessageBatch:
        resp = self.client.request(FETCH, topic=self.topic, partition=self.partition,
                                   offset=start_offset, maxMessages=max_messages,
                                   timeoutMs=timeout_ms)
        msgs = [StreamMessage(value=m["v"], offset=m["o"], key=m.get("k"),
                              timestamp_ms=m.get("t", 0)) for m in resp["messages"]]
        return MessageBatch(msgs, resp["nextOffset"])

    def latest_offset(self) -> int:
        return self.client.request(LIST_OFFSETS, topic=self.topic,
                                   partition=self.partition)["latest"]

    def close(self) -> None:
        self.client.close()


class KafkaLiteFactory(StreamConsumerFactory):
    """Stream plugin factory; `properties["bootstrap"]` locates the broker."""

    def __init__(self, topic: str, properties: Optional[Dict[str, Any]] = None):
        self.topic = topic
        props = properties or {}
        self.bootstrap = props.get("bootstrap", "")
        if not self.bootstrap:
            raise ValueError("kafkalite stream requires properties['bootstrap']")

    def create_consumer(self, topic: str, partition: int) -> PartitionGroupConsumer:
        return KafkaLiteConsumer(self.bootstrap, topic or self.topic, partition)

    def metadata_provider(self) -> StreamMetadataProvider:
        factory = self

        class _Meta(StreamMetadataProvider):
            def partition_count(self, topic: str) -> int:
                client = LogBrokerClient(factory.bootstrap)
                try:
                    return client.request(METADATA,
                                          topic=topic or factory.topic)["numPartitions"]
                finally:
                    client.close()

        return _Meta()


register_stream_factory("kafkalite", KafkaLiteFactory)
