"""KafkaLite: a Kafka-wire-protocol TCP log broker + the stream plugin for it.

The reference ships a Kafka consumer plugin (`pinot-plugins/pinot-stream-ingestion/
pinot-kafka-2.0/.../KafkaPartitionLevelConsumer.java`) against an external Kafka
cluster; this module provides both halves so the stream SPI is proven against a REAL
socket boundary speaking Kafka's ACTUAL binary encoding (`ingest/kafka_wire.py`):

* `LogBrokerServer` — partitioned, offset-addressed, append-only topic logs served
  over TCP with Kafka framing: length-prefixed frames, the int16 api_key/api_version
  + int32 correlation_id header, ApiVersions / Metadata / ListOffsets / Fetch (with
  `max_wait_ms` long-polling) / Produce / CreateTopics bodies, and record batches in
  the v2 (magic=2, CRC-32C, zigzag-varint) format — so a stock Kafka client can
  produce into it and our consumer fetches real Kafka frames. Optional file-backed
  logs (JSONL per partition) survive broker restarts.
* `KafkaLiteConsumer` / `KafkaLiteFactory` — the plugin side: implements
  `PartitionGroupConsumer`/`StreamConsumerFactory` purely in terms of the binary
  client, registering as stream type "kafkalite". The realtime consumption FSM
  (`ingest/realtime.py`) runs against it UNCHANGED — the SPI claim the reference
  makes for its Kafka plugin, demonstrated end-to-end in tests/test_kafkalite.py.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..utils.faults import fault_point
from . import kafka_wire as kw
from .stream import (MessageBatch, PartitionGroupConsumer, StreamConsumerFactory,
                     StreamMessage, StreamMetadataProvider, register_stream_factory)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # recv_into one preallocated buffer: `buf += chunk` reallocates and
    # copies the prefix per recv call, which at multi-MB fetch payloads costs
    # more than the kernel copy itself (O(n^2) over the chunk count)
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            return None
        got += r
    return bytes(buf)


def _recv_payload(sock: socket.socket) -> Optional[bytes]:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">i", header)
    return _recv_exact(sock, n)


def _to_bytes(v: Any) -> bytes:
    if v is None:
        return b""
    if isinstance(v, bytes):
        return v
    return str(v).encode("utf-8", "surrogateescape")


def _to_str(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else b.decode("utf-8", "surrogateescape")


class _PartitionLog:
    """Append-only offset-addressed log of RAW v2 record batches.

    The stored artifact IS the CRC'd wire batch (base offset patched in —
    outside the CRC's coverage, exactly how a real broker assigns offsets):
    fetch serves the stored bytes verbatim with ZERO re-encoding, restart
    replays the identical bytes, and the on-disk file is a sequence of those
    frames (reference: the kafka log segment format). Legacy JSONL partition
    files from older builds are converted once at load."""

    def __init__(self, path: Optional[str]):
        self.batches: List[bytes] = []       # raw frames: base(8) len(4) body
        self.base_offsets: List[int] = []    # absolute base offset per batch
        self.counts: List[int] = []          # records per batch
        self.next_offset = 0
        self.path = path
        self._file = None
        if path:
            legacy = os.path.splitext(path)[0] + ".jsonl"
            if os.path.exists(legacy) and not os.path.exists(path):
                self._convert_legacy(legacy)
            if os.path.exists(path):
                self._recover(path)
            self._file = open(path, "ab")

    def _convert_legacy(self, legacy: str) -> None:
        # temp + atomic replace: a crash mid-conversion must leave either no
        # .log (retry converts) or a complete one — a torn .log next to the
        # intact .jsonl would be truncated by recovery and the legacy records
        # silently lost forever
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(legacy) as f, open(tmp, "wb") as out:
            off = 0
            for line in f:
                d = json.loads(line)
                frame = kw.encode_record_batch(
                    off, [(None if d.get("k") is None else _to_bytes(d["k"]),
                           _to_bytes(d["v"]), int(d.get("t", 0)))])
                out.write(frame)
                off += 1
        os.replace(tmp, self.path)
        os.rename(legacy, legacy + ".converted")

    def _recover(self, path: str) -> None:
        """Load frames; a torn tail (crash mid-append) truncates to the last
        complete frame, like log recovery in the reference broker."""
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + 12 <= len(data):
            (blen,) = struct.unpack(">i", data[pos + 8:pos + 12])
            end = pos + 12 + blen
            if blen <= 0 or end > len(data):
                break  # torn tail
            self._index_frame(data[pos:end])
            pos = end
        if pos < len(data):
            with open(path, "r+b") as f:
                f.truncate(pos)

    def _index_frame(self, frame: bytes) -> None:
        (base,) = struct.unpack(">q", frame[:8])
        # count = lastOffsetDelta + 1 (frame: 12B header + leaderEpoch(4)
        # magic(1) crc(4) attrs(2) -> lastOffsetDelta at [23:27])
        (last_delta,) = struct.unpack(">i", frame[23:27])
        self.batches.append(frame)
        self.base_offsets.append(base)
        self.counts.append(last_delta + 1)
        self.next_offset = base + last_delta + 1

    def append_record_set(self, record_set: bytes) -> int:
        """Validate, offset-patch, and append every batch in a produce
        record set; returns the FIRST assigned offset.

        TWO-PHASE: every batch validates (framing + CRC) before ANY appends —
        a bad batch k must not leave batches 1..k-1 durably appended while
        the producer sees an error (its retry would duplicate them), and a
        truncated tail is an error, never a silent partial accept."""
        data = bytes(record_set)
        frames: List[bytes] = []
        pos = 0
        while pos < len(data):
            if pos + 12 > len(data):
                raise ValueError("truncated record-set frame header")
            (blen,) = struct.unpack(">i", data[pos + 8:pos + 12])
            end = pos + 12 + blen
            if blen <= 0 or end > len(data):
                raise ValueError("truncated record batch in produce set")
            frame = data[pos:end]
            # broker-side CRC validation (crc at [17:21], covering [21:])
            (crc,) = struct.unpack(">I", frame[17:21])
            if kw.crc32c(frame[21:]) != crc:
                raise ValueError("produce record batch CRC mismatch")
            frames.append(frame)
            pos = end
        if not frames:
            raise ValueError("empty produce record set")
        first = self.next_offset
        for frame in frames:
            # assign offsets by PATCHING base offset — outside CRC coverage
            frame = struct.pack(">q", self.next_offset) + frame[8:]
            self._index_frame(frame)
            if self._file:
                self._file.write(frame)
        if self._file:
            self._file.flush()
        return first

    def read_from(self, offset: int, max_bytes: int) -> bytes:
        """Stored frames covering `offset`, concatenated verbatim (the client
        skips records below its requested offset, like a stock consumer)."""
        import bisect
        i = bisect.bisect_right(self.base_offsets, offset) - 1
        if i >= 0 and self.base_offsets[i] + self.counts[i] <= offset:
            i += 1
        i = max(i, 0)
        out = []
        size = 0
        while i < len(self.batches) and size < max(max_bytes, 1):
            out.append(self.batches[i])
            size += len(self.batches[i])
            i += 1
        return b"".join(out)

    def iter_records(self):
        """(offset, ts, key, value) across all batches — the lazy per-record
        view (timestamp lookups only; the hot paths never materialize it)."""
        for frame in self.batches:
            for rec in kw.decode_record_batches(frame):
                yield rec

    def close(self):
        if self._file:
            self._file.close()


class LogBrokerServer:
    """The broker process: accept loop + per-connection request threads."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 log_dir: Optional[str] = None):
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self._topics: Dict[str, List[_PartitionLog]] = {}
        self._lock = threading.RLock()
        self._data_arrived = threading.Condition(self._lock)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        if log_dir:
            self._load_existing_topics()
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="kafkalite-accept", daemon=True)
        self._acceptor.start()

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def _load_existing_topics(self) -> None:
        # runs from __init__ before the acceptor starts, but under the (re-
        # entrant) lock anyway so the topic map only ever mutates guarded
        with self._lock:
            for topic in sorted(os.listdir(self.log_dir)):
                tdir = os.path.join(self.log_dir, topic)
                if not os.path.isdir(tdir):
                    continue
                parts = sorted({int(p.split(".")[0]) for p in os.listdir(tdir)
                                if p.split(".")[0].isdigit()})
                self._topics[topic] = [
                    _PartitionLog(os.path.join(tdir, f"{p}.log"))
                    for p in parts]

    def create_topic(self, topic: str, num_partitions: int) -> None:
        with self._lock:
            if topic in self._topics:
                return
            paths = [None] * num_partitions
            if self.log_dir:
                tdir = os.path.join(self.log_dir, topic)
                os.makedirs(tdir, exist_ok=True)
                paths = [os.path.join(tdir, f"{p}.log")
                         for p in range(num_partitions)]
            self._topics[topic] = [_PartitionLog(p) for p in paths]

    # -- request handling ----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # graftcheck: ignore[thread-no-join] -- per-connection daemon;
            # stop() closes every live socket via _conns, unblocking the recv
            th = threading.Thread(target=self._serve_conn, args=(conn,),
                                  daemon=True)
            th.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # LIVE connections only: entries drop on handler exit, or a
        # long-lived broker would grow one dead socket per short-lived client
        with self._conns_lock:
            self._conns.add(conn)
        try:
            self._serve_conn_loop(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _serve_conn_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    payload = _recv_payload(conn)
                except OSError:
                    return
                if payload is None:
                    return
                try:
                    api, version, cid, _client, r = kw.decode_request_header(payload)
                    body = self._handle(api, version, r)
                except Exception:
                    return  # malformed frame: drop the connection (Kafka does)
                try:
                    conn.sendall(kw.encode_response(cid, body))
                except OSError:
                    return

    def _handle(self, api: int, version: int, r: kw.Reader) -> bytes:
        lo_hi = kw.SUPPORTED.get(api)
        if lo_hi is None or not lo_hi[0] <= version <= lo_hi[1]:
            if api == kw.API_API_VERSIONS:
                # spec: answer v0 with UNSUPPORTED_VERSION so the client can
                # downgrade its handshake
                return kw.i16(kw.ERR_UNSUPPORTED_VERSION) + kw.array([])
            raise ValueError(f"unsupported api {api} v{version}")
        if api == kw.API_API_VERSIONS:
            return kw.encode_api_versions_response()
        if api == kw.API_CREATE_TOPICS:
            results = []
            for name, n in kw.decode_create_topics_request(r):
                self.create_topic(name, n)
                results.append((name, kw.ERR_NONE))
            return kw.encode_create_topics_response(results)
        if api == kw.API_METADATA:
            wanted = kw.decode_metadata_request(r)
            with self._lock:
                topics = {t: len(ls) for t, ls in self._topics.items()
                          if wanted is None or not wanted or t in wanted}
            return kw.encode_metadata_response(version, self.host, self.port,
                                               topics)
        if api == kw.API_PRODUCE:
            results = []
            for topic, partition, record_set in kw.decode_produce_request(r):
                with self._lock:
                    logs = self._topics.get(topic)
                    if logs is None or not 0 <= partition < len(logs):
                        results.append((topic, partition,
                                        kw.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1))
                        continue
                    try:
                        # offsets assigned by patching each batch's base (the
                        # CRC does not cover it — spec); the stored artifact
                        # is the producer's CRC'd bytes, verbatim
                        base = logs[partition].append_record_set(record_set)
                    except ValueError:
                        results.append((topic, partition,
                                        kw.ERR_CORRUPT_MESSAGE, -1))
                        continue
                    self._data_arrived.notify_all()
                results.append((topic, partition, kw.ERR_NONE, base))
            return kw.encode_produce_response(results)
        if api == kw.API_LIST_OFFSETS:
            results = []
            with self._lock:
                for topic, partition, ts in kw.decode_list_offsets_request(r):
                    logs = self._topics.get(topic)
                    if logs is None or not 0 <= partition < len(logs):
                        results.append((topic, partition,
                                        kw.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, -1))
                        continue
                    log = logs[partition]
                    if ts == kw.EARLIEST_TS:
                        off = 0
                    elif ts == kw.LATEST_TS:
                        off = log.next_offset
                    else:
                        # v1 semantics: first offset whose timestamp >= ts
                        # (offsetsForTimes); -1 when no such record exists —
                        # lazy per-record decode, rare admin-path op
                        off = next((o for o, t, _k, _v in log.iter_records()
                                    if t >= ts), -1)
                    results.append((topic, partition, kw.ERR_NONE, -1, off))
            return kw.encode_list_offsets_response(results)
        if api == kw.API_FETCH:
            max_wait, _max_bytes, parts = kw.decode_fetch_request(r)
            results = []
            for topic, partition, offset, part_max_bytes in parts:
                with self._lock:
                    logs = self._topics.get(topic)
                    if logs is None or not 0 <= partition < len(logs):
                        results.append((topic, partition,
                                        kw.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, b""))
                        continue
                    log = logs[partition]
                    if offset >= log.next_offset and max_wait > 0:
                        # long-poll like Kafka's fetch.max.wait.ms
                        self._data_arrived.wait(max_wait / 1000.0)
                    # serve the STORED frames verbatim — zero re-encode, zero
                    # CRC recompute (the log bytes ARE the wire bytes, like a
                    # real broker's zero-copy sendfile path)
                    record_set = log.read_from(offset, part_max_bytes)
                    hw = log.next_offset
                results.append((topic, partition, kw.ERR_NONE, hw, record_set))
            return kw.encode_fetch_response(results)
        raise ValueError(f"unhandled api {api}")

    def stop(self) -> None:
        self._stop.set()
        # WAKE the acceptor: a thread blocked in accept() pins the listening
        # socket's file description past close(), so the port would stay
        # bound (EADDRINUSE on a same-port restart) until a connection
        # happened to arrive
        try:
            socket.create_connection((self.host, self.port),
                                     timeout=1.0).close()
        except OSError:
            pass
        self._acceptor.join(timeout=2.0)
        try:
            self._sock.close()
        except OSError:
            pass
        # close accepted connections too: a handler blocked in recv keeps its
        # socket (and therefore the PORT) alive, so a same-port restart would
        # EADDRINUSE forever
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        with self._lock:
            for logs in self._topics.values():
                for log in logs:
                    log.close()


class LogBrokerClient:
    """One TCP connection speaking the Kafka binary protocol; thread-safe
    request/response. Negotiates with ApiVersions on connect, exactly like a
    stock client's bootstrap handshake."""

    def __init__(self, bootstrap: str, timeout_s: float = 30.0,
                 client_id: str = "pinot-tpu"):
        host, port = bootstrap.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout_s = timeout_s
        self._sock = socket.create_connection(self._addr, timeout=timeout_s)
        self._lock = threading.Lock()
        self._correlation = 0
        self.client_id = client_id
        self._rr: Dict[str, int] = {}          # producer round-robin per topic
        self._partitions: Dict[str, int] = {}  # cached partition counts
        self.api_versions = kw.decode_api_versions_response(
            self._request(kw.API_API_VERSIONS, 0, b""))

    def _request(self, api: int, version: int, body: bytes) -> kw.Reader:
        """One request/response, with ONE transparent reconnect on a dead
        socket (a stock Kafka client reconnects the same way — without this,
        a broker RESTART permanently stalls every consuming partition whose
        client socket died). Idempotency: fetch/metadata/list-offsets are
        read-only; a produce retried after a mid-flight failure could
        duplicate, exactly like Kafka without idempotent-producer mode."""
        with self._lock:
            for attempt in (0, 1):
                self._correlation += 1
                cid = self._correlation
                try:
                    self._sock.sendall(kw.encode_request(
                        api, version, cid, self.client_id, body))
                    payload = _recv_payload(self._sock)
                    if payload is None:
                        raise ConnectionError("broker closed the connection")
                    break
                except OSError:
                    if attempt:
                        raise
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = socket.create_connection(
                        self._addr, timeout=self._timeout_s)
        r = kw.Reader(payload)
        if r.i32() != cid:
            raise ConnectionError("correlation id mismatch")
        return r

    # -- admin / metadata ---------------------------------------------------
    def create_topic(self, topic: str, num_partitions: int) -> None:
        r = self._request(kw.API_CREATE_TOPICS, 0,
                          kw.encode_create_topics_request(topic, num_partitions))
        for name, err in kw.decode_create_topics_response(r):
            if err:
                raise RuntimeError(f"CreateTopics {name}: error {err}")
        self._partitions.pop(topic, None)

    def metadata(self, topic: Optional[str] = None) -> Dict[str, Any]:
        body = kw.encode_metadata_request(None if topic is None else [topic])
        return kw.decode_metadata_response(
            1, self._request(kw.API_METADATA, 1, body))

    def partition_count(self, topic: str) -> int:
        n = self._partitions.get(topic)
        if n is None:
            meta = self.metadata(topic)
            for t in meta["topics"]:
                if t["topic"] == topic:
                    if t["error"]:
                        raise RuntimeError(f"metadata {topic}: error {t['error']}")
                    n = len(t["partitions"])
            if n is None:
                raise RuntimeError(f"unknown topic {topic!r}")
            self._partitions[topic] = n
        return n

    def partition_for(self, topic: str, key: str) -> int:
        """The partition a keyed produce will land on (client-side hashing,
        stable across processes — Python's salted hash() would not be)."""
        return zlib.crc32(str(key).encode()) % self.partition_count(topic)

    # -- data plane ----------------------------------------------------------
    def produce(self, topic: str, value: Any, partition: Optional[int] = None,
                key: Optional[str] = None,
                timestamp_ms: Optional[int] = None) -> int:
        if partition is None:
            # client-side partitioning, like a stock producer: key hash when
            # keyed (stable across processes), round-robin otherwise
            n = self.partition_count(topic)
            if key is not None:
                partition = zlib.crc32(str(key).encode()) % n
            else:
                partition = self._rr.get(topic, 0) % n
                self._rr[topic] = partition + 1
        # None -> producer stamps wall clock (CreateTime, like a stock client);
        # an EXPLICIT value — including 0 — is preserved verbatim
        ts = timestamp_ms if timestamp_ms is not None else int(time.time() * 1000)
        record_set = kw.encode_record_batch(
            0, [(None if key is None else _to_bytes(key), _to_bytes(value), ts)])
        r = self._request(kw.API_PRODUCE, 3,
                          kw.encode_produce_request(topic, partition, record_set))
        for d in kw.decode_produce_response(r):
            if d["error"]:
                raise RuntimeError(f"Produce {topic}/{partition}: "
                                   f"error {d['error']}")
            return d["offset"]
        raise RuntimeError("empty produce response")

    def produce_many(self, topic: str, values, partition: int = 0,
                     timestamp_ms: Optional[int] = None) -> int:
        """Batch produce: ONE record batch, ONE round trip (a stock producer's
        linger/batching); returns the LAST assigned offset."""
        values = list(values)   # a generator must count AND encode the same
        ts = timestamp_ms if timestamp_ms is not None else int(time.time() * 1000)
        record_set = kw.encode_record_batch(
            0, [(None, _to_bytes(v), ts) for v in values])
        r = self._request(kw.API_PRODUCE, 3,
                          kw.encode_produce_request(topic, partition,
                                                    record_set))
        for d in kw.decode_produce_response(r):
            if d["error"]:
                raise RuntimeError(f"Produce {topic}/{partition}: "
                                   f"error {d['error']}")
            return d["offset"] + len(values) - 1
        raise RuntimeError("empty produce response")

    def fetch(self, topic: str, partition: int, offset: int,
              max_wait_ms: int = 0, max_bytes: int = 8 << 20) -> List[Dict]:
        r = self._request(kw.API_FETCH, 4,
                          kw.encode_fetch_request(topic, partition, offset,
                                                  max_wait_ms, max_bytes))
        for d in kw.decode_fetch_response(r):
            if d["error"]:
                raise RuntimeError(f"Fetch {topic}/{partition}: error {d['error']}")
            # a stored batch may start BEFORE the requested offset (the broker
            # serves whole frames) — skip below-offset records like a stock
            # consumer
            return [rec for rec in d["records"] if rec[0] >= offset]
        return []

    def fetch_spliced(self, topic: str, partition: int, offset: int,
                      max_wait_ms: int = 0, max_bytes: int = 8 << 20,
                      sep: bytes = b",", max_records: int = 1 << 62):
        """(values spliced with sep, count, next_offset) via the native
        splicer, or None when the native library is unavailable — the
        JSON-batch consume fast path (one C parse per fetch, zero
        per-record Python objects)."""
        r = self._request(kw.API_FETCH, 4,
                          kw.encode_fetch_request(topic, partition, offset,
                                                  max_wait_ms, max_bytes))
        for d in kw.decode_fetch_response(r, raw_records=True):
            if d["error"]:
                raise RuntimeError(f"Fetch {topic}/{partition}: error {d['error']}")
            spliced = kw.splice_record_batches(d["recordSet"], offset, sep,
                                               max_records=max_records)
            if spliced is None:
                return None
            data, n, last = spliced
            return data, n, (last + 1 if n else offset)
        return b"", 0, offset

    def list_offsets(self, topic: str, partition: int,
                     timestamp: int = kw.LATEST_TS) -> int:
        r = self._request(kw.API_LIST_OFFSETS, 1,
                          kw.encode_list_offsets_request(topic, partition,
                                                         timestamp))
        for d in kw.decode_list_offsets_response(r):
            if d["error"]:
                raise RuntimeError(f"ListOffsets {topic}/{partition}: "
                                   f"error {d['error']}")
            return d["offset"]
        raise RuntimeError("empty ListOffsets response")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# -- the stream SPI plugin ----------------------------------------------------

class KafkaLiteConsumer(PartitionGroupConsumer):
    """PartitionGroupConsumer over the binary client (the
    KafkaPartitionLevelConsumer analog)."""

    def __init__(self, bootstrap: str, topic: str, partition: int):
        self.client = LogBrokerClient(bootstrap)
        self.topic = topic
        self.partition = partition
        # running average record size: the Kafka fetch protocol bounds BYTES,
        # not records, so the max_messages contract translates through this
        # estimate (over-fetching then slicing would decode and discard)
        self._avg_record_bytes = 256.0

    def fetch(self, start_offset: int, max_messages: int, timeout_ms: int = 0) -> MessageBatch:
        records = self._fetch_records(start_offset, max_messages, timeout_ms)
        msgs = [StreamMessage(value=_to_str(value), offset=off,
                              key=_to_str(key), timestamp_ms=ts)
                for off, ts, key, value in records]
        next_offset = msgs[-1].offset + 1 if msgs else start_offset
        return MessageBatch(msgs, next_offset)

    def fetch_raw(self, start_offset: int, max_messages: int,
                  timeout_ms: int = 0):
        """(raw value bytes list, next_offset): the columnar consume fast
        path — no StreamMessage objects, no utf-8 str materialization, keys
        skipped. Pairs with a registered batch decoder
        (`stream.get_batch_decoder`); at realtime rates the per-message
        object churn costs more than the wire decode itself (measured ~2x
        on the 200k-row ingest bench)."""
        records = self._fetch_records(start_offset, max_messages, timeout_ms)
        if not records:
            return [], start_offset
        return [value for _off, _ts, _k, value in records], records[-1][0] + 1

    def fetch_spliced(self, start_offset: int, max_messages: int,
                      timeout_ms: int = 0, sep: bytes = b","):
        """(spliced values, count, next_offset) or None without the native
        splicer. The record-count contract is approximated through the
        byte budget like `fetch` (Kafka bounds bytes, not records)."""
        fault_point("stream.stall")
        fault_point("stream.partition.lost")
        budget = int(max_messages * self._avg_record_bytes)
        budget = min(max(budget, 64 << 10), 8 << 20)
        out = self.client.fetch_spliced(self.topic, self.partition,
                                        start_offset, max_wait_ms=timeout_ms,
                                        max_bytes=budget, sep=sep,
                                        max_records=max_messages)
        if out is None:
            return None
        data, n, next_offset = out
        if n:
            self._avg_record_bytes = 0.8 * self._avg_record_bytes \
                + 0.2 * (len(data) / n + 32)
        return data, n, next_offset

    def _fetch_records(self, start_offset: int, max_messages: int,
                       timeout_ms: int):
        # graftfault: the wire-consumer injection point — a lost partition
        # raises out of the fetch exactly like the broker closing the socket
        fault_point("stream.stall")
        fault_point("stream.partition.lost")
        budget = int(max_messages * self._avg_record_bytes)
        budget = min(max(budget, 64 << 10), 8 << 20)
        records = self.client.fetch(self.topic, self.partition, start_offset,
                                    max_wait_ms=timeout_ms, max_bytes=budget)
        if records:
            got = sum(len(v) + 32 for _off, _ts, _k, v in records) / len(records)
            self._avg_record_bytes = 0.8 * self._avg_record_bytes + 0.2 * got
        return records[:max_messages]

    def latest_offset(self) -> int:
        return self.client.list_offsets(self.topic, self.partition)

    def close(self) -> None:
        self.client.close()


class KafkaLiteFactory(StreamConsumerFactory):
    """Stream plugin factory; `properties["bootstrap"]` locates the broker."""

    def __init__(self, topic: str, properties: Optional[Dict[str, Any]] = None):
        self.topic = topic
        props = properties or {}
        self.bootstrap = props.get("bootstrap", "")
        if not self.bootstrap:
            raise ValueError("kafkalite stream requires properties['bootstrap']")

    def create_consumer(self, topic: str, partition: int) -> PartitionGroupConsumer:
        return KafkaLiteConsumer(self.bootstrap, topic or self.topic, partition)

    def metadata_provider(self) -> StreamMetadataProvider:
        factory = self

        class _Meta(StreamMetadataProvider):
            def partition_count(self, topic: str) -> int:
                client = LogBrokerClient(factory.bootstrap)
                try:
                    return client.partition_count(topic or factory.topic)
                finally:
                    client.close()

        return _Meta()


register_stream_factory("kafkalite", KafkaLiteFactory)
