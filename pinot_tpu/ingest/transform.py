"""Record transform pipeline: filter -> expression transforms -> type coercion.

Analog of the reference's ordered transformer chain
(`pinot-segment-local/.../recordtransformer/CompositeTransformer.java:33`:
complex-type flatten -> FilterTransformer -> ExpressionTransformer ->
DataTypeTransformer -> null handling -> sanitize). Transform expressions reuse the SQL
expression compiler — the same `eval_expr` that powers queries — so ingestion-time
functions and query-time functions are one registry (the reference shares its
`FunctionRegistry` between both for the same reason).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..engine.expr import eval_expr
from ..schema import Schema
from ..sql.parser import Parser


def _parse_expr(text: str):
    p = Parser(text)
    e = p.expression()
    if p.cur.kind != "EOF":
        raise ValueError(f"trailing input in expression {text!r}")
    return e


class TransformPipeline:
    """Vectorized over row batches (columns dict of lists/arrays)."""

    def __init__(self, schema: Schema,
                 filter_expr: Optional[str] = None,
                 column_transforms: Optional[Dict[str, str]] = None):
        """`filter_expr`: rows matching are DROPPED (reference FilterTransformer
        semantics: filterFunction selects records to skip).
        `column_transforms`: dest column -> SQL expression over source fields."""
        self.schema = schema
        self.filter_expr = _parse_expr(filter_expr) if filter_expr else None
        self.column_transforms = {dest: _parse_expr(src)
                                  for dest, src in (column_transforms or {}).items()}

    def apply(self, columns: Dict[str, Any]) -> Dict[str, List[Any]]:
        n = len(next(iter(columns.values()))) if columns else 0
        env = {k: _as_array(v) for k, v in columns.items()}

        # 0. pre-coerce schema columns so filters/transforms see typed values even for
        #    string inputs (CSV); non-schema fields stay raw for transforms to consume.
        for spec in self.schema.fields:
            if spec.name in env:
                coerce = spec.data_type.coerce
                env[spec.name] = _as_array(
                    [None if v is None or _is_nan(v) else coerce(v)
                     for v in env[spec.name].tolist()])

        # 1. expression transforms (may reference raw input fields)
        for dest, expr in self.column_transforms.items():
            out = eval_expr(expr, env, np)
            env[dest] = np.full(n, out, dtype=object) if np.isscalar(out) else _as_array(out)

        # 2. filter (drop matching rows)
        if self.filter_expr is not None:
            drop = np.asarray(eval_expr(self.filter_expr, env, np), dtype=bool)
            keep = ~drop
            env = {k: v[keep] for k, v in env.items()}
            n = int(keep.sum())

        # 3. type coercion + null defaulting per schema (DataTypeTransformer analog);
        #    None survives as None so the segment writer records null bitmaps.
        out_cols: Dict[str, List[Any]] = {}
        for spec in self.schema.fields:
            if spec.name not in env:
                out_cols[spec.name] = [None] * n
                continue
            vals = env[spec.name]
            coerce = spec.data_type.coerce
            out_cols[spec.name] = [None if v is None or _is_nan(v) else coerce(v)
                                   for v in vals.tolist()]
        return out_cols

    def apply_row(self, row: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Single-row variant for the realtime consume loop."""
        cols = self.apply({k: [v] for k, v in row.items()})
        if not cols or len(next(iter(cols.values()))) == 0:
            return None
        return {k: v[0] for k, v in cols.items()}


def _as_array(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    arr = np.empty(len(v), dtype=object)
    arr[:] = v
    return arr


def _is_nan(v: Any) -> bool:
    return isinstance(v, float) and v != v
