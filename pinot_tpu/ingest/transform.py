"""Record transform pipeline: filter -> expression transforms -> type coercion.

Analog of the reference's ordered transformer chain
(`pinot-segment-local/.../recordtransformer/CompositeTransformer.java:33`:
complex-type flatten -> FilterTransformer -> ExpressionTransformer ->
DataTypeTransformer -> null handling -> sanitize). Transform expressions reuse the SQL
expression compiler — the same `eval_expr` that powers queries — so ingestion-time
functions and query-time functions are one registry (the reference shares its
`FunctionRegistry` between both for the same reason).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..engine.expr import eval_expr
from ..schema import Schema
from ..sql.parser import Parser

#: graftcheck row-loop-in-ingest contract: these functions are the LIST-based
#: fallback lane (exact per-cell null/coercion semantics, string interning) —
#: the hot path is ingest/vectorized.py's array-native decode, which falls
#: back here only for mixed/escaped/overflow cells the arrays can't express.
__graft_slow_paths__ = ("columns_from_spliced_json",)


def _parse_expr(text: str):
    p = Parser(text)
    e = p.expression()
    if p.cur.kind != "EOF":
        raise ValueError(f"trailing input in expression {text!r}")
    return e


class TransformPipeline:
    """Vectorized over row batches (columns dict of lists/arrays)."""

    def __init__(self, schema: Schema,
                 filter_expr: Optional[str] = None,
                 column_transforms: Optional[Dict[str, str]] = None):
        """`filter_expr`: rows matching are DROPPED (reference FilterTransformer
        semantics: filterFunction selects records to skip).
        `column_transforms`: dest column -> SQL expression over source fields."""
        self.schema = schema
        self.filter_expr = _parse_expr(filter_expr) if filter_expr else None
        self.column_transforms = {dest: _parse_expr(src)
                                  for dest, src in (column_transforms or {}).items()}

    def apply(self, columns: Dict[str, Any]) -> Dict[str, List[Any]]:
        n = len(next(iter(columns.values()))) if columns else 0
        env = {k: _as_array(v) for k, v in columns.items()}

        # 0. pre-coerce schema columns so filters/transforms see typed values even for
        #    string inputs (CSV); non-schema fields stay raw for transforms to consume.
        coerced0 = set()
        for spec in self.schema.fields:
            if spec.name in env:
                env[spec.name] = _as_array(
                    _coerce_list(spec, env[spec.name].tolist()))
                coerced0.add(spec.name)

        # 1. expression transforms (may reference raw input fields)
        for dest, expr in self.column_transforms.items():
            out = eval_expr(expr, env, np)
            env[dest] = np.full(n, out, dtype=object) if np.isscalar(out) else _as_array(out)

        # 2. filter (drop matching rows)
        if self.filter_expr is not None:
            drop = np.asarray(eval_expr(self.filter_expr, env, np), dtype=bool)
            keep = ~drop
            env = {k: v[keep] for k, v in env.items()}
            n = int(keep.sum())

        # 3. type coercion + null defaulting per schema (DataTypeTransformer analog);
        #    None survives as None so the segment writer records null bitmaps.
        #    Columns step 0 already coerced and no transform overwrote pass
        #    through — coercing every value TWICE dominated the consume rate.
        out_cols: Dict[str, List[Any]] = {}
        for spec in self.schema.fields:
            if spec.name not in env:
                out_cols[spec.name] = [None] * n
                continue
            vals = env[spec.name].tolist()
            if spec.name in coerced0 and spec.name not in self.column_transforms:
                out_cols[spec.name] = vals
            else:
                out_cols[spec.name] = _coerce_list(spec, vals)
        return out_cols

    def apply_row(self, row: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Single-row variant for the realtime consume loop."""
        cols = self.apply({k: [v] for k, v in row.items()})
        if not cols or len(next(iter(cols.values()))) == 0:
            return None
        return {k: v[0] for k, v in cols.items()}


def _coerce_list(spec, vals: list) -> list:
    """Typed values with None preserved. Numeric fast path: one numpy cast
    when every value is already clean (no None/NaN/strings) — the per-value
    python coerce loop is the realtime consume path's hot spot."""
    from ..schema import DataType
    dt = spec.data_type
    coerce = dt.coerce
    # BOOLEAN coerces by TRUTHINESS (2 -> 1, 0.5 -> 1, 'yes' -> 1); a plain
    # numeric cast would store raw/truncated values — excluded from the fast
    # path so both paths stay value-identical
    if dt.is_numeric and dt is not DataType.BOOLEAN and vals:
        # cast through int64/float64 regardless of the column's storage
        # width: the slow path's coerce() yields full-precision python
        # values, and the two paths must produce IDENTICAL values or the
        # same input would round differently batch-to-batch (narrowing to
        # the storage dtype happens once, at segment write)
        wide = np.float64 if np.dtype(dt.numpy_dtype).kind == "f" else np.int64
        try:
            arr = np.asarray(vals, dtype=wide)
        except (TypeError, ValueError, OverflowError):
            arr = None
        if arr is not None and not (arr.dtype.kind == "f"
                                    and np.isnan(arr).any()):
            # numpy may have mapped None -> nan silently for float dtypes;
            # the nan check above routes to the slow path (None must
            # survive as None for the null bitmap)
            return arr.tolist()
    return [None if v is None or _is_nan(v) else coerce(v) for v in vals]


def rows_to_all_columns(rows: List[Dict[str, Any]]) -> Dict[str, List[Any]]:
    """Row dicts -> column lists over the UNION of keys (non-schema fields
    survive for transforms to consume) — the batch-decode shape the realtime
    consume path and the ingest bench share."""
    keys: set = set()
    for r in rows:
        keys.update(r)
    return {k: [r.get(k) for r in rows] for k in keys}


_NUMERIC_TYPES = ("INT", "LONG", "FLOAT", "DOUBLE")


def columns_from_spliced_json(data: bytes, n: int, schema) -> \
        Optional[Dict[str, List[Any]]]:
    """NATIVE columnar decode of n spliced flat-JSON records straight to
    index-ready, schema-coerced column lists — the decode->transform->
    dict-assign fast path (VERDICT r4 #4): one C walk replaces per-row
    json.loads + rows_to_all_columns + per-value coercion.

    Returns None when the shape can't take the fast path (no native lib,
    multi-value/typed-beyond-{INT,LONG,FLOAT,DOUBLE,STRING} schema fields,
    malformed outer structure) — callers run the generic pipeline. Output
    semantics match `TransformPipeline.apply` for a pipeline with no
    filter/transforms: schema columns only, values coerced per DataType,
    None for null/missing (index_batch records null bitmaps from them).
    Rows the C decoder flags (nested values under schema keys,
    out-of-int64 numbers) are re-parsed individually with json.loads."""
    from ..native import json_columns
    fields = list(schema.fields)
    if any(not f.single_value or f.data_type.value not in
           _NUMERIC_TYPES + ("STRING",) for f in fields):
        return None
    names = [f.name for f in fields]
    out = json_columns(data, n, names)
    if out is None:
        return None
    nums, lints, types, str_off, str_len, rec_ranges, bad = out
    cols: Dict[str, List[Any]] = {}
    for c, f in enumerate(fields):
        t = types[c]
        dt = f.data_type.value
        if dt in ("INT", "LONG"):
            if (t == 8).all():
                cols[f.name] = lints[c].tolist()
                continue
            f_mask = t == 1
            if ((t == 8) | f_mask).all():
                fvals = nums[c][f_mask]
                # vectorized float->int only when every double is safely in
                # int64 range: numpy's cast of 1e300 silently yields
                # INT64_MIN where the generic path's int() is exact — those
                # rows take the per-cell loop below instead
                if np.isfinite(fvals).all() and                         (np.abs(fvals) < float(1 << 62)).all():
                    ints = lints[c].copy()
                    ints[f_mask] = fvals.astype(np.int64)
                    cols[f.name] = ints.tolist()
                    continue
        elif dt in ("FLOAT", "DOUBLE"):
            i_mask = t == 8
            if (i_mask | (t == 1)).all():
                v = nums[c].copy()
                v[i_mask] = lints[c][i_mask].astype(np.float64)
                cols[f.name] = v.tolist()
                continue
        elif dt == "STRING" and ((t == 2).all()):
            so, sl = str_off[c], str_len[c]
            # intern repeated values (OLAP dimension columns are low-card:
            # one decode per DISTINCT value, dict hits for the rest)
            cache: Dict[bytes, str] = {}
            out_s: List[Any] = []
            for o, l in zip(so.tolist(), sl.tolist()):
                b = data[o:o + l]
                s = cache.get(b)
                if s is None:
                    if len(cache) > 65536:
                        cache.clear()
                    s = cache[b] = b.decode("utf-8")
                out_s.append(s)
            cols[f.name] = out_s
            continue
        # mixed/missing/escaped cells: per-cell assembly with exact
        # null/coercion semantics (still no re-parse of the record)
        import json as _json
        coerce = f.data_type.coerce
        vals: List[Any] = []
        so, sl = str_off[c], str_len[c]
        for r in range(n):
            tv = t[r]
            if tv == 0 or tv == 5:
                vals.append(None)
            elif tv == 8:
                vals.append(coerce(int(lints[c, r])))
            elif tv == 1:
                vals.append(coerce(float(nums[c, r])))
            elif tv == 2:
                vals.append(coerce(
                    data[so[r]:so[r] + sl[r]].decode("utf-8")))
            elif tv == 6:
                raw = data[so[r] - 1:so[r] + sl[r] + 1]
                vals.append(coerce(_json.loads(raw)))
            elif tv == 3:
                vals.append(coerce(True))
            else:
                vals.append(coerce(False))
        cols[f.name] = vals
    if bad.any():
        import json as _json
        for r in np.nonzero(bad)[0].tolist():
            off, ln = rec_ranges[r]
            row = _json.loads(data[off:off + ln])
            for f in fields:
                if f.name in row:
                    v = row[f.name]
                    cols[f.name][r] = None if v is None \
                        else f.data_type.coerce(v)
                else:
                    cols[f.name][r] = None
    return cols


def _as_array(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    arr = np.empty(len(v), dtype=object)
    arr[:] = v
    return arr


def _is_nan(v: Any) -> bool:
    return isinstance(v, float) and v != v
