"""Ingestion: record readers, transform pipeline, batch jobs, realtime streams.

Mirrors the reference's ingestion surface (SURVEY.md §2.1 stream SPI + record I/O SPI,
§3.2 realtime consumption, §3.3 batch build-and-push) with a TPU-first twist: the batch
path builds aligned-dictionary segment sets so the mesh combine fast path applies.
"""
