"""Dataframe connector: bulk-build segments from pandas DataFrames.

Analog of the reference's Spark/Flink connectors
(`pinot-connectors/pinot-spark-3-connector`): distributed frameworks hand the
ingestion layer partitioned tabular batches; here the tabular lingua franca of
the Python ecosystem (pandas — already the parquet reader's substrate) maps a
DataFrame (or an iterator of partition DataFrames, which is what
`spark_df.toPandas()` per partition produces) onto built-and-pushed segments.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from ..schema import DataType, FieldRole, FieldSpec, Schema
from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig


def schema_from_dataframe(df, name: str,
                          metrics: Optional[List[str]] = None,
                          time_column: Optional[str] = None) -> Schema:
    """Infer a Schema from dtypes (reference: connector schema inference).
    Numeric columns listed in `metrics` become METRIC fields; the rest are
    dimensions; `time_column` becomes DATE_TIME."""
    metrics = set(metrics or [])
    fields: List[FieldSpec] = []
    for col in df.columns:
        kind = df[col].dtype.kind
        if kind == "u":
            # unsigned widths promote one step (uint32's top half would wrap a
            # signed INT); uint64 has no signed container -> DOUBLE, lossy past
            # 2^53 but never silently negative
            size = df[col].dtype.itemsize
            dt = (DataType.DOUBLE if size >= 8 else
                  DataType.LONG if size >= 4 else DataType.INT)
        elif kind == "i":
            dt = DataType.LONG if df[col].dtype.itemsize > 4 else DataType.INT
        elif kind == "f":
            dt = DataType.DOUBLE
        elif kind == "b":
            dt = DataType.BOOLEAN
        else:
            dt = DataType.STRING
        role = (FieldRole.DATE_TIME if col == time_column else
                FieldRole.METRIC if col in metrics else FieldRole.DIMENSION)
        fields.append(FieldSpec(col, dt, role))
    return Schema(name, fields)


def _columns_from_frame(df, schema: Schema) -> Dict[str, Any]:
    cols: Dict[str, Any] = {}
    for spec in schema.fields:
        if spec.name not in df.columns:
            continue
        s = df[spec.name]
        # s.isna() covers None, NaN AND pd.NA (arrow-backed nullable dtypes from
        # spark_df.toPandas()) — hand-rolled checks miss pd.NA, whose truthiness
        # raises inside the writer
        na = s.isna()
        if spec.data_type.is_numeric and not na.any():
            cols[spec.name] = np.asarray(s.to_numpy())
        else:
            cols[spec.name] = [None if isna else v
                               for isna, v in zip(na.tolist(), s.tolist())]
    return cols


def segments_from_dataframe(df_or_parts, schema: Schema, out_dir: str,
                            base_name: str,
                            config: Optional[SegmentGeneratorConfig] = None,
                            rows_per_segment: int = 2_000_000) -> List[str]:
    """DataFrame (or iterable of partition frames) -> built segment dirs.

    One segment per partition frame; a single big frame splits at
    `rows_per_segment` (the connector's per-task segment sizing)."""
    builder = SegmentBuilder(schema, config or SegmentGeneratorConfig())
    parts: Iterable = ([df_or_parts] if hasattr(df_or_parts, "columns")
                       else df_or_parts)
    out: List[str] = []
    seq = 0
    for frame in parts:
        for lo in range(0, len(frame), rows_per_segment):
            chunk = frame.iloc[lo:lo + rows_per_segment]
            if len(chunk) == 0:  # empty partitions produce NO segment, ever
                continue
            out.append(builder.build(_columns_from_frame(chunk, schema),
                                     out_dir, f"{base_name}_{seq}"))
            seq += 1
    return out


def push_dataframe(df_or_parts, schema: Schema, controller, table: str,
                   work_dir: str, base_name: Optional[str] = None,
                   config: Optional[SegmentGeneratorConfig] = None) -> List[str]:
    """Build + upload in one call (`controller` is a Controller object or a
    ControllerClient) — the connector's write path."""
    names = []
    for seg_dir in segments_from_dataframe(df_or_parts, schema, work_dir,
                                           base_name or schema.name,
                                           config=config):
        controller.upload_segment(table, seg_dir)
        names.append(seg_dir)
    return names
