"""Batch ingestion job: files -> transformed segments -> controller push.

Analog of the reference's batch ingestion framework
(`pinot-spi/.../ingestion/batch/IngestionJobLauncher.java:43,103` +
`pinot-plugins/pinot-batch-ingestion/pinot-batch-ingestion-standalone/...
SegmentGenerationJobRunner.java:61`): a job spec names inputs, the table, and
partitioning; the runner streams records, applies the transform pipeline, cuts segments
at `segment_rows`, builds them (aligned dictionaries per job so the mesh fast path
applies across the job's output), and pushes via the controller. The hadoop/spark
runners of the reference parallelize the same per-file unit; here `map_workers` uses a
thread pool per input file.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..schema import Schema
from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig, build_aligned_segments
from ..table import TableConfig
from .readers import reader_for, rows_to_columns
from .transform import TransformPipeline


@dataclass
class BatchIngestionJobSpec:
    """Reference: SegmentGenerationJobSpec (YAML-mapped in the reference; a dataclass
    here — the CLI loads either JSON or YAML-subset)."""

    input_paths: List[str] = field(default_factory=list)
    input_format: Optional[str] = None                 # inferred from extension if None
    table: str = ""                                    # table name with type
    segment_name_prefix: str = ""
    segment_rows: int = 1_000_000
    filter_expr: Optional[str] = None
    column_transforms: Dict[str, str] = field(default_factory=dict)
    aligned_dictionaries: bool = True                  # TPU mesh fast path across output
    map_workers: int = 1


def ingest_file_to_segments(schema: Schema, table_cfg: TableConfig, path: str,
                            *, input_format: Optional[str] = None,
                            filter_expr: Optional[str] = None,
                            column_transforms: Optional[Dict[str, str]] = None,
                            segment_rows: int = 1_000_000,
                            prefix: str, build_dir: str) -> List[str]:
    """THE per-file ingestion unit (read -> transform -> chunk -> build),
    shared by the standalone runner below and the distributed
    SegmentGenerationAndPushTask minion executor — one implementation, so
    standalone and fleet ingestion of the same spec build identical
    segments. Returns the built segment dirs (caller pushes them)."""
    pipeline = TransformPipeline(schema, filter_expr, column_transforms or {})
    reader = reader_for(path, input_format)
    try:
        rows = list(reader.rows())
    finally:
        reader.close()
    columns = pipeline.apply(rows_to_columns(rows, schema))
    n = len(next(iter(columns.values()))) if columns else 0
    if n == 0:
        return []
    builder = SegmentBuilder(
        schema, SegmentGeneratorConfig.from_indexing(table_cfg.indexing))
    seg_dirs = []
    for i in range(max(1, -(-n // segment_rows))):
        lo, hi = i * segment_rows, min(n, (i + 1) * segment_rows)
        part = {c: v[lo:hi] for c, v in columns.items()}
        seg_dirs.append(builder.build(part, build_dir, f"{prefix}_{i}"))
    return seg_dirs


def run_batch_ingestion(spec: BatchIngestionJobSpec, controller, *,
                        work_dir: str) -> List[str]:
    """Execute the job against a Controller (in-proc or HTTP proxy). Returns segment
    names pushed (reference: IngestionJobLauncher.runIngestionJob ->
    SegmentGenerationJobRunner + SegmentTarPushJobRunner)."""
    table_cfg: TableConfig = controller.catalog.table_configs[spec.table]
    schema: Schema = controller.catalog.schemas[table_cfg.name]
    pipeline = TransformPipeline(schema, spec.filter_expr, spec.column_transforms)
    prefix = spec.segment_name_prefix or table_cfg.name
    build_dir = os.path.join(work_dir, "batch_build")
    os.makedirs(build_dir, exist_ok=True)

    idx = table_cfg.indexing
    gen_cfg = SegmentGeneratorConfig.from_indexing(idx)

    def read_one(path: str) -> List[Dict[str, Any]]:
        reader = reader_for(path, spec.input_format)
        try:
            return list(reader.rows())
        finally:
            reader.close()

    if spec.map_workers > 1 and len(spec.input_paths) > 1:
        with ThreadPoolExecutor(max_workers=spec.map_workers) as pool:
            per_file = list(pool.map(read_one, spec.input_paths))
    else:
        per_file = [read_one(p) for p in spec.input_paths]

    rows: List[Dict[str, Any]] = [r for rs in per_file for r in rs]
    columns = pipeline.apply(rows_to_columns(rows, schema))
    n = len(next(iter(columns.values()))) if columns else 0

    pushed: List[str] = []
    if n == 0:
        return pushed
    num_segments = max(1, -(-n // spec.segment_rows))
    if spec.aligned_dictionaries and num_segments > 1:
        seg_dirs = build_aligned_segments(schema, columns, build_dir,
                                          prefix, num_segments, gen_cfg)
    else:
        builder = SegmentBuilder(schema, gen_cfg)
        seg_dirs = []
        for i in range(num_segments):
            lo, hi = i * spec.segment_rows, min(n, (i + 1) * spec.segment_rows)
            part = {c: v[lo:hi] for c, v in columns.items()}
            seg_dirs.append(builder.build(part, build_dir, f"{prefix}_{i}"))

    for seg_dir in seg_dirs:
        meta = controller.upload_segment(spec.table, seg_dir)
        pushed.append(meta.name)
    return pushed
