"""Batch ingestion job: files -> transformed segments -> controller push.

Analog of the reference's batch ingestion framework
(`pinot-spi/.../ingestion/batch/IngestionJobLauncher.java:43,103` +
`pinot-plugins/pinot-batch-ingestion/pinot-batch-ingestion-standalone/...
SegmentGenerationJobRunner.java:61`): a job spec names inputs, the table, and
partitioning; the runner STREAMS records (O(segment)+O(dictionary) peak memory,
never O(job)), applies the transform pipeline, cuts segments at `segment_rows`,
builds them (aligned dictionaries per job so the mesh fast path applies across
the job's output), and pushes each via the controller as it is cut. The
hadoop/spark runners of the reference parallelize a per-file unit; the
distributed analog here is `POST /ingestJobs` fanning per-file
SegmentGenerationAndPushTasks over the minion fleet (services.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..schema import Schema
from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig
from ..table import TableConfig
from .readers import reader_for, rows_to_columns
from .transform import TransformPipeline


@dataclass
class BatchIngestionJobSpec:
    """Reference: SegmentGenerationJobSpec (YAML-mapped in the reference; a dataclass
    here — the CLI loads either JSON or YAML-subset)."""

    input_paths: List[str] = field(default_factory=list)
    input_format: Optional[str] = None                 # inferred from extension if None
    table: str = ""                                    # table name with type
    segment_name_prefix: str = ""
    segment_rows: int = 1_000_000
    filter_expr: Optional[str] = None
    column_transforms: Dict[str, str] = field(default_factory=dict)
    aligned_dictionaries: bool = True                  # TPU mesh fast path across output
    map_workers: int = 1   # distributed fan-out width hint (POST /ingestJobs path)


def ingest_file_to_segments(schema: Schema, table_cfg: TableConfig, path: str,
                            *, input_format: Optional[str] = None,
                            filter_expr: Optional[str] = None,
                            column_transforms: Optional[Dict[str, str]] = None,
                            segment_rows: int = 1_000_000,
                            prefix: str, build_dir: str) -> List[str]:
    """THE per-file ingestion unit (read -> transform -> chunk -> build),
    shared by the standalone runner below and the distributed
    SegmentGenerationAndPushTask minion executor — one implementation, so
    standalone and fleet ingestion of the same spec build identical
    segments. Returns the built segment dirs (caller pushes them)."""
    pipeline = TransformPipeline(schema, filter_expr, column_transforms or {})
    reader = reader_for(path, input_format)
    try:
        rows = list(reader.rows())
    finally:
        reader.close()
    columns = pipeline.apply(rows_to_columns(rows, schema))
    n = len(next(iter(columns.values()))) if columns else 0
    if n == 0:
        return []
    builder = SegmentBuilder(
        schema, SegmentGeneratorConfig.from_indexing(table_cfg.indexing))
    seg_dirs = []
    for i in range(max(1, -(-n // segment_rows))):
        lo, hi = i * segment_rows, min(n, (i + 1) * segment_rows)
        part = {c: v[lo:hi] for c, v in columns.items()}
        seg_dirs.append(builder.build(part, build_dir, f"{prefix}_{i}"))
    return seg_dirs


# rows per streamed read chunk (bounds the stats pass's working set)
CHUNK_ROWS = 65536


def _iter_transformed_chunks(spec: BatchIngestionJobSpec, schema: Schema,
                             pipeline: TransformPipeline,
                             chunk_rows: int):
    """Stream `input_paths` in order as transformed column-dict chunks of at
    most `chunk_rows` rows — the O(chunk) unit both passes of the streaming
    runner consume (reference: the record-at-a-time loop of
    `SegmentIndexCreationDriverImpl.build():204`, chunk-vectorized here)."""
    import itertools
    for path in spec.input_paths:
        reader = reader_for(path, spec.input_format)
        try:
            it = iter(reader.rows())
            while True:
                rows = list(itertools.islice(it, chunk_rows))
                if not rows:
                    break
                cols = pipeline.apply(rows_to_columns(rows, schema))
                if cols and len(next(iter(cols.values()))):
                    yield cols
        finally:
            reader.close()


def _collect_fixed_dictionaries(spec: BatchIngestionJobSpec, schema: Schema,
                                pipeline: TransformPipeline,
                                gen_cfg: SegmentGeneratorConfig,
                                chunk_rows: int):
    """Stats pass (reference: `SegmentPreIndexStatsCollectorImpl` feeding
    `SegmentDictionaryCreator`): one streaming read collecting per-column
    distinct values — memory is O(cardinality + chunk), never O(rows) — so
    the write pass can pin every segment to shared dictionaries (the TPU
    mesh fast path needs aligned dict-id spaces across the job's output)."""
    from ..segment.dictionary import build_dictionary
    uniques: Dict[str, Any] = {}
    specs = {f.name: f for f in schema.fields}
    no_dict = set(gen_cfg.no_dictionary_columns)
    total = 0
    for cols in _iter_transformed_chunks(spec, schema, pipeline, chunk_rows):
        total += len(next(iter(cols.values())))
        for name, fs in specs.items():
            if name in no_dict or name not in cols:
                continue
            vals = cols[name]
            if fs.data_type.is_numeric:
                arr = np.asarray(
                    [fs.null_value if v is None else v for v in vals],
                    dtype=fs.data_type.numpy_dtype)
                prev = uniques.get(name)
                u = np.unique(arr)
                uniques[name] = u if prev is None else np.union1d(prev, u)
            else:
                uniques.setdefault(name, set()).update(
                    fs.null_value if v is None else v for v in vals)
    fixed: Dict[str, Any] = {}
    extra_no_dict: List[str] = []
    for name, u in uniques.items():
        fs = specs[name]
        if fs.data_type.is_numeric:
            if len(u) > gen_cfg.raw_cardinality_fraction * max(total, 1):
                # force raw in EVERY segment, like build_aligned_segments —
                # per-segment heuristics could diverge across the output set
                extra_no_dict.append(name)
                continue
            fixed[name], _ = build_dictionary(u, fs.data_type)
        else:
            fixed[name], _ = build_dictionary(sorted(u), fs.data_type)
    return fixed, extra_no_dict, total


def run_batch_ingestion(spec: BatchIngestionJobSpec, controller, *,
                        work_dir: str) -> List[str]:
    """Execute the job against a Controller (in-proc or HTTP proxy). Returns
    segment names pushed (reference: IngestionJobLauncher.runIngestionJob ->
    SegmentGenerationJobRunner + SegmentTarPushJobRunner).

    STREAMING: segments are cut incrementally while reading — peak memory is
    O(segment_rows + dictionary), not O(total rows), so a job 10x larger than
    one segment never needs 10x the RAM (reference: the two-pass
    stats-then-write driver `SegmentIndexCreationDriverImpl.java:99,204`).
    With `aligned_dictionaries` a first stats pass streams the inputs to
    collect shared dictionaries; the write pass then streams again, buffering
    only one segment's rows at a time and pushing each segment as it is cut."""
    table_cfg: TableConfig = controller.catalog.table_configs[spec.table]
    schema: Schema = controller.catalog.schemas[table_cfg.name]
    pipeline = TransformPipeline(schema, spec.filter_expr, spec.column_transforms)
    prefix = spec.segment_name_prefix or table_cfg.name
    build_dir = os.path.join(work_dir, "batch_build")
    os.makedirs(build_dir, exist_ok=True)

    import dataclasses
    gen_cfg = dataclasses.replace(
        SegmentGeneratorConfig.from_indexing(table_cfg.indexing))
    gen_cfg.no_dictionary_columns = list(gen_cfg.no_dictionary_columns)
    chunk_rows = min(spec.segment_rows, CHUNK_ROWS)

    fixed = None
    if spec.aligned_dictionaries:
        fixed, extra_no_dict, total = _collect_fixed_dictionaries(
            spec, schema, pipeline, gen_cfg, chunk_rows)
        if total == 0:
            return []
        gen_cfg.no_dictionary_columns.extend(extra_no_dict)

    builder = SegmentBuilder(schema, gen_cfg)
    pushed: List[str] = []
    buf: Dict[str, List[Any]] = {}
    buffered = 0
    seq = 0

    def flush() -> None:
        nonlocal buffered, seq, buf
        if buffered == 0:
            return
        seg_dir = builder.build(buf, build_dir, f"{prefix}_{seq}",
                                fixed_dictionaries=fixed)
        meta = controller.upload_segment(spec.table, seg_dir)
        pushed.append(meta.name)
        # free the built segment's rows AND its on-disk build dir promptly:
        # the runner's footprint must stay O(one segment)
        import shutil
        shutil.rmtree(seg_dir, ignore_errors=True)
        buf = {c: [] for c in buf}
        buffered = 0
        seq += 1

    for cols in _iter_transformed_chunks(spec, schema, pipeline, chunk_rows):
        if not buf:
            buf = {c: [] for c in cols}
        n = len(next(iter(cols.values())))
        off = 0
        while off < n:
            take = min(spec.segment_rows - buffered, n - off)
            for c, acc in buf.items():
                v = cols.get(c)
                seg = (v[off:off + take] if v is not None
                       else [None] * take)
                acc.extend(seg.tolist() if isinstance(seg, np.ndarray) else seg)
            buffered += take
            off += take
            if buffered >= spec.segment_rows:
                flush()
    flush()
    return pushed
