"""Record readers: pluggable input formats -> row dicts.

Analog of the reference's record I/O SPI (`pinot-spi/.../data/readers/RecordReader.java`,
`GenericRow`, `RecordReaderFactory`) and the input-format plugins
(`pinot-plugins/pinot-input-format/`: csv/json/parquet/avro/...). Rows are plain dicts
(GenericRow analog); readers are iterators so batch jobs stream arbitrarily large files.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

from ..schema import Schema


class RecordReader:
    def rows(self) -> Iterator[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CsvRecordReader(RecordReader):
    def __init__(self, path: str, delimiter: str = ","):
        self.path = path
        self.delimiter = delimiter

    def rows(self) -> Iterator[Dict[str, Any]]:
        with open(self.path, newline="") as f:
            for row in csv.DictReader(f, delimiter=self.delimiter):
                yield {k: (v if v != "" else None) for k, v in row.items()}


class JsonLineRecordReader(RecordReader):
    def __init__(self, path: str):
        self.path = path

    def rows(self) -> Iterator[Dict[str, Any]]:
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)


class ParquetRecordReader(RecordReader):
    """Via pandas; requires a parquet engine in the environment (gated)."""

    def __init__(self, path: str):
        self.path = path

    def rows(self) -> Iterator[Dict[str, Any]]:
        import pandas as pd
        try:
            frame = pd.read_parquet(self.path)
        except ImportError as e:
            raise RuntimeError("no parquet engine available in this environment") from e
        for rec in frame.to_dict(orient="records"):
            yield rec


class OrcRecordReader(RecordReader):
    """ORC via pyarrow (reference: pinot-orc/.../ORCRecordReader.java);
    streams stripe batches, never the whole file."""

    def __init__(self, path: str):
        self.path = path

    def rows(self) -> Iterator[Dict[str, Any]]:
        try:
            import pyarrow.orc as orc
        except ImportError as e:
            raise RuntimeError("pyarrow ORC support unavailable") from e
        f = orc.ORCFile(self.path)
        for si in range(f.nstripes):
            for rec in f.read_stripe(si).to_pylist():
                yield rec


class DictRecordReader(RecordReader):
    """In-memory rows (tests, realtime decoding output)."""

    def __init__(self, records: Sequence[Dict[str, Any]]):
        self.records = records

    def rows(self) -> Iterator[Dict[str, Any]]:
        return iter(self.records)


def _avro_reader(path: str) -> RecordReader:
    from .avro import AvroRecordReader   # lazy: avro codec loads on demand
    return AvroRecordReader(path)


def _proto_reader(path: str) -> RecordReader:
    from .proto import ProtoRecordReader   # lazy; sidecar <path>.desc schema
    return ProtoRecordReader(path)


def _thrift_reader(path: str) -> RecordReader:
    from .thriftfmt import ThriftRecordReader   # lazy; <path>.thrift sidecar
    return ThriftRecordReader(path)


_READERS: Dict[str, Callable[[str], RecordReader]] = {
    "csv": CsvRecordReader,
    "json": JsonLineRecordReader,
    "jsonl": JsonLineRecordReader,
    "parquet": ParquetRecordReader,
    "orc": OrcRecordReader,
    "avro": _avro_reader,
    "pb": _proto_reader,
    "protobuf": _proto_reader,
    "thrift": _thrift_reader,
}


def register_reader(fmt: str, factory: Callable[[str], RecordReader]) -> None:
    """Plugin hook (reference: RecordReaderFactory registration)."""
    _READERS[fmt.lower()] = factory


def reader_for(path: str, fmt: Optional[str] = None) -> RecordReader:
    fmt = (fmt or os.path.splitext(path)[1].lstrip(".")).lower()
    if fmt not in _READERS:
        raise ValueError(f"no record reader for format {fmt!r}")
    return _READERS[fmt](path)


def rows_to_columns(rows: Sequence[Dict[str, Any]], schema: Schema) -> Dict[str, List[Any]]:
    """Pivot row dicts into column lists ordered by the schema."""
    cols: Dict[str, List[Any]] = {f.name: [] for f in schema.fields}
    for row in rows:
        for f in schema.fields:
            cols[f.name].append(row.get(f.name))
    return cols
