"""Pure-Python protobuf: wire codec + descriptor-driven decode/encode.

Analog of the reference's protobuf input format
(`pinot-plugins/pinot-input-format/pinot-protobuf/src/main/java/org/apache/
pinot/plugin/inputformat/protobuf/ProtoBufRecordReader.java` — reads
varint-length-delimited messages from a file using a compiled descriptor —
and its `ProtoBufMessageDecoder` for streams). Implemented from the public
protobuf wire specification; schemas come from a standard
`FileDescriptorSet` blob (`protoc --descriptor_set_out`), which is itself
protobuf-encoded — parsed here with the same generic wire walker against
descriptor.proto's well-known field numbers.

Supported: all scalar types (varint/zigzag/fixed/float/double/bool/enum),
string/bytes, repeated fields (packed and unpacked), nested messages
(decoded to dicts), proto2 + proto3 files. Unknown fields are skipped by
wire type, like every conforming decoder.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

# wire types
_VARINT, _I64, _LEN, _SGROUP, _EGROUP, _I32 = 0, 1, 2, 3, 4, 5

# FieldDescriptorProto.Type numbers (descriptor.proto)
T_DOUBLE, T_FLOAT, T_INT64, T_UINT64, T_INT32 = 1, 2, 3, 4, 5
T_FIXED64, T_FIXED32, T_BOOL, T_STRING, T_GROUP = 6, 7, 8, 9, 10
T_MESSAGE, T_BYTES, T_UINT32, T_ENUM = 11, 12, 13, 14
T_SFIXED32, T_SFIXED64, T_SINT32, T_SINT64 = 15, 16, 17, 18

LABEL_REPEATED = 3

_PACKABLE = {T_DOUBLE, T_FLOAT, T_INT64, T_UINT64, T_INT32, T_FIXED64,
             T_FIXED32, T_BOOL, T_UINT32, T_ENUM, T_SFIXED32, T_SFIXED64,
             T_SINT32, T_SINT64}


class ProtoError(ValueError):
    pass


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

# the uvarint WRITE side is byte-identical to the kafka record codec's —
# shared; the readers differ in interface shape ((data, pos) here vs the
# Reader/stream objects in kafka_wire/avro), and proto field varints are
# PLAIN uvarints (zigzag only for sint*), unlike kafka records
from .kafka_wire import uvarint as write_uvarint  # noqa: E402


def read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        if pos >= len(data):
            raise ProtoError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 70:
            raise ProtoError("varint too long")


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def _i64_signed(u: int) -> int:
    return u - (1 << 64) if u >= (1 << 63) else u


def _i32_signed(u: int) -> int:
    u &= 0xFFFFFFFF
    return u - (1 << 32) if u >= (1 << 31) else u


def _skip_field(data: bytes, pos: int, wt: int) -> int:
    """Advance past one NON-GROUP field's payload (the one wire-type walk the
    group skipper reuses — a second inlined copy would drift)."""
    if wt == _VARINT:
        _, pos = read_uvarint(data, pos)
    elif wt == _I64:
        pos += 8
    elif wt == _I32:
        pos += 4
    elif wt == _LEN:
        n, pos = read_uvarint(data, pos)
        pos += n
    else:
        raise ProtoError(f"bad wire type {wt}")
    if pos > len(data):
        raise ProtoError("truncated field")
    return pos


def _skip_group(data: bytes, pos: int) -> int:
    """Scan past a group body to the matching end-group tag. ITERATIVE depth
    counter, not recursion: nesting depth is attacker-controlled (600 nested
    group tags fit in ~1.2KB of input) and must never exhaust the stack."""
    depth = 1
    while depth:
        tag, pos = read_uvarint(data, pos)
        wt = tag & 7
        if wt == _SGROUP:
            depth += 1
        elif wt == _EGROUP:
            depth -= 1
        else:
            pos = _skip_field(data, pos, wt)
    return pos


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Walk one message's (field number, wire type, raw value) tags."""
    pos = 0
    while pos < len(data):
        tag, pos = read_uvarint(data, pos)
        num, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, pos = read_uvarint(data, pos)
        elif wt == _I64:
            if pos + 8 > len(data):
                raise ProtoError("truncated fixed64")
            v = data[pos:pos + 8]
            pos += 8
        elif wt == _LEN:
            n, pos = read_uvarint(data, pos)
            if pos + n > len(data):
                raise ProtoError("truncated length-delimited field")
            v = data[pos:pos + n]
            pos += n
        elif wt == _I32:
            if pos + 4 > len(data):
                raise ProtoError("truncated fixed32")
            v = data[pos:pos + 4]
            pos += 4
        elif wt == _SGROUP:
            # legacy group field: a conforming decoder SKIPS it by scanning
            # to the matching end-group tag, nesting included (groups are
            # deprecated since proto2's earliest days; declared group fields
            # decode as absent — see decode_message's default fill)
            pos = _skip_group(data, pos)
            continue
        elif wt == _EGROUP:
            raise ProtoError("unmatched end-group tag")
        else:
            raise ProtoError(f"bad wire type {wt}")
        yield num, wt, v


# ---------------------------------------------------------------------------
# descriptor model (parsed from a FileDescriptorSet with the wire walker)
# ---------------------------------------------------------------------------

class FieldSchema:
    __slots__ = ("name", "number", "type", "repeated", "type_name",
                 "in_oneof", "default")

    def __init__(self, name, number, ftype, repeated, type_name,
                 in_oneof=False, default=None):
        self.name = name
        self.number = number
        self.type = ftype
        self.repeated = repeated
        self.type_name = type_name   # fully-qualified for message/enum
        self.in_oneof = in_oneof     # incl. proto3 `optional` synthetic oneofs
        self.default = default       # proto2 declared default (already typed)


class MessageSchema:
    def __init__(self, full_name: str):
        self.full_name = full_name
        self.fields: Dict[int, FieldSchema] = {}
        self.by_name: Dict[str, FieldSchema] = {}


def _c_unescape(txt: str) -> bytes:
    """Descriptor default_value for bytes is C-escaped text — unescape it."""
    out = bytearray()
    i = 0
    while i < len(txt):
        c = txt[i]
        if c != "\\":
            out += c.encode("latin-1")
            i += 1
            continue
        i += 1
        e = txt[i]
        simple = {"n": 10, "r": 13, "t": 9, "a": 7, "b": 8, "f": 12, "v": 11,
                  "\\": 92, "'": 39, '"': 34, "?": 63}
        if e in simple:
            out.append(simple[e])
            i += 1
        elif e == "x":
            j = i + 1
            while j < len(txt) and j <= i + 2 and txt[j] in "0123456789abcdefABCDEF":
                j += 1
            out.append(int(txt[i + 1:j], 16))
            i = j
        elif e.isdigit():
            j = i
            while j < len(txt) and j < i + 3 and txt[j] in "01234567":
                j += 1
            out.append(int(txt[i:j], 8))
            i = j
        else:
            out += e.encode("latin-1")
            i += 1
    return bytes(out)


def _parse_default(ftype: int, txt: Optional[str]):
    """proto2 declared default (descriptor carries it as TEXT) -> typed value.
    Enum defaults arrive as SYMBOLIC names; the pool resolves them to numbers
    after all enum descriptors are loaded (decode yields enum NUMBERS)."""
    if txt is None:
        return None
    if ftype in (T_DOUBLE, T_FLOAT):
        return float(txt)
    if ftype == T_BOOL:
        return txt == "true"
    if ftype == T_STRING:
        return txt
    if ftype == T_BYTES:
        return _c_unescape(txt)
    if ftype == T_ENUM:
        return txt                    # symbolic; resolved by the pool
    try:
        return int(txt)
    except ValueError:
        return txt


class DescriptorPool:
    """Message schemas from a `FileDescriptorSet` (protoc --descriptor_set_out)."""

    def __init__(self, descriptor_set: bytes):
        self.messages: Dict[str, MessageSchema] = {}
        self.enums: Dict[str, Dict[str, int]] = {}   # full name -> symbol -> num
        for num, _wt, v in iter_fields(descriptor_set):
            if num == 1:   # FileDescriptorSet.file
                self._load_file(v)
        # resolve symbolic enum defaults to NUMBERS now that every enum
        # descriptor is loaded (decode yields enum numbers; a string default
        # would make the same column int-or-str depending on field presence)
        for schema in self.messages.values():
            for f in schema.fields.values():
                if f.type == T_ENUM and isinstance(f.default, str):
                    f.default = self.enums.get(f.type_name, {}).get(f.default)

    def _load_file(self, fdp: bytes) -> None:
        package = ""
        msgs: List[bytes] = []
        enums: List[bytes] = []
        for num, _wt, v in iter_fields(fdp):
            if num == 2:           # FileDescriptorProto.package
                package = v.decode()
            elif num == 4:         # message_type
                msgs.append(v)
            elif num == 5:         # enum_type
                enums.append(v)
        prefix = f".{package}" if package else ""
        for e in enums:
            self._load_enum(e, prefix)
        for m in msgs:
            self._load_message(m, prefix)

    def _load_enum(self, edp: bytes, prefix: str) -> None:
        name = ""
        values: Dict[str, int] = {}
        for num, _wt, v in iter_fields(edp):
            if num == 1:           # EnumDescriptorProto.name
                name = v.decode()
            elif num == 2:         # value: EnumValueDescriptorProto
                vname, vnum = "", 0
                for n2, _w2, v2 in iter_fields(v):
                    if n2 == 1:
                        vname = v2.decode()
                    elif n2 == 2:
                        vnum = v2
                values[vname] = vnum
        self.enums[f"{prefix}.{name}"] = values

    def _load_message(self, dp: bytes, prefix: str) -> None:
        name = ""
        fields: List[bytes] = []
        nested: List[bytes] = []
        nested_enums: List[bytes] = []
        for num, _wt, v in iter_fields(dp):
            if num == 1:           # DescriptorProto.name
                name = v.decode()
            elif num == 2:         # field
                fields.append(v)
            elif num == 3:         # nested_type
                nested.append(v)
            elif num == 4:         # enum_type (nested)
                nested_enums.append(v)
        full = f"{prefix}.{name}"
        for e in nested_enums:
            self._load_enum(e, full)
        schema = MessageSchema(full)
        for f in fields:
            fname = ""
            number = ftype = 0
            label = 1
            type_name = ""
            in_oneof = False
            default_txt: Optional[str] = None
            for num, _wt, v in iter_fields(f):
                if num == 1:
                    fname = v.decode()
                elif num == 3:
                    number = v
                elif num == 4:
                    label = v
                elif num == 5:
                    ftype = v
                elif num == 6:
                    type_name = v.decode()
                elif num == 7:        # proto2 default_value (text form)
                    default_txt = v.decode()
                elif num == 9:        # oneof_index (proto3 `optional` uses a
                    in_oneof = True   # synthetic oneof too: field 17)
                elif num == 17 and v:
                    in_oneof = True
            fs = FieldSchema(fname, number, ftype, label == LABEL_REPEATED,
                             type_name, in_oneof,
                             _parse_default(ftype, default_txt))
            schema.fields[number] = fs
            schema.by_name[fname] = fs
        self.messages[full] = schema
        for n in nested:
            self._load_message(n, full)

    def message(self, name: str) -> MessageSchema:
        key = name if name.startswith(".") else f".{name}"
        m = self.messages.get(key)
        if m is None:
            # tolerate unqualified names (single-package descriptor sets)
            cands = [v for k, v in self.messages.items()
                     if k.endswith(f".{name}")]
            if len(cands) == 1:
                return cands[0]
            raise ProtoError(f"unknown message {name!r} "
                             f"(have {sorted(self.messages)})")
        return m


# ---------------------------------------------------------------------------
# descriptor-driven decode / encode
# ---------------------------------------------------------------------------

def _scalar(ftype: int, wt: int, v) -> Any:
    if ftype in (T_DOUBLE, T_FLOAT, T_FIXED64, T_SFIXED64, T_FIXED32,
                 T_SFIXED32, T_STRING, T_BYTES):
        if not isinstance(v, (bytes, bytearray)):
            raise ProtoError(
                f"wire/type mismatch for field type {ftype} (wrong schema?)")
    elif not isinstance(v, int):
        raise ProtoError(
            f"wire/type mismatch for field type {ftype} (wrong schema?)")
    if ftype in (T_INT64, T_INT32, T_ENUM):
        # enums have int32 wire semantics: a negative constant arrives as a
        # sign-extended 64-bit varint, NOT a huge unsigned value
        return _i64_signed(v)
    if ftype in (T_UINT64, T_UINT32):
        return v
    if ftype in (T_SINT32, T_SINT64):
        return _unzigzag(v)
    if ftype == T_BOOL:
        return bool(v)
    if ftype == T_DOUBLE:
        return struct.unpack("<d", v)[0]
    if ftype == T_FLOAT:
        return struct.unpack("<f", v)[0]
    if ftype == T_FIXED64:
        return struct.unpack("<Q", v)[0]
    if ftype == T_SFIXED64:
        return struct.unpack("<q", v)[0]
    if ftype == T_FIXED32:
        return struct.unpack("<I", v)[0]
    if ftype == T_SFIXED32:
        return struct.unpack("<i", v)[0]
    if ftype == T_STRING:
        return v.decode("utf-8")
    if ftype == T_BYTES:
        return bytes(v)
    raise ProtoError(f"unsupported field type {ftype}")


def _unpack_packed(ftype: int, v: bytes) -> List[Any]:
    out = []
    if ftype in (T_DOUBLE, T_FIXED64, T_SFIXED64):
        if len(v) % 8:
            raise ProtoError("truncated packed fixed64 field")
        fmt = {T_DOUBLE: "<d", T_FIXED64: "<Q", T_SFIXED64: "<q"}[ftype]
        for i in range(0, len(v), 8):
            out.append(struct.unpack(fmt, v[i:i + 8])[0])
    elif ftype in (T_FLOAT, T_FIXED32, T_SFIXED32):
        if len(v) % 4:
            raise ProtoError("truncated packed fixed32 field")
        fmt = {T_FLOAT: "<f", T_FIXED32: "<I", T_SFIXED32: "<i"}[ftype]
        for i in range(0, len(v), 4):
            out.append(struct.unpack(fmt, v[i:i + 4])[0])
    else:
        pos = 0
        while pos < len(v):
            u, pos = read_uvarint(v, pos)
            out.append(_scalar(ftype, _VARINT, u))
    return out


_TYPE_DEFAULT = {T_STRING: "", T_BYTES: b"", T_BOOL: False,
                 T_DOUBLE: 0.0, T_FLOAT: 0.0}


def decode_message(pool: DescriptorPool, schema: MessageSchema,
                   data: bytes) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for num, wt, v in iter_fields(data):
        f = schema.fields.get(num)
        if f is None:
            continue   # unknown field: skipped (already consumed by wire type)
        if f.type == T_MESSAGE:
            sub = decode_message(pool, pool.message(f.type_name), v)
            if f.repeated:
                out.setdefault(f.name, []).append(sub)
            else:
                out[f.name] = sub
            continue
        if f.repeated:
            vals = out.setdefault(f.name, [])
            if wt == _LEN and f.type in _PACKABLE:
                vals.extend(_unpack_packed(f.type, v))
            else:
                vals.append(_scalar(f.type, wt, v))
        else:
            out[f.name] = _scalar(f.type, wt, v)
    # implicit defaults: a field holding its default value is OMITTED on the
    # wire; the reader contract (like the reference's generated getters) is
    # 0/""/false/[] (or the proto2 declared default), never a missing key —
    # without this, a .pb and a .jsonl of identical rows ingest differently.
    # ONEOF members (incl. proto3 `optional` synthetic oneofs) have explicit
    # presence: absent stays absent (null).
    for f in schema.fields.values():
        if f.name in out:
            continue
        if f.repeated:
            out[f.name] = []
        elif f.type in (T_MESSAGE, T_GROUP) or f.in_oneof:
            # absent submessage / skipped group / unset oneof arm stays null
            # (a 0 fill for a group column would read as data, not absence)
            continue
        elif f.default is not None:
            out[f.name] = f.default
        else:
            out[f.name] = _TYPE_DEFAULT.get(f.type, 0)
    return out


def encode_message(pool: DescriptorPool, schema: MessageSchema,
                   row: Dict[str, Any]) -> bytes:
    """Descriptor-driven encoder (tests + datagen; repeated scalars packed)."""
    by_name = schema.by_name   # built once at descriptor load, not per row
    out = bytearray()

    def scalar_bytes(f: FieldSchema, v) -> Tuple[int, bytes]:
        t = f.type
        if t in (T_INT64, T_INT32, T_UINT64, T_UINT32, T_ENUM, T_BOOL):
            return _VARINT, write_uvarint(int(v) & 0xFFFFFFFFFFFFFFFF)
        if t in (T_SINT32, T_SINT64):
            return _VARINT, write_uvarint(_zigzag(int(v)))
        if t == T_DOUBLE:
            return _I64, struct.pack("<d", float(v))
        if t == T_FIXED64:
            return _I64, struct.pack("<Q", int(v))
        if t == T_SFIXED64:
            return _I64, struct.pack("<q", int(v))
        if t == T_FLOAT:
            return _I32, struct.pack("<f", float(v))
        if t == T_FIXED32:
            return _I32, struct.pack("<I", int(v))
        if t == T_SFIXED32:
            return _I32, struct.pack("<i", int(v))
        if t == T_STRING:
            raw = str(v).encode("utf-8")
            return _LEN, write_uvarint(len(raw)) + raw
        if t == T_BYTES:
            raw = bytes(v)
            return _LEN, write_uvarint(len(raw)) + raw
        raise ProtoError(f"unsupported field type {t}")

    for name, v in row.items():
        f = by_name.get(name)
        if f is None or v is None:
            continue
        if f.type == T_MESSAGE:
            subs = v if f.repeated else [v]
            for sub in subs:
                raw = encode_message(pool, pool.message(f.type_name), sub)
                out += write_uvarint((f.number << 3) | _LEN)
                out += write_uvarint(len(raw)) + raw
        elif f.repeated:
            if f.type in _PACKABLE:
                payload = bytearray()
                for item in v:
                    wt, raw = scalar_bytes(f, item)
                    payload += raw
                out += write_uvarint((f.number << 3) | _LEN)
                out += write_uvarint(len(payload)) + bytes(payload)
            else:
                for item in v:
                    wt, raw = scalar_bytes(f, item)
                    out += write_uvarint((f.number << 3) | wt) + raw
        else:
            wt, raw = scalar_bytes(f, v)
            out += write_uvarint((f.number << 3) | wt) + raw
    return bytes(out)


# ---------------------------------------------------------------------------
# RecordReader + stream decoder plugins
# ---------------------------------------------------------------------------

class ProtoRecordReader:
    """Varint-length-delimited protobuf messages from a file (the reference
    ProtoBufRecordReader's format), schema from a descriptor set.

    `reader_for("x.pb")` convention: the descriptor lives in a sidecar
    `<path>.desc`; the record message name in `<path>.msg` (one line) —
    required only when the descriptor defines more than one message. The
    explicit constructor takes descriptor bytes + message name."""

    def __init__(self, path: str, descriptor_set: Optional[bytes] = None,
                 message: Optional[str] = None):
        self.path = path
        if descriptor_set is None:
            sidecar = path + ".desc"
            if not os.path.exists(sidecar):
                raise ProtoError(
                    f"{path}: no descriptor given and no sidecar {sidecar}")
            with open(sidecar, "rb") as f:
                descriptor_set = f.read()
        self.pool = DescriptorPool(descriptor_set)
        if message is None:
            msg_sidecar = path + ".msg"
            if os.path.exists(msg_sidecar):
                with open(msg_sidecar) as f:
                    message = f.read().strip()
            elif len(self.pool.messages) == 1:
                message = next(iter(self.pool.messages))
            else:
                raise ProtoError(
                    f"{path}: descriptor defines {len(self.pool.messages)} "
                    f"messages — name the record type in {msg_sidecar} "
                    f"(have {sorted(self.pool.messages)})")
        self.schema = self.pool.message(message)

    def rows(self) -> Iterator[Dict[str, Any]]:
        # STREAMING: one message at a time off the file object (like every
        # other reader — batch jobs must not materialize multi-GB inputs)
        with open(self.path, "rb") as f:
            while True:
                n = self._read_len_prefix(f)
                if n is None:
                    return
                raw = f.read(n)
                if len(raw) < n:
                    raise ProtoError("truncated delimited message")
                yield decode_message(self.pool, self.schema, raw)

    @staticmethod
    def _read_len_prefix(f) -> Optional[int]:
        out = shift = 0
        first = True
        while True:
            b = f.read(1)
            if not b:
                if first:
                    return None   # clean EOF at a message boundary
                raise ProtoError("truncated varint length prefix")
            first = False
            out |= (b[0] & 0x7F) << shift
            if not b[0] & 0x80:
                return out
            shift += 7
            if shift > 70:
                raise ProtoError("varint too long")

    def close(self) -> None:
        pass


def write_delimited(path: str, pool: DescriptorPool, schema: MessageSchema,
                    rows) -> None:
    """Companion writer: varint-length-delimited messages."""
    with open(path, "wb") as f:
        for row in rows:
            raw = encode_message(pool, schema, row)
            f.write(write_uvarint(len(raw)) + raw)


def make_proto_decoder(descriptor_set: bytes, message: str):
    """StreamMessageDecoder for raw protobuf message payloads (reference:
    ProtoBufMessageDecoder with descriptorFile + protoClassName props)."""
    pool = DescriptorPool(descriptor_set)
    schema = pool.message(message)

    def decode(value) -> Dict[str, Any]:
        return decode_message(pool, schema, bytes(value))
    return decode


def compile_proto(proto_source: str, workdir: str) -> bytes:
    """Run `protoc --descriptor_set_out` on inline .proto source -> the
    FileDescriptorSet blob (tests/tools; protoc ships in the image)."""
    import subprocess
    src = os.path.join(workdir, "schema.proto")
    out = os.path.join(workdir, "schema.desc")
    with open(src, "w") as f:
        f.write(proto_source)
    subprocess.run(["protoc", f"--proto_path={workdir}",
                    f"--descriptor_set_out={out}", src],
                   check=True, capture_output=True)
    with open(out, "rb") as f:
        return f.read()
