"""Pure-Python Avro: binary codec, object-container files, confluent stream wire.

Analog of the reference's flagship input format
(`pinot-plugins/pinot-input-format/pinot-avro/src/main/java/org/apache/pinot/
plugin/inputformat/avro/AvroRecordReader.java`) and its realtime decoders
(`SimpleAvroMessageDecoder`, `KafkaConfluentSchemaRegistryAvroMessageDecoder`
in `pinot-plugins/pinot-input-format/pinot-confluent-avro/`). Implemented
from the public Avro 1.11 specification — no avro library in this
environment, and like `kafka_wire.py` the wire format is produced and parsed
entirely by this module.

Supported schema subset (the verdict-scoped resolution subset): records,
all primitives (null/boolean/int/long/float/double/bytes/string), unions,
arrays, maps, enums, fixed. Schema resolution: reader-field defaults,
writer-field skipping, numeric promotion (int->long->float->double,
string<->bytes), union member resolution by branch type. Container codecs:
`null` and `deflate` (raw zlib); `snappy` is rejected loudly (no snappy in
this environment).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Callable, Dict, Iterator, List, Optional, Tuple

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double",
               "bytes", "string"}


class AvroError(ValueError):
    pass


# ---------------------------------------------------------------------------
# schema model
# ---------------------------------------------------------------------------

def parse_schema(schema) -> Any:
    """JSON text/object -> normalized schema tree. Named types are registered
    so later references by name resolve (spec: named type references)."""
    if isinstance(schema, (str, bytes)):
        try:
            schema = json.loads(schema)
        except ValueError:
            schema = schema.decode() if isinstance(schema, bytes) else schema
            # a bare primitive name like "string" is a valid schema
    names: Dict[str, Any] = {}
    return _norm(schema, names)


def _norm(s, names: Dict[str, Any]):
    if isinstance(s, str):
        if s in _PRIMITIVES:
            return s
        if s in names:
            return names[s]
        raise AvroError(f"unknown schema name {s!r}")
    if isinstance(s, list):  # union
        return {"type": "union", "branches": [_norm(b, names) for b in s]}
    if not isinstance(s, dict):
        raise AvroError(f"bad schema node {s!r}")
    t = s.get("type")
    if t in _PRIMITIVES:
        # spec: unknown attributes on a type dict (logicalType,
        # avro.java.string, precision, ...) are ignored, never errors —
        # real Java-written files carry them
        return t
    if t == "record":
        node = {"type": "record", "name": s["name"], "fields": []}
        names[s["name"]] = node
        if s.get("namespace"):
            names[f"{s['namespace']}.{s['name']}"] = node
        for f in s["fields"]:
            fld = {"name": f["name"], "type": _norm(f["type"], names)}
            if "default" in f:
                fld["default"] = f["default"]
            node["fields"].append(fld)
        return node
    if t == "enum":
        node = {"type": "enum", "name": s["name"], "symbols": list(s["symbols"])}
        names[s["name"]] = node
        return node
    if t == "fixed":
        node = {"type": "fixed", "name": s["name"], "size": int(s["size"])}
        names[s["name"]] = node
        return node
    if t == "array":
        return {"type": "array", "items": _norm(s["items"], names)}
    if t == "map":
        return {"type": "map", "values": _norm(s["values"], names)}
    if isinstance(t, (list, dict)):
        return _norm(t, names)
    raise AvroError(f"unsupported schema type {t!r}")


def _type_of(s) -> str:
    return s if isinstance(s, str) else s["type"]


# ---------------------------------------------------------------------------
# binary codec (spec: zig-zag varint ints, little-endian IEEE floats,
# length-prefixed bytes/strings, block-encoded arrays/maps)
# ---------------------------------------------------------------------------

class BinaryEncoder:
    def __init__(self, out: Optional[io.BytesIO] = None):
        self.out = out if out is not None else io.BytesIO()

    def write_long(self, v: int) -> None:
        v = (v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1  # zig-zag
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.write(bytes([b | 0x80]))
            else:
                self.out.write(bytes([b]))
                return

    def write_float(self, v: float) -> None:
        self.out.write(struct.pack("<f", v))

    def write_double(self, v: float) -> None:
        self.out.write(struct.pack("<d", v))

    def write_bytes(self, v: bytes) -> None:
        self.write_long(len(v))
        self.out.write(v)

    def getvalue(self) -> bytes:
        return self.out.getvalue()


class BinaryDecoder:
    def __init__(self, data) -> None:
        self.buf = io.BytesIO(data) if isinstance(data, (bytes, bytearray)) \
            else data

    def _read(self, n: int) -> bytes:
        b = self.buf.read(n)
        if len(b) < n:
            raise AvroError("truncated avro data")
        return b

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            (b,) = self._read(1)
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 70:
                raise AvroError("varint too long")
        return (acc >> 1) ^ -(acc & 1)  # un-zig-zag

    def read_float(self) -> float:
        return struct.unpack("<f", self._read(4))[0]

    def read_double(self) -> float:
        return struct.unpack("<d", self._read(8))[0]

    def read_bytes(self) -> bytes:
        n = self.read_long()
        if n < 0:
            raise AvroError("negative byte length")
        return self._read(n)


def write_datum(enc: BinaryEncoder, schema, v: Any) -> None:
    t = _type_of(schema)
    if t == "null":
        if v is not None:
            raise AvroError(f"null schema got {v!r}")
    elif t == "boolean":
        enc.out.write(b"\x01" if v else b"\x00")
    elif t in ("int", "long"):
        enc.write_long(int(v))
    elif t == "float":
        enc.write_float(float(v))
    elif t == "double":
        enc.write_double(float(v))
    elif t == "bytes":
        enc.write_bytes(bytes(v))
    elif t == "string":
        enc.write_bytes(v.encode("utf-8"))
    elif t == "record":
        for f in schema["fields"]:
            write_datum(enc, f["type"], v.get(f["name"]) if isinstance(v, dict)
                        else getattr(v, f["name"]))
    elif t == "enum":
        enc.write_long(schema["symbols"].index(v))
    elif t == "fixed":
        if len(v) != schema["size"]:
            raise AvroError("fixed size mismatch")
        enc.out.write(bytes(v))
    elif t == "array":
        if v:
            enc.write_long(len(v))
            for item in v:
                write_datum(enc, schema["items"], item)
        enc.write_long(0)
    elif t == "map":
        if v:
            enc.write_long(len(v))
            for k, item in v.items():
                enc.write_bytes(k.encode("utf-8"))
                write_datum(enc, schema["values"], item)
        enc.write_long(0)
    elif t == "union":
        idx = _union_index(schema["branches"], v)
        enc.write_long(idx)
        write_datum(enc, schema["branches"][idx], v)
    else:
        raise AvroError(f"unsupported schema {t!r}")


def _union_index(branches, v) -> int:
    for i, b in enumerate(branches):
        bt = _type_of(b)
        if v is None and bt == "null":
            return i
        if isinstance(v, bool):
            if bt == "boolean":
                return i
            continue
        if isinstance(v, int) and bt in ("int", "long"):
            return i
        if isinstance(v, float) and bt in ("float", "double"):
            return i
        if isinstance(v, str) and bt in ("string", "enum"):
            return i
        if isinstance(v, (bytes, bytearray)) and bt in ("bytes", "fixed"):
            return i
        if isinstance(v, dict) and bt in ("record", "map"):
            return i
        if isinstance(v, (list, tuple)) and bt == "array":
            return i
    if isinstance(v, int) and not isinstance(v, bool):
        # promotion pass: an int encodes into a float/double-only union (the
        # read path promotes the same way; JSON whole numbers arrive as int)
        for i, b in enumerate(branches):
            if _type_of(b) in ("float", "double"):
                return i
    raise AvroError(f"no union branch for {type(v).__name__}")


def read_datum(dec: BinaryDecoder, writer, reader=None) -> Any:
    """Decode one datum written with `writer`, resolved to `reader` when given
    (spec: schema resolution — defaults, skipped fields, promotions)."""
    wt = _type_of(writer)
    if reader is not None and _type_of(reader) == "union" and wt != "union":
        # writer non-union read by union reader: resolve to the matching branch
        reader = _resolve_branch(reader["branches"], writer)
    if wt == "null":
        return None
    if wt == "boolean":
        return dec._read(1) != b"\x00"
    if wt in ("int", "long"):
        v = dec.read_long()
        if reader is not None and _type_of(reader) in ("float", "double"):
            return float(v)
        return v
    if wt == "float":
        return dec.read_float()
    if wt == "double":
        return dec.read_double()
    if wt == "bytes":
        raw = dec.read_bytes()
        if reader is not None and _type_of(reader) == "string":
            return raw.decode("utf-8")
        return raw
    if wt == "string":
        return dec.read_bytes().decode("utf-8")
    if wt == "record":
        reader_fields = ({f["name"]: f for f in reader["fields"]}
                         if reader is not None and _type_of(reader) == "record"
                         else None)
        out: Dict[str, Any] = {}
        for f in writer["fields"]:
            rf = reader_fields.get(f["name"]) if reader_fields is not None else None
            v = read_datum(dec, f["type"], rf["type"] if rf else None)
            if reader_fields is None or rf is not None:
                out[f["name"]] = v     # reader-absent writer fields are skipped
        if reader_fields is not None:
            for name, rf in reader_fields.items():
                if name not in out:
                    if "default" not in rf:
                        raise AvroError(f"missing field {name!r} has no default")
                    out[name] = rf["default"]
        return out
    if wt == "enum":
        idx = dec.read_long()
        try:
            return writer["symbols"][idx]
        except IndexError:
            raise AvroError(f"enum index {idx} out of range") from None
    if wt == "fixed":
        return dec._read(writer["size"])
    if wt == "array":
        items = writer["items"]
        ritems = (reader["items"] if reader is not None
                  and _type_of(reader) == "array" else None)
        out_list: List[Any] = []
        while True:
            n = dec.read_long()
            if n == 0:
                return out_list
            if n < 0:  # negative count: block byte size follows (spec)
                n = -n
                dec.read_long()
            for _ in range(n):
                out_list.append(read_datum(dec, items, ritems))
    if wt == "map":
        values = writer["values"]
        rvalues = (reader["values"] if reader is not None
                   and _type_of(reader) == "map" else None)
        out_map: Dict[str, Any] = {}
        while True:
            n = dec.read_long()
            if n == 0:
                return out_map
            if n < 0:
                n = -n
                dec.read_long()
            for _ in range(n):
                k = dec.read_bytes().decode("utf-8")
                out_map[k] = read_datum(dec, values, rvalues)
    if wt == "union":
        idx = dec.read_long()
        try:
            branch = writer["branches"][idx]
        except IndexError:
            raise AvroError(f"union index {idx} out of range") from None
        rbranch = None
        if reader is not None:
            rb = reader["branches"] if _type_of(reader) == "union" else [reader]
            try:
                rbranch = _resolve_branch(rb, branch)
            except AvroError:
                rbranch = None
        return read_datum(dec, branch, rbranch)
    raise AvroError(f"unsupported schema {wt!r}")


def _resolve_branch(branches, writer):
    wt = _type_of(writer)
    promotions = {"int": {"int", "long", "float", "double"},
                  "long": {"long", "float", "double"},
                  "float": {"float", "double"},
                  "string": {"string", "bytes"},
                  "bytes": {"bytes", "string"}}
    for b in branches:
        if _type_of(b) == wt:
            return b
    for b in branches:
        if _type_of(b) in promotions.get(wt, ()):
            return b
    raise AvroError(f"no reader branch for writer type {wt!r}")


# ---------------------------------------------------------------------------
# object container files (spec: magic, metadata map, sync-delimited blocks)
# ---------------------------------------------------------------------------

class AvroFileWriter:
    def __init__(self, path: str, schema, codec: str = "null",
                 sync_interval: int = 4000):
        if codec not in ("null", "deflate"):
            raise AvroError(f"unsupported codec {codec!r}")
        self.schema = parse_schema(schema)
        self._schema_json = (schema if isinstance(schema, str)
                             else json.dumps(schema))
        self.codec = codec
        self.sync = os.urandom(SYNC_SIZE)
        self.sync_interval = sync_interval
        self._f: BinaryIO = open(path, "wb")
        self._buf = BinaryEncoder()
        self._count = 0
        header = BinaryEncoder()
        header.out.write(MAGIC)
        write_datum(header, {"type": "map", "values": "bytes"},
                    {"avro.schema": self._schema_json.encode(),
                     "avro.codec": self.codec.encode()})
        header.out.write(self.sync)
        self._f.write(header.getvalue())

    def append(self, record: Dict[str, Any]) -> None:
        write_datum(self._buf, self.schema, record)
        self._count += 1
        if self._count >= self.sync_interval:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._count:
            return
        payload = self._buf.getvalue()
        if self.codec == "deflate":
            payload = zlib.compress(payload)[2:-4]  # raw deflate (spec)
        head = BinaryEncoder()
        head.write_long(self._count)
        head.write_long(len(payload))
        self._f.write(head.getvalue())
        self._f.write(payload)
        self._f.write(self.sync)
        self._buf = BinaryEncoder()
        self._count = 0

    def close(self) -> None:
        self._flush_block()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class AvroFileReader:
    """Streams records out of an .avro object-container file; an optional
    `reader_schema` resolves against the file's writer schema."""

    def __init__(self, path: str, reader_schema=None):
        self._f = open(path, "rb")
        if self._f.read(4) != MAGIC:
            self._f.close()
            raise AvroError(f"{path}: not an avro object-container file")
        dec = BinaryDecoder(self._f)
        meta = read_datum(dec, {"type": "map", "values": "bytes"})
        self.codec = meta.get("avro.codec", b"null").decode()
        if self.codec not in ("null", "deflate"):
            self._f.close()
            raise AvroError(f"unsupported codec {self.codec!r} "
                            f"(null/deflate only in this environment)")
        self.writer_schema = parse_schema(meta["avro.schema"].decode())
        self.reader_schema = (parse_schema(reader_schema)
                              if reader_schema is not None else None)
        self.sync = self._f.read(SYNC_SIZE)

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        while True:
            head = self._f.read(1)
            if not head:
                return
            self._f.seek(-1, 1)
            dec = BinaryDecoder(self._f)
            count = dec.read_long()
            size = dec.read_long()
            payload = self._f.read(size)
            if len(payload) < size:
                raise AvroError("truncated avro block")
            if self.codec == "deflate":
                payload = zlib.decompress(payload, wbits=-15)
            block = BinaryDecoder(payload)
            for _ in range(count):
                yield read_datum(block, self.writer_schema, self.reader_schema)
            if self._f.read(SYNC_SIZE) != self.sync:
                raise AvroError("sync marker mismatch (corrupt avro file)")

    def close(self) -> None:
        self._f.close()


# ---------------------------------------------------------------------------
# RecordReader plugin (batch ingestion of .avro files)
# ---------------------------------------------------------------------------

class AvroRecordReader:
    """Reference: `pinot-avro/.../AvroRecordReader.java` — streams GenericRow
    dicts out of an object-container file. Restartable like every other
    RecordReader: each rows() call opens a fresh pass over the file (the
    streaming batch runner's stats-then-write shape re-iterates readers)."""

    def __init__(self, path: str):
        self.path = path
        AvroFileReader(path).close()   # validate magic/codec eagerly
        self._open: List[AvroFileReader] = []

    def rows(self) -> Iterator[Dict[str, Any]]:
        reader = AvroFileReader(self.path)
        self._open.append(reader)
        return iter(reader)

    def close(self) -> None:
        for r in self._open:
            r.close()
        self._open = []


# ---------------------------------------------------------------------------
# confluent-style stream wire + decoders
# ---------------------------------------------------------------------------

class LocalSchemaRegistry:
    """In-process schema registry (the schema-registry-server analog for
    kafkalite streams): id -> parsed schema. Thread-safe: concurrent
    producers must never be issued the same id."""

    def __init__(self):
        import threading
        self._by_id: Dict[int, Any] = {}
        self._next = 1
        self._lock = threading.Lock()

    def register(self, schema) -> int:
        parsed = parse_schema(schema)
        with self._lock:
            sid = self._next
            self._next += 1
            self._by_id[sid] = parsed
        return sid

    def get(self, schema_id: int):
        s = self._by_id.get(schema_id)
        if s is None:
            raise AvroError(f"unknown schema id {schema_id}")
        return s


DEFAULT_REGISTRY = LocalSchemaRegistry()


_PARSE_CACHE: Dict[str, Any] = {}


def _parse_cached(schema) -> Any:
    key = schema if isinstance(schema, str) else json.dumps(schema,
                                                            sort_keys=True)
    parsed = _PARSE_CACHE.get(key)
    if parsed is None:
        if len(_PARSE_CACHE) > 256:
            _PARSE_CACHE.clear()
        parsed = _PARSE_CACHE[key] = parse_schema(schema)
    return parsed


def encode_confluent(schema_id: int, schema, record: Dict[str, Any]) -> bytes:
    """Confluent wire format: magic 0x00 | schema-id u32 BE | avro binary
    (reference: KafkaConfluentSchemaRegistryAvroMessageDecoder's input).
    `schema` is JSON text/object (parse memoized — this is the per-message
    produce path)."""
    enc = BinaryEncoder()
    enc.out.write(b"\x00")
    enc.out.write(struct.pack(">I", schema_id))
    write_datum(enc, _parse_cached(schema), record)
    return enc.getvalue()


def confluent_avro_decoder(value: Any,
                           registry: Optional[LocalSchemaRegistry] = None
                           ) -> Dict[str, Any]:
    """StreamMessageDecoder: confluent-framed avro message bytes -> row dict."""
    reg = registry if registry is not None else DEFAULT_REGISTRY
    data = bytes(value)
    if not data or data[0] != 0:
        raise AvroError("not a confluent-framed avro message (magic != 0)")
    if len(data) < 5:
        raise AvroError("truncated confluent header")
    (schema_id,) = struct.unpack(">I", data[1:5])
    return read_datum(BinaryDecoder(data[5:]), reg.get(schema_id))


def make_simple_avro_decoder(schema) -> Callable[[Any], Dict[str, Any]]:
    """Decoder closure for a FIXED schema with no framing (reference:
    SimpleAvroMessageDecoder with the schema in the table's stream config)."""
    parsed = parse_schema(schema)

    def decode(value: Any) -> Dict[str, Any]:
        return read_datum(BinaryDecoder(bytes(value)), parsed)
    return decode


# registration lives in the SPI modules (readers.py / stream.py) as lazy
# factories, so `reader_for("x.avro")` and decoder "avro" work without an
# explicit `import pinot_tpu.ingest.avro` anywhere
