"""Pure-Python Thrift: TBinaryProtocol codec + a .thrift IDL struct parser.

Analog of the reference's thrift input format
(`pinot-plugins/pinot-input-format/pinot-thrift/src/main/java/org/apache/
pinot/plugin/inputformat/thrift/ThriftRecordReader.java` — reads
back-to-back TBinaryProtocol-serialized structs from a file using a
generated thrift class). No generated classes here: field names come from a
parsed .thrift IDL subset (structs, enums, typedefs, base types,
list/set/map, nested structs) and values decode generically from the wire.

TBinaryProtocol (strict and non-strict struct bodies are identical): a
struct is a sequence of `type:i8 field-id:i16 value` entries terminated by
STOP(0). TCompactProtocol is not implemented (the reference's reader
defaults to binary too).
"""

from __future__ import annotations

import os
import re
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

# TBinaryProtocol type ids
T_STOP, T_BOOL, T_BYTE, T_DOUBLE = 0, 2, 3, 4
T_I16, T_I32, T_I64, T_STRING = 6, 8, 10, 11
T_STRUCT, T_MAP, T_SET, T_LIST = 12, 13, 14, 15

_BASE_TYPES = {
    "bool": T_BOOL, "byte": T_BYTE, "i8": T_BYTE, "double": T_DOUBLE,
    "i16": T_I16, "i32": T_I32, "i64": T_I64, "string": T_STRING,
    "binary": T_STRING,
}


class ThriftError(ValueError):
    pass


# ---------------------------------------------------------------------------
# IDL subset parser
# ---------------------------------------------------------------------------

class ThriftField:
    __slots__ = ("fid", "name", "ttype", "spec")

    def __init__(self, fid: int, name: str, ttype: int, spec: str):
        self.fid = fid
        self.name = name
        self.ttype = ttype      # wire type id
        self.spec = spec        # resolved IDL type spec ("i64", "map<K,V>", ...)


_CONTAINER_RE = re.compile(r"(list|set|map)\s*<\s*(.*)\s*>$")


def _split_container(spec: str):
    """'list<X>'/'set<X>' -> (kind, None, X); 'map<K,V>' -> ('map', K, V);
    None for non-containers. Splits the map comma at top nesting level."""
    m = _CONTAINER_RE.fullmatch(spec.strip())
    if not m:
        return None
    kind, inner = m.group(1), m.group(2)
    if kind in ("list", "set"):
        return kind, None, inner.strip()
    depth = 0
    for i, ch in enumerate(inner):
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        elif ch == "," and depth == 0:
            return "map", inner[:i].strip(), inner[i + 1:].strip()
    raise ThriftError(f"bad map spec {spec!r}")


class ThriftStruct:
    def __init__(self, name: str):
        self.name = name
        self.fields: Dict[int, ThriftField] = {}


class ThriftIDL:
    """Parsed .thrift schema: structs + enums (typedefs resolved inline)."""

    def __init__(self, source: str):
        self.structs: Dict[str, ThriftStruct] = {}
        self.enums: Dict[str, Dict[int, str]] = {}
        self.typedefs: Dict[str, str] = {}
        src = re.sub(r"//[^\n]*|#[^\n]*", "", source)
        src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
        for m in re.finditer(r"typedef\s+(\S+)\s+(\w+)", src):
            self.typedefs[m.group(2)] = m.group(1)
        for m in re.finditer(r"enum\s+(\w+)\s*\{([^}]*)\}", src):
            values: Dict[int, str] = {}
            auto = 0
            for item in m.group(2).split(","):
                item = item.strip().rstrip(";").strip()
                if not item:
                    continue
                if "=" in item:
                    nm, _, num = item.partition("=")
                    auto = int(num.strip(), 0)
                    values[auto] = nm.strip()
                else:
                    values[auto] = item
                auto += 1
            self.enums[m.group(1)] = values
        for m in re.finditer(r"struct\s+(\w+)\s*\{([^}]*)\}", src):
            st = ThriftStruct(m.group(1))
            body = m.group(2)
            # the type is either a container with (one level of nested)
            # generics matched as a UNIT, or a plain name — a lazy char class
            # would split "map<string, double>" at the comma
            for fm in re.finditer(
                    r"(\d+)\s*:\s*(?:required\s+|optional\s+)?"
                    r"([\w\.]+\s*<(?:[^<>]|<[^<>]*>)*>|[\w\.]+)"
                    r"\s+(\w+)\s*(?:=[^;,]+)?[;,]?", body):
                fid = int(fm.group(1))
                st.fields[fid] = self._field(fid, fm.group(2).strip(),
                                             fm.group(3))
            self.structs[st.name] = st

    def _resolve(self, tname: str) -> str:
        seen = set()
        while tname in self.typedefs and tname not in seen:
            seen.add(tname)
            tname = self.typedefs[tname]
        return tname

    def _field(self, fid: int, tname: str, fname: str) -> ThriftField:
        tname = self._resolve(tname)
        parts = _split_container(tname)
        if parts:
            ttype = {"list": T_LIST, "set": T_SET, "map": T_MAP}[parts[0]]
            return ThriftField(fid, fname, ttype, tname)
        if tname in _BASE_TYPES:
            return ThriftField(fid, fname, _BASE_TYPES[tname], tname)
        if tname in self.enums:
            return ThriftField(fid, fname, T_I32, tname)
        # struct reference (possibly forward): resolved at decode time
        return ThriftField(fid, fname, T_STRUCT, tname)

    def struct(self, name: str) -> ThriftStruct:
        st = self.structs.get(name)
        if st is None:
            raise ThriftError(f"unknown struct {name!r} "
                              f"(have {sorted(self.structs)})")
        return st


def _wire_type(idl: ThriftIDL, tname: str) -> int:
    tname = idl._resolve(tname)
    if tname in _BASE_TYPES:
        return _BASE_TYPES[tname]
    if tname in idl.enums:
        return T_I32
    if tname in idl.structs:
        return T_STRUCT
    lm = re.fullmatch(r"(list|set)\s*<.*>", tname)
    if lm:
        return T_LIST if lm.group(1) == "list" else T_SET
    if re.fullmatch(r"map\s*<.*>", tname):
        return T_MAP
    raise ThriftError(f"unknown thrift type {tname!r}")


# ---------------------------------------------------------------------------
# TBinaryProtocol decode (spec: big-endian fixed-width everything)
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, f):
        self.f = f

    def take(self, n: int) -> bytes:
        b = self.f.read(n)
        if len(b) < n:
            raise ThriftError("truncated thrift data")
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def double(self) -> float:
        return struct.unpack(">d", self.take(8))[0]

    def binary(self) -> bytes:
        n = self.i32()
        if n < 0:
            raise ThriftError("negative thrift string length")
        return self.take(n)


_SKIP_STACK_CAP = 10_000


def _skip(r: _Reader, ttype: int) -> None:
    """Skip one value of a wire type (unknown fields, like generated code).

    ITERATIVE with an explicit work stack: nesting depth is wire-controlled
    (a few bytes per level of hostile input), so recursion would die with
    RecursionError — outside the ThriftError contract bad-record handlers
    catch. Container sizes are validated like the decode path (a negative
    count would silently misalign the stream into garbage fields)."""
    stack: List[Tuple] = [("val", ttype)]
    while stack:
        if len(stack) > _SKIP_STACK_CAP:
            raise ThriftError("thrift nesting too deep")
        frame = stack.pop()
        kind = frame[0]
        if kind == "val":
            t = frame[1]
            if t in (T_BOOL, T_BYTE):
                r.take(1)
            elif t == T_I16:
                r.take(2)
            elif t == T_I32:
                r.take(4)
            elif t in (T_I64, T_DOUBLE):
                r.take(8)
            elif t == T_STRING:
                r.binary()
            elif t == T_STRUCT:
                stack.append(("struct",))
            elif t in (T_LIST, T_SET):
                et = r.i8()
                n = r.i32()
                if n < 0:
                    raise ThriftError("negative thrift container size")
                stack.append(("rep", et, None, n))
            elif t == T_MAP:
                kt = r.i8()
                vt = r.i8()
                n = r.i32()
                if n < 0:
                    raise ThriftError("negative thrift map size")
                stack.append(("rep", kt, vt, n))
            else:
                raise ThriftError(f"bad thrift type {t}")
        elif kind == "struct":
            ft = r.i8()
            if ft != T_STOP:
                r.i16()
                stack.append(frame)          # resume this struct afterwards
                stack.append(("val", ft))
        else:  # ("rep", t1, t2|None, remaining)
            _, t1, t2, n = frame
            if n:
                stack.append(("rep", t1, t2, n - 1))
                if t2 is not None:
                    stack.append(("val", t2))
                stack.append(("val", t1))


def _decode_value(idl: ThriftIDL, r: _Reader, ttype: int,
                  spec: Optional[str]):
    """Decode one value; `spec` is the resolved IDL type spec of THIS value
    (None = untyped — struct names and nested container shapes come from it)."""
    if ttype == T_BOOL:
        return r.take(1) != b"\x00"
    if ttype == T_BYTE:
        return r.i8()
    if ttype == T_DOUBLE:
        return r.double()
    if ttype == T_I16:
        return r.i16()
    if ttype == T_I32:
        return r.i32()
    if ttype == T_I64:
        return r.i64()
    if ttype == T_STRING:
        raw = r.binary()
        return raw if spec == "binary" else raw.decode("utf-8")
    if ttype == T_STRUCT:
        if spec and spec in idl.structs:
            return decode_struct(idl, idl.struct(spec), r)
        _skip(r, T_STRUCT)
        return None
    parts = _split_container(idl._resolve(spec)) if spec else None
    if ttype in (T_LIST, T_SET):
        et = r.i8()
        n = r.i32()
        if n < 0:
            raise ThriftError("negative thrift container size")
        espec = parts[2] if parts else None
        return [_decode_value(idl, r, et, espec) for _ in range(n)]
    if ttype == T_MAP:
        kt = r.i8()
        vt = r.i8()
        n = r.i32()
        if n < 0:
            raise ThriftError("negative thrift map size")
        kspec = parts[1] if parts else None
        vspec = parts[2] if parts else None
        out = {}
        for _ in range(n):
            k = _decode_value(idl, r, kt, kspec)
            out[k] = _decode_value(idl, r, vt, vspec)
        return out
    raise ThriftError(f"bad thrift type {ttype}")


def decode_struct(idl: ThriftIDL, st: ThriftStruct, r: _Reader
                  ) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    while True:
        ftype = r.i8()
        if ftype == T_STOP:
            return out
        fid = r.i16()
        f = st.fields.get(fid)
        if f is None or (f.ttype != ftype
                         and not (f.ttype in (T_LIST, T_SET)
                                  and ftype in (T_LIST, T_SET))):
            _skip(r, ftype)   # unknown/evolved field: skipped like generated code
            continue
        out[f.name] = _decode_value(idl, r, ftype, f.spec)


# ---------------------------------------------------------------------------
# TBinaryProtocol encode (tests + datagen)
# ---------------------------------------------------------------------------

def encode_struct(idl: ThriftIDL, st: ThriftStruct, row: Dict[str, Any]
                  ) -> bytes:
    out = bytearray()

    def enc_spec(spec: str, v) -> bytes:
        """Value bytes for a resolved type SPEC (containers nest naturally)."""
        spec = idl._resolve(spec)
        parts = _split_container(spec)
        if parts:
            kind, kspec, espec = parts
            if kind == "map":
                kt = _wire_type(idl, kspec)
                vt = _wire_type(idl, espec)
                body = b"".join(enc_spec(kspec, k) + enc_spec(espec, mv)
                                for k, mv in v.items())
                return struct.pack(">bbi", kt, vt, len(v)) + body
            et = _wire_type(idl, espec)
            body = b"".join(enc_spec(espec, item) for item in v)
            return struct.pack(">bi", et, len(v)) + body
        if spec in idl.structs:
            return encode_struct(idl, idl.struct(spec), v)
        ttype = T_I32 if spec in idl.enums else _BASE_TYPES.get(spec)
        if ttype is None:
            raise ThriftError(f"unknown thrift type {spec!r}")
        if ttype == T_BOOL:
            return b"\x01" if v else b"\x00"
        if ttype == T_BYTE:
            return struct.pack(">b", int(v))
        if ttype == T_DOUBLE:
            return struct.pack(">d", float(v))
        if ttype == T_I16:
            return struct.pack(">h", int(v))
        if ttype == T_I32:
            return struct.pack(">i", int(v))
        if ttype == T_I64:
            return struct.pack(">q", int(v))
        raw = v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        return struct.pack(">i", len(raw)) + bytes(raw)

    for f in st.fields.values():
        v = row.get(f.name)
        if v is None:
            continue
        out += struct.pack(">bh", f.ttype, f.fid)
        out += enc_spec(f.spec, v)
    out += struct.pack(">b", T_STOP)
    return bytes(out)


# ---------------------------------------------------------------------------
# RecordReader + stream decoder plugins
# ---------------------------------------------------------------------------

class ThriftRecordReader:
    """Back-to-back TBinaryProtocol structs from a file (structs are
    self-delimiting: fields until STOP), schema from a .thrift IDL sidecar
    `<path>.thrift` (+ `<path>.msg` naming the record struct when the IDL
    defines several). Streams one struct at a time."""

    def __init__(self, path: str, idl: Optional[ThriftIDL] = None,
                 struct_name: Optional[str] = None):
        self.path = path
        if idl is None:
            sidecar = path + ".thrift"
            if not os.path.exists(sidecar):
                raise ThriftError(
                    f"{path}: no IDL given and no sidecar {sidecar}")
            with open(sidecar) as f:
                idl = ThriftIDL(f.read())
        self.idl = idl
        if struct_name is None:
            msg_sidecar = path + ".msg"
            if os.path.exists(msg_sidecar):
                with open(msg_sidecar) as f:
                    struct_name = f.read().strip()
            elif len(idl.structs) == 1:
                struct_name = next(iter(idl.structs))
            else:
                raise ThriftError(
                    f"{path}: IDL defines {len(idl.structs)} structs — name "
                    f"the record struct in {msg_sidecar}")
        self.struct = idl.struct(struct_name)

    def rows(self) -> Iterator[Dict[str, Any]]:
        with open(self.path, "rb") as f:
            r = _Reader(f)
            while True:
                first = f.read(1)
                if not first:
                    return
                f.seek(-1, 1)
                yield decode_struct(self.idl, self.struct, r)

    def close(self) -> None:
        pass


def write_structs(path: str, idl: ThriftIDL, st: ThriftStruct, rows) -> None:
    with open(path, "wb") as f:
        for row in rows:
            f.write(encode_struct(idl, st, row))


def make_thrift_decoder(idl_source: str, struct_name: str):
    """StreamMessageDecoder: TBinaryProtocol struct payloads -> row dicts
    (reference: the thrift message decoder configured with a thrift class)."""
    import io
    idl = ThriftIDL(idl_source)
    st = idl.struct(struct_name)

    def decode(value) -> Dict[str, Any]:
        return decode_struct(idl, st, _Reader(io.BytesIO(bytes(value))))
    return decode
