"""Stream SPI + in-memory stream implementation.

Analog of the reference's pluggable stream abstraction
(`pinot-spi/src/main/java/org/apache/pinot/spi/stream/`: `PartitionGroupConsumer`,
`StreamConsumerFactory`, `StreamPartitionMsgOffset`, `MessageBatch`,
`StreamMessageDecoder`, `StreamMetadataProvider`). Offsets are opaque comparables
serialized as strings, exactly like the reference, so a Kafka-protocol consumer plugs in
without touching the consumption FSM. `MemoryStream` plays the role of the embedded
Kafka the reference uses in tests (`KafkaDataServerStartable`).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class StreamMessage:
    value: Any
    offset: int
    key: Optional[str] = None
    timestamp_ms: int = 0


@dataclass
class MessageBatch:
    messages: List[StreamMessage]
    next_offset: int                 # offset to resume from

    def __len__(self):
        return len(self.messages)


class PartitionGroupConsumer:
    """Fetch interface for one partition (reference: PartitionGroupConsumer)."""

    def fetch(self, start_offset: int, max_messages: int, timeout_ms: int = 0) -> MessageBatch:
        raise NotImplementedError

    def latest_offset(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamMetadataProvider:
    def partition_count(self, topic: str) -> int:
        raise NotImplementedError

    def earliest_offset(self, topic: str, partition: int) -> int:
        return 0


class StreamConsumerFactory:
    """Reference: StreamConsumerFactory — one per stream plugin type."""

    def create_consumer(self, topic: str, partition: int) -> PartitionGroupConsumer:
        raise NotImplementedError

    def metadata_provider(self) -> StreamMetadataProvider:
        raise NotImplementedError


# -- in-memory stream --------------------------------------------------------

class MemoryStream:
    """In-process partitioned topic store shared by producers and consumers."""

    _topics: Dict[str, "MemoryStream"] = {}
    _lock = threading.RLock()

    def __init__(self, topic: str, num_partitions: int):
        self.topic = topic
        self.num_partitions = num_partitions
        self.partitions: List[List[StreamMessage]] = [[] for _ in range(num_partitions)]
        self._plock = threading.RLock()

    @classmethod
    def create(cls, topic: str, num_partitions: int) -> "MemoryStream":
        with cls._lock:
            if topic not in cls._topics:
                cls._topics[topic] = MemoryStream(topic, num_partitions)
            return cls._topics[topic]

    @classmethod
    def get(cls, topic: str) -> "MemoryStream":
        with cls._lock:
            if topic not in cls._topics:
                raise KeyError(f"unknown topic {topic!r}")
            return cls._topics[topic]

    @classmethod
    def reset_all(cls) -> None:
        with cls._lock:
            cls._topics.clear()

    def produce(self, value: Any, partition: Optional[int] = None,
                key: Optional[str] = None) -> int:
        with self._plock:
            if partition is None:
                partition = (hash(key) if key is not None else
                             sum(len(p) for p in self.partitions)) % self.num_partitions
            plist = self.partitions[partition]
            msg = StreamMessage(value=value, offset=len(plist), key=key)
            plist.append(msg)
            return msg.offset


class MemoryStreamConsumer(PartitionGroupConsumer):
    def __init__(self, topic: str, partition: int):
        self.stream = MemoryStream.get(topic)
        self.partition = partition

    def fetch(self, start_offset: int, max_messages: int, timeout_ms: int = 0) -> MessageBatch:
        with self.stream._plock:
            msgs = self.stream.partitions[self.partition][
                start_offset:start_offset + max_messages]
        return MessageBatch(list(msgs), start_offset + len(msgs))

    def latest_offset(self) -> int:
        with self.stream._plock:
            return len(self.stream.partitions[self.partition])


class MemoryStreamFactory(StreamConsumerFactory):
    def __init__(self, topic: str, properties: Optional[Dict[str, Any]] = None):
        self.topic = topic

    def create_consumer(self, topic: str, partition: int) -> PartitionGroupConsumer:
        return MemoryStreamConsumer(topic, partition)

    def metadata_provider(self) -> StreamMetadataProvider:
        factory = self

        class _Meta(StreamMetadataProvider):
            def partition_count(self, topic: str) -> int:
                return MemoryStream.get(topic or factory.topic).num_partitions

        return _Meta()


# -- decoders (reference: StreamMessageDecoder SPI) --------------------------

def json_decoder(value: Any) -> Dict[str, Any]:
    if isinstance(value, (bytes, str)):
        return json.loads(value)
    return dict(value)


def passthrough_decoder(value: Any) -> Dict[str, Any]:
    return value


def avro_decoder(value: Any) -> Dict[str, Any]:
    """Confluent-framed avro message -> row dict (schema id resolved against
    the process-local registry; see ingest/avro.py)."""
    from .avro import confluent_avro_decoder   # lazy
    return confluent_avro_decoder(value)


def columnar_decoder(value: Any) -> Dict[str, Any]:
    """Per-row decode is undefined for columnar block streams (one message =
    many rows) — consumers use the block decoder (`get_block_decoder`); this
    entry only keeps `get_decoder("columnar")` resolvable so stream configs
    validate uniformly."""
    raise ValueError("columnar block streams decode whole blocks; "
                     "per-row decode is not supported")


_DECODERS: Dict[str, Callable[[Any], Dict[str, Any]]] = {
    "json": json_decoder,
    "dict": passthrough_decoder,
    "avro": avro_decoder,
    "columnar": columnar_decoder,
}

_FACTORIES: Dict[str, Callable[[str], StreamConsumerFactory]] = {
    "memory": MemoryStreamFactory,
}


def _json_batch_decoder(values) -> List[Dict[str, Any]]:
    """Decode a WHOLE batch of JSON payloads with ONE C-level parse by
    splicing them into a JSON array — per-message json.loads costs more in
    call overhead than in parsing at realtime consume rates. Raises on any
    malformed member (callers fall back to the per-message decoder, which
    also isolates WHICH message was bad)."""
    import json as _json
    parts = [v if isinstance(v, bytes) else str(v).encode("utf-8")
             for v in values]
    return _json.loads(b"[" + b",".join(parts) + b"]")


#: SPLICED protocol: a batch decoder with a `spliced` attribute
#: (prefix, sep, suffix, parse) can consume values pre-joined by the
#: transport (kafkalite's native C splicer) — the whole fetch decodes with
#: ONE parse call and zero per-record Python objects
_json_batch_decoder.spliced = (b"[", b",", b"]", json.loads)

#: batch decoders: name -> (List[raw value] -> List[row dict]); optional
#: fast path next to _DECODERS — consumers with `fetch_raw` + a registered
#: batch decoder skip per-message object/str materialization entirely
_BATCH_DECODERS: Dict[str, Callable[[List[Any]], List[Dict[str, Any]]]] = {
    "json": _json_batch_decoder,
}


def get_batch_decoder(name: str):
    return _BATCH_DECODERS.get(name)


#: block decoders: name -> object with `sep` (1-byte transport splice
#: separator), `decode_spliced(data, n_msgs) -> List[ColumnarBatch]` and
#: `decode_one(value) -> ColumnarBatch`. One stream message carries a whole
#: columnar block of rows — the vectorized ingest plane's wire format
#: (ingest/vectorized.py); decoded batches feed `index_arrays` directly.
_BLOCK_DECODERS: Dict[str, Any] = {}


def register_block_decoder(name: str, decoder: Any) -> None:
    _BLOCK_DECODERS[name] = decoder


def get_block_decoder(name: str):
    if name not in _BLOCK_DECODERS and name == "columnar":
        from .vectorized import ColumnarBlockDecoder   # lazy builtin
        _BLOCK_DECODERS[name] = ColumnarBlockDecoder()
    return _BLOCK_DECODERS.get(name)


def register_batch_decoder(name: str,
                           fn: Callable[[List[Any]], List[Dict[str, Any]]]
                           ) -> None:
    _BATCH_DECODERS[name] = fn


def register_decoder(name: str, fn: Callable[[Any], Dict[str, Any]]) -> None:
    _DECODERS[name] = fn


def register_stream_factory(name: str, factory: Callable[[str], StreamConsumerFactory]) -> None:
    """Plugin hook (reference: stream type -> factory class name in stream configs)."""
    _FACTORIES[name] = factory


def get_decoder(name: str) -> Callable[[Any], Dict[str, Any]]:
    return _DECODERS[name]


def get_stream_factory(stream_type: str, topic: str,
                       properties: Optional[Dict[str, Any]] = None
                       ) -> StreamConsumerFactory:
    """Instantiate a stream plugin factory; `properties` carries plugin-specific
    connection config (reference: the stream.* keys of StreamConfig, e.g. Kafka
    bootstrap servers). `kafkalite` (socket log broker) registers lazily."""
    if stream_type not in _FACTORIES:
        # lazily-registered builtins live in ONE list (plugins._BUILTIN_MODULES)
        from .. import plugins
        plugins._ensure_builtins()
    return _FACTORIES[stream_type](topic, properties)
