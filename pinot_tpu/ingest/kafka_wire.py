"""Kafka binary wire protocol: primitives, record batch v2, and the five APIs
the log broker speaks (ApiVersions / Metadata / ListOffsets / Fetch / Produce,
plus CreateTopics for admin).

Implemented from the Kafka protocol specification (non-flexible versions —
fixed-width header, no tagged fields): request frames are
`int32 length | int16 api_key | int16 api_version | int32 correlation_id |
nullable_string client_id | body`; responses are
`int32 length | int32 correlation_id | body`. Record batches are the v2
(magic=2) on-disk format with CRC-32C over attributes..records and
zigzag-varint record fields — byte-compatible with what a stock Kafka client
produces and consumes (reference consumer being replaced:
`pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/.../KafkaPartitionLevelConsumer.java`).

This module is pure encode/decode — no sockets; `kafkalite.py` owns transport.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

# api keys (Kafka protocol numbers)
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_API_VERSIONS = 18
API_CREATE_TOPICS = 19

# error codes
ERR_NONE = 0
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_CORRUPT_MESSAGE = 2
ERR_UNSUPPORTED_VERSION = 35

# supported version ranges advertised through ApiVersions
SUPPORTED = {
    API_PRODUCE: (3, 3),
    API_FETCH: (4, 4),
    API_LIST_OFFSETS: (1, 1),
    API_METADATA: (0, 1),
    API_API_VERSIONS: (0, 0),
    API_CREATE_TOPICS: (0, 0),
}

LATEST_TS = -1   # ListOffsets timestamp sentinel: latest
EARLIEST_TS = -2


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("kafka frame truncated")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> str:
        return self._take(self.i16()).decode("utf-8")

    def nullable_string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self._take(n).decode("utf-8")

    def bytes32(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self._take(n)

    def array(self, item_fn) -> Optional[list]:
        n = self.i32()
        if n < 0:
            return None
        return [item_fn() for _ in range(n)]

    def uvarint(self) -> int:
        shift = out = 0
        while True:
            b = self._take(1)[0]
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def varint(self) -> int:
        u = self.uvarint()
        return (u >> 1) ^ -(u & 1)  # zigzag decode


def i8(v: int) -> bytes:
    return struct.pack(">b", v)


def i16(v: int) -> bytes:
    return struct.pack(">h", v)


def i32(v: int) -> bytes:
    return struct.pack(">i", v)


def i64(v: int) -> bytes:
    return struct.pack(">q", v)


def u32(v: int) -> bytes:
    return struct.pack(">I", v)


def string(s: str) -> bytes:
    raw = s.encode("utf-8")
    return i16(len(raw)) + raw


def nullable_string(s: Optional[str]) -> bytes:
    return i16(-1) if s is None else string(s)


def bytes32(b: Optional[bytes]) -> bytes:
    return i32(-1) if b is None else i32(len(b)) + b


def array(items: Optional[List[bytes]]) -> bytes:
    if items is None:
        return i32(-1)
    return i32(len(items)) + b"".join(items)


def uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def varint(v: int) -> bytes:
    return uvarint((v << 1) ^ (v >> 63))  # zigzag encode (64-bit domain)


# CRC-32C (Castagnoli), reflected, poly 0x1EDC6F41 — Kafka batch checksums use
# this, NOT zlib's CRC-32 (IEEE). Table-driven; the standard check vector
# crc32c(b"123456789") == 0xE3069283 is asserted in tests.
_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C; native C fast path (~GB/s — the pure-Python walk bottlenecked
    the realtime consume rate), byte-identical fallback otherwise."""
    from ..native import crc32c as _native
    out = _native(bytes(data), crc)
    return _crc32c_py(data, crc) if out is None else out


# ---------------------------------------------------------------------------
# record batch v2 (magic = 2)
# ---------------------------------------------------------------------------

def encode_record_batch(base_offset: int,
                        records: List[Tuple[Optional[bytes], bytes, int]]) -> bytes:
    """records = [(key|None, value, timestamp_ms)] -> one v2 batch."""
    if not records:
        return b""
    first_ts = records[0][2]
    max_ts = max(r[2] for r in records)
    recs = bytearray()
    for idx, (key, value, ts) in enumerate(records):
        body = (i8(0)                          # record attributes
                + varint(ts - first_ts)        # timestampDelta
                + varint(idx)                  # offsetDelta
                + (varint(-1) if key is None
                   else varint(len(key)) + key)
                + varint(len(value)) + value
                + uvarint(0))                  # headers
        recs += varint(len(body)) + body
    crc_part = (i16(0)                          # batch attributes (no compression)
                + i32(len(records) - 1)         # lastOffsetDelta
                + i64(first_ts) + i64(max_ts)
                + i64(-1) + i16(-1) + i32(-1)   # producerId/epoch/baseSequence
                + i32(len(records)) + bytes(recs))
    inner = (i32(-1)                            # partitionLeaderEpoch
             + i8(2)                            # magic
             + u32(crc32c(crc_part)) + crc_part)
    return i64(base_offset) + i32(len(inner)) + inner


def splice_record_batches(data: bytes, min_offset: int, sep: bytes = b",",
                          max_records: int = 1 << 62):
    """All batches in a record set -> (values spliced with `sep`, count,
    last_offset) with CRC verification, or None when the native splicer is
    unavailable (callers use `decode_record_batches`). Zero per-record
    Python objects: each batch's value section splices in C and the caller
    runs ONE batch parse over the joined payload. `max_records` caps the
    TOTAL spliced count — consume catch-up targets depend on the limit
    being honored, not approximated."""
    from ..native import splice_values as _native_splice
    parts: List[bytes] = []
    total = 0
    last_offset = -1
    r = Reader(data)
    while r.pos + 12 <= len(r.data):
        base_offset = r.i64()
        batch_len = r.i32()
        if r.pos + batch_len > len(r.data):
            break  # partial trailing batch (Kafka allows truncated tails)
        # read the header in place (no per-batch body copy: batches are
        # multi-MB on the consume hot path); `rest` below is the single
        # slice shared by the CRC check and the native splice
        start, end = r.pos, r.pos + batch_len
        r.pos = end
        body = Reader(data)
        body.pos = start
        body.i32()                      # partitionLeaderEpoch
        magic = body.i8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc = body.u32()
        rest = data[body.pos:end]
        if crc32c(rest) != crc:
            raise ValueError("record batch CRC mismatch")
        count = struct.unpack(">i", rest[36:40])[0]
        if total >= max_records:
            break
        spliced = _native_splice(rest[40:], base_offset,
                                 min(count, max_records - total),
                                 min_offset, sep)
        if spliced is None:
            return None
        chunk, n, last = spliced
        if n:
            parts.append(chunk)
            total += n
            last_offset = max(last_offset, last)
    return sep.join(parts), total, last_offset


def decode_record_batches(data: bytes) -> List[Tuple[int, int, Optional[bytes], bytes]]:
    """All batches in a record set -> [(offset, timestamp_ms, key, value)]."""
    out: List[Tuple[int, int, Optional[bytes], bytes]] = []
    r = Reader(data)
    while r.pos + 12 <= len(r.data):
        base_offset = r.i64()
        batch_len = r.i32()
        if r.pos + batch_len > len(r.data):
            break  # partial trailing batch (Kafka allows truncated tails)
        body = Reader(r._take(batch_len))
        body.i32()                      # partitionLeaderEpoch
        magic = body.i8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc = body.u32()
        rest = body.data[body.pos:]
        if crc32c(rest) != crc:
            raise ValueError("record batch CRC mismatch")
        body.i16()                      # attributes
        body.i32()                      # lastOffsetDelta
        first_ts = body.i64()
        body.i64()                      # maxTimestamp
        body.i64(); body.i16(); body.i32()  # producer id/epoch/base seq
        count = body.i32()
        # native fast path: the per-record varint walk is the realtime
        # consume hot loop; the C decoder returns byte ranges over the same
        # buffer (falls back below on unavailability/malformed input)
        from ..native import decode_records as _native_decode
        native = _native_decode(body.data[body.pos:], base_offset, first_ts,
                                count)
        if native is not None:
            out.extend(native)
            continue
        for _ in range(count):
            length = body.varint()
            rec = Reader(body._take(length))
            rec.i8()                    # record attributes
            ts_delta = rec.varint()
            off_delta = rec.varint()
            klen = rec.varint()
            key = None if klen < 0 else rec._take(klen)
            vlen = rec.varint()
            value = b"" if vlen < 0 else rec._take(vlen)
            out.append((base_offset + off_delta, first_ts + ts_delta, key, value))
    return out


# ---------------------------------------------------------------------------
# request/response framing
# ---------------------------------------------------------------------------

def encode_request(api_key: int, api_version: int, correlation_id: int,
                   client_id: Optional[str], body: bytes) -> bytes:
    payload = (i16(api_key) + i16(api_version) + i32(correlation_id)
               + nullable_string(client_id) + body)
    return i32(len(payload)) + payload


def decode_request_header(payload: bytes) -> Tuple[int, int, int, Optional[str], Reader]:
    r = Reader(payload)
    return r.i16(), r.i16(), r.i32(), r.nullable_string(), r


def encode_response(correlation_id: int, body: bytes) -> bytes:
    payload = i32(correlation_id) + body
    return i32(len(payload)) + payload


# -- per-API bodies (the versions in SUPPORTED) ------------------------------

def encode_api_versions_response() -> bytes:
    return i16(ERR_NONE) + array(
        [i16(k) + i16(lo) + i16(hi) for k, (lo, hi) in sorted(SUPPORTED.items())])


def encode_metadata_request(topics: Optional[List[str]]) -> bytes:
    return array(None if topics is None else [string(t) for t in topics])


def decode_metadata_request(r: Reader) -> Optional[List[str]]:
    return r.array(r.string)


def encode_metadata_response(version: int, host: str, port: int,
                             topics: Dict[str, int]) -> bytes:
    """One-broker cluster: node 0 is leader of every partition."""
    broker = i32(0) + string(host) + i32(port) + (nullable_string(None)
                                                  if version >= 1 else b"")
    topic_items = []
    for name, n_parts in sorted(topics.items()):
        parts = [i16(ERR_NONE) + i32(p) + i32(0)
                 + array([i32(0)]) + array([i32(0)])
                 for p in range(n_parts)]
        topic_items.append(i16(ERR_NONE) + string(name)
                           + (i8(0) if version >= 1 else b"")  # is_internal
                           + array(parts))
    return (array([broker])
            + (i32(0) if version >= 1 else b"")   # controller_id
            + array(topic_items))


def decode_metadata_response(version: int, r: Reader) -> Dict[str, Any]:
    def broker():
        node, host, port = r.i32(), r.string(), r.i32()
        rack = r.nullable_string() if version >= 1 else None
        return {"node": node, "host": host, "port": port, "rack": rack}
    brokers = r.array(broker)
    controller = r.i32() if version >= 1 else 0

    def topic():
        err, name = r.i16(), r.string()
        internal = bool(r.i8()) if version >= 1 else False

        def part():
            perr, idx, leader = r.i16(), r.i32(), r.i32()
            r.array(r.i32); r.array(r.i32)  # replicas, isr
            return {"error": perr, "partition": idx, "leader": leader}
        return {"error": err, "topic": name, "internal": internal,
                "partitions": r.array(part)}
    return {"brokers": brokers, "controller": controller,
            "topics": r.array(topic)}


def encode_list_offsets_request(topic: str, partition: int, timestamp: int) -> bytes:
    return i32(-1) + array([string(topic) + array([i32(partition) + i64(timestamp)])])


def decode_list_offsets_request(r: Reader) -> List[Tuple[str, int, int]]:
    r.i32()  # replica_id
    out = []

    def topic():
        name = r.string()

        def part():
            out.append((name, r.i32(), r.i64()))
        r.array(part)
    r.array(topic)
    return out


def encode_list_offsets_response(results: List[Tuple[str, int, int, int, int]]) -> bytes:
    """results = [(topic, partition, error, timestamp, offset)] (v1 shape)."""
    by_topic: Dict[str, List[bytes]] = {}
    for topic, part, err, ts, off in results:
        by_topic.setdefault(topic, []).append(i32(part) + i16(err) + i64(ts)
                                              + i64(off))
    return array([string(t) + array(ps) for t, ps in sorted(by_topic.items())])


def decode_list_offsets_response(r: Reader) -> List[Dict[str, Any]]:
    out = []

    def topic():
        name = r.string()

        def part():
            out.append({"topic": name, "partition": r.i32(), "error": r.i16(),
                        "timestamp": r.i64(), "offset": r.i64()})
        r.array(part)
    r.array(topic)
    return out


def encode_fetch_request(topic: str, partition: int, offset: int,
                         max_wait_ms: int, max_bytes: int) -> bytes:
    return (i32(-1) + i32(max_wait_ms) + i32(1) + i32(max_bytes) + i8(0)
            + array([string(topic)
                     + array([i32(partition) + i64(offset) + i32(max_bytes)])]))


def decode_fetch_request(r: Reader) -> Tuple[int, int, List[Tuple[str, int, int, int]]]:
    r.i32()                     # replica_id
    max_wait = r.i32()
    r.i32()                     # min_bytes
    max_bytes = r.i32()
    r.i8()                      # isolation_level
    parts = []

    def topic():
        name = r.string()

        def part():
            parts.append((name, r.i32(), r.i64(), r.i32()))
        r.array(part)
    r.array(topic)
    return max_wait, max_bytes, parts


def encode_fetch_response(
        results: List[Tuple[str, int, int, int, bytes]]) -> bytes:
    """results = [(topic, partition, error, high_watermark, record_set)]."""
    by_topic: Dict[str, List[bytes]] = {}
    for topic, part, err, hw, recs in results:
        by_topic.setdefault(topic, []).append(
            i32(part) + i16(err) + i64(hw) + i64(hw)   # last_stable = hw
            + array([])                                 # aborted transactions
            + bytes32(recs))
    return i32(0) + array([string(t) + array(ps)
                           for t, ps in sorted(by_topic.items())])


def decode_fetch_response(r: Reader, raw_records: bool = False
                          ) -> List[Dict[str, Any]]:
    """`raw_records=True` keeps each partition's record-set BYTES under
    "recordSet" instead of decoding per-record tuples (the splice fast
    path's input)."""
    r.i32()  # throttle
    out = []

    def topic():
        name = r.string()

        def part():
            d = {"topic": name, "partition": r.i32(), "error": r.i16(),
                 "highWatermark": r.i64()}
            r.i64()             # last_stable_offset
            r.array(lambda: (r.i64(), r.i64()))  # aborted txns
            data = r.bytes32() or b""
            if raw_records:
                d["recordSet"] = data
            else:
                d["records"] = decode_record_batches(data)
            out.append(d)
        r.array(part)
    r.array(topic)
    return out


def encode_produce_request(topic: str, partition: int, record_set: bytes,
                           acks: int = -1, timeout_ms: int = 30000) -> bytes:
    return (nullable_string(None) + i16(acks) + i32(timeout_ms)
            + array([string(topic) + array([i32(partition)
                                            + bytes32(record_set)])]))


def decode_produce_request(r: Reader) -> List[Tuple[str, int, bytes]]:
    r.nullable_string()         # transactional_id
    r.i16()                     # acks
    r.i32()                     # timeout
    out = []

    def topic():
        name = r.string()

        def part():
            out.append((name, r.i32(), r.bytes32() or b""))
        r.array(part)
    r.array(topic)
    return out


def encode_produce_response(results: List[Tuple[str, int, int, int]]) -> bytes:
    """results = [(topic, partition, error, base_offset)] (v3 shape)."""
    by_topic: Dict[str, List[bytes]] = {}
    for topic, part, err, off in results:
        by_topic.setdefault(topic, []).append(i32(part) + i16(err) + i64(off)
                                              + i64(-1))  # log_append_time
    return array([string(t) + array(ps)
                  for t, ps in sorted(by_topic.items())]) + i32(0)


def decode_produce_response(r: Reader) -> List[Dict[str, Any]]:
    out = []

    def topic():
        name = r.string()

        def part():
            out.append({"topic": name, "partition": r.i32(), "error": r.i16(),
                        "offset": r.i64(), "logAppendTime": r.i64()})
        r.array(part)
    r.array(topic)
    r.i32()  # throttle
    return out


def encode_create_topics_request(topic: str, num_partitions: int) -> bytes:
    return array([string(topic) + i32(num_partitions) + i16(1)
                  + array([]) + array([])]) + i32(30000)


def decode_create_topics_request(r: Reader) -> List[Tuple[str, int]]:
    out = []

    def topic():
        name = r.string()
        n = r.i32()
        r.i16()                 # replication factor
        r.array(lambda: (r.i32(), r.array(r.i32)))  # assignments
        r.array(lambda: (r.string(), r.nullable_string()))  # configs
        out.append((name, n))
    r.array(topic)
    r.i32()  # timeout
    return out


def encode_create_topics_response(results: List[Tuple[str, int]]) -> bytes:
    return array([string(t) + i16(err) for t, err in results])


def decode_create_topics_response(r: Reader) -> List[Tuple[str, int]]:
    return r.array(lambda: (r.string(), r.i16()))


def decode_api_versions_response(r: Reader) -> Dict[int, Tuple[int, int]]:
    err = r.i16()
    if err:
        raise ValueError(f"ApiVersions error {err}")
    out = {}
    for k, lo, hi in r.array(lambda: (r.i16(), r.i16(), r.i16())):
        out[k] = (lo, hi)
    return out
