"""pinot_tpu: a TPU-native realtime distributed OLAP datastore.

A from-scratch reimplementation of the capabilities of Apache Pinot (reference mounted at
/root/reference) designed TPU-first: columnar segments live in HBM as fixed-width arrays,
the per-segment scan path (decode -> predicate masks -> projection -> group-by -> reduce)
is jax.jit/Pallas compiled, multi-segment combine uses shard_map + ICI collectives, and the
control plane (catalog, routing, ingestion FSMs) is host-side Python. See SURVEY.md.
"""

__version__ = "0.2.0"


def __getattr__(name):
    # lazy top-level conveniences: `pinot_tpu.connect` / `QuickCluster` /
    # `execute_query` without importing jax at package-import time
    if name == "connect":
        from .client import connect
        return connect
    if name == "QuickCluster":
        from .cluster import QuickCluster
        return QuickCluster
    if name == "execute_query":
        from .query.executor import execute_query
        return execute_query
    if name == "Schema":
        from .schema import Schema
        return Schema
    if name == "TableConfig":
        from .table import TableConfig
        return TableConfig
    raise AttributeError(f"module 'pinot_tpu' has no attribute {name!r}")
