"""pinot_tpu: a TPU-native realtime distributed OLAP datastore.

A from-scratch reimplementation of the capabilities of Apache Pinot (reference mounted at
/root/reference) designed TPU-first: columnar segments live in HBM as fixed-width arrays,
the per-segment scan path (decode -> predicate masks -> projection -> group-by -> reduce)
is jax.jit/Pallas compiled, multi-segment combine uses shard_map + ICI collectives, and the
control plane (catalog, routing, ingestion FSMs) is host-side Python. See SURVEY.md.
"""

__version__ = "0.1.0"
