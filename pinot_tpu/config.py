"""Layered configuration: defaults < file < environment < explicit overrides.

Analog of the reference's PinotConfiguration
(`pinot-spi/src/main/java/org/apache/pinot/spi/env/PinotConfiguration.java`):
one key space (dotted, case-insensitive) fed from properties/JSON files, the
process environment (`PINOT_TPU_` prefix, `_` doubling as `.`), and in-code
overrides — the same precedence order the reference applies (explicit args >
env > files > defaults). Role starters consume `subset("pinot.server.")`-style
views, mirroring the reference's per-component config slicing.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Mapping, Optional

ENV_PREFIX = "PINOT_TPU_"


def _norm(key: str) -> str:
    return key.strip().lower()


def _env_key_to_config(key: str) -> str:
    """PINOT_TPU_SERVER_QUERY_TIMEOUT -> server.query.timeout."""
    return key[len(ENV_PREFIX):].lower().replace("__", "-").replace("_", ".")


class Configuration:
    """Immutable layered key-value view; later layers win."""

    def __init__(self, *layers: Mapping[str, Any]):
        merged: Dict[str, Any] = {}
        for layer in layers:
            for k, v in layer.items():
                merged[_norm(k)] = v
        self._data = merged

    # -- constructors ------------------------------------------------------
    @staticmethod
    def load(path: Optional[str] = None,
             defaults: Optional[Mapping[str, Any]] = None,
             overrides: Optional[Mapping[str, Any]] = None,
             env: Optional[Mapping[str, str]] = None) -> "Configuration":
        """The standard stack: defaults < file < environment < overrides."""
        layers: List[Mapping[str, Any]] = [defaults or {}]
        if path:
            layers.append(read_config_file(path))
        environ = os.environ if env is None else env
        layers.append({_env_key_to_config(k): v for k, v in environ.items()
                       if k.startswith(ENV_PREFIX)})
        layers.append(overrides or {})
        return Configuration(*layers)

    # -- typed getters -----------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(_norm(key), default)

    def get_str(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self.get(key, default)
        return None if v is None else str(v)

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        v = self.get(key)
        return default if v is None or v == "" else int(v)

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        v = self.get(key)
        return default if v is None or v == "" else float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None or v == "":
            return default
        if isinstance(v, bool):
            return v
        return str(v).strip().lower() in ("true", "1", "yes", "on")

    def get_list(self, key: str, default: Optional[List[str]] = None) -> List[str]:
        v = self.get(key)
        if v is None or v == "":
            return list(default or [])
        if isinstance(v, (list, tuple)):
            return [str(x) for x in v]
        return [s.strip() for s in str(v).split(",") if s.strip()]

    # -- views -------------------------------------------------------------
    def subset(self, prefix: str) -> "Configuration":
        """Keys under `prefix` with the prefix stripped (reference:
        PinotConfiguration.subset)."""
        p = _norm(prefix)
        if not p.endswith("."):
            p += "."
        return Configuration({k[len(p):]: v for k, v in self._data.items()
                              if k.startswith(p)})

    def with_overrides(self, overrides: Mapping[str, Any]) -> "Configuration":
        return Configuration(self._data, overrides)

    def keys(self) -> List[str]:
        return sorted(self._data)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    def __contains__(self, key: str) -> bool:
        return _norm(key) in self._data

    def __repr__(self) -> str:
        return f"Configuration({len(self._data)} keys)"


def read_config_file(path: str) -> Dict[str, Any]:
    """JSON (nested dicts flatten to dotted keys) or .properties (key=value)."""
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return _flatten(json.loads(text))
    out: Dict[str, Any] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith(("#", "!")):
            continue
        if "=" not in line:
            raise ValueError(f"bad properties line in {path}: {line!r}")
        k, v = line.split("=", 1)
        out[_norm(k)] = v.strip()
    return out


def _flatten(d: Mapping[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, Mapping):
            out.update(_flatten(v, key))
        else:
            out[_norm(key)] = v
    return out
