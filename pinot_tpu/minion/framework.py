"""Segment processing framework: partition -> merge/rollup/dedup -> build.

Analog of the reference's `SegmentProcessorFramework`
(`pinot-core/src/main/java/org/apache/pinot/core/segment/processing/framework/
SegmentProcessorFramework.java`: mappers partition records by time bucket, reducers
CONCAT / ROLLUP / DEDUP them, and a segment creator splits output rows into bounded
segments). The row pipeline here is columnar numpy end-to-end — partitioning is a
vectorized bucket computation, rollup is the same dense factorize + per-group ufunc
reduction the host group-by engine uses — instead of the reference's row-at-a-time
`GenericRow` mappers; background compaction is host ETL work, so it stays off the TPU
and never competes with the query path for the chip.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..schema import Schema
from ..segment.writer import SegmentBuilder, SegmentGeneratorConfig

CONCAT = "CONCAT"
ROLLUP = "ROLLUP"
DEDUP = "DEDUP"


@dataclass
class ProcessorConfig:
    """Reference: SegmentProcessorConfig (merge type, time handling, partitioning,
    segment config)."""
    merge_type: str = CONCAT                    # CONCAT | ROLLUP | DEDUP
    time_column: Optional[str] = None
    bucket_ms: Optional[int] = None             # output partitioning granularity
    round_time_to: Optional[int] = None         # truncate time values before rollup
    window_start: Optional[int] = None          # keep only rows in [start, end)
    window_end: Optional[int] = None
    max_rows_per_segment: int = 5_000_000
    segment_prefix: str = "merged"
    # metric column -> sum | min | max (ROLLUP; unlisted metrics default to sum)
    aggregations: Dict[str, str] = field(default_factory=dict)
    generator_config: SegmentGeneratorConfig = field(default_factory=SegmentGeneratorConfig)


def read_columns(segment, schema: Schema) -> Dict[str, np.ndarray]:
    """Decode one segment into a column dict (object arrays for strings).

    Null positions come back as None cells: `.values()` materializes the
    default-value fill, so without consulting the null bitmap every rewrite
    task (merge/rollup, raw-index convert, ...) would silently drop nullness
    and IS NULL queries against the rewritten segment would go empty."""
    out = {}
    for f in schema.fields:
        reader = segment.column(f.name)
        vals = np.asarray(reader.values())
        bitmap = reader.null_bitmap
        if bitmap is not None and not reader.is_multi_value and bitmap.any():
            if vals.dtype != object:
                vals = vals.astype(object)
            else:
                vals = vals.copy()
            vals[np.asarray(bitmap, dtype=bool)] = None
        out[f.name] = vals
    return out


def concat_columns(parts: Sequence[Dict[str, np.ndarray]], schema: Schema
                   ) -> Dict[str, np.ndarray]:
    return {f.name: np.concatenate([p[f.name] for p in parts]) for f in schema.fields}


def _take(cols: Dict[str, np.ndarray], idx: np.ndarray) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in cols.items()}


def _rollup(cols: Dict[str, np.ndarray], schema: Schema,
            aggregations: Dict[str, str]) -> Dict[str, np.ndarray]:
    """Aggregate metric columns over rows with identical dimension+time values.

    Reference: `RollupReducer` — here one dense combined key per row (factorize each
    key column, mixed-radix combine) and vectorized per-group reductions.
    """
    from ..query.executor import _factorize_keys

    metric_cols = set(schema.metric_columns)
    key_cols = [f.name for f in schema.fields if f.name not in metric_cols]
    if not key_cols:
        key_cols = [f.name for f in schema.fields][:1]
    n = len(next(iter(cols.values())))
    combined = np.zeros(n, dtype=np.int64)
    stride = 1
    codes_values = []
    for c in key_cols:
        codes, values = _factorize_keys(cols[c])
        combined += codes * stride
        codes_values.append((c, codes, values))
        stride *= max(len(values), 1)
    uniq, inverse = np.unique(combined, return_inverse=True)
    # first occurrence per group carries the key values through unchanged
    first_row = np.full(len(uniq), n, dtype=np.int64)
    np.minimum.at(first_row, inverse, np.arange(n))
    out: Dict[str, np.ndarray] = {}
    for c in key_cols:
        out[c] = cols[c][first_row]
    for c in metric_cols:
        agg = aggregations.get(c, "sum")
        v = cols[c]
        if v.dtype == object:
            # nulls restored by read_columns: a null metric contributes the
            # aggregation identity instead of poisoning the whole group
            ident = {"sum": 0, "min": np.inf, "max": -np.inf}.get(agg, 0)
            v = np.asarray([ident if x is None else x for x in v])
        if agg == "sum":
            acc = np.zeros(len(uniq), dtype=np.float64 if v.dtype.kind == "f" else np.int64)
            np.add.at(acc, inverse, v)
        elif agg == "min":
            acc = np.full(len(uniq), np.inf if v.dtype.kind == "f" else np.iinfo(np.int64).max,
                          dtype=np.float64 if v.dtype.kind == "f" else np.int64)
            np.minimum.at(acc, inverse, v)
        elif agg == "max":
            acc = np.full(len(uniq), -np.inf if v.dtype.kind == "f" else np.iinfo(np.int64).min,
                          dtype=np.float64 if v.dtype.kind == "f" else np.int64)
            np.maximum.at(acc, inverse, v)
        else:
            raise ValueError(f"unsupported rollup aggregation {agg!r} for {c}")
        out[c] = acc.astype(v.dtype) if v.dtype.kind != "f" else acc
    return out


def _dedup(cols: Dict[str, np.ndarray], schema: Schema) -> Dict[str, np.ndarray]:
    """Drop rows whose FULL column tuple repeats (reference: DedupReducer)."""
    from ..query.executor import _factorize_keys
    names = [f.name for f in schema.fields]
    n = len(next(iter(cols.values())))
    combined = np.zeros(n, dtype=np.int64)
    stride = 1
    for c in names:
        codes, values = _factorize_keys(cols[c])
        combined += codes * stride
        stride *= max(len(values), 1)
    _, first = np.unique(combined, return_index=True)
    return _take(cols, np.sort(first))


def process_segments(segments: Sequence, schema: Schema, config: ProcessorConfig,
                     out_dir: str, start_seq: int = 0) -> List[str]:
    """Run the full pipeline over loaded segments; returns built segment dirs.

    Mirrors SegmentProcessorFramework.process(): map (time window filter + time
    rounding + bucket partition) -> reduce (concat/rollup/dedup per bucket) ->
    segment creation (bounded rows, names `{prefix}_{seq}`).
    """
    cols = concat_columns([read_columns(s, schema) for s in segments], schema)
    n = len(next(iter(cols.values()))) if cols else 0
    if n == 0:
        return []

    tc = config.time_column
    if tc and (config.window_start is not None or config.window_end is not None):
        t = cols[tc].astype(np.int64)
        keep = np.ones(n, dtype=bool)
        if config.window_start is not None:
            keep &= t >= config.window_start
        if config.window_end is not None:
            keep &= t < config.window_end
        cols = _take(cols, np.nonzero(keep)[0])
        n = int(keep.sum())
        if n == 0:
            return []
    if tc and config.round_time_to:
        t = cols[tc].astype(np.int64)
        cols[tc] = ((t // config.round_time_to) * config.round_time_to).astype(cols[tc].dtype)

    # -- partition into time buckets (mapper output partitions) -------------
    if tc and config.bucket_ms:
        t = cols[tc].astype(np.int64)
        bucket_ids = t // config.bucket_ms
        buckets = [(_take(cols, np.nonzero(bucket_ids == b)[0]))
                   for b in np.unique(bucket_ids)]
    else:
        buckets = [cols]

    # -- reduce + build ------------------------------------------------------
    os.makedirs(out_dir, exist_ok=True)
    built: List[str] = []
    seq = start_seq
    builder = SegmentBuilder(schema, config.generator_config)
    for bucket_cols in buckets:
        if config.merge_type == ROLLUP:
            bucket_cols = _rollup(bucket_cols, schema, config.aggregations)
        elif config.merge_type == DEDUP:
            bucket_cols = _dedup(bucket_cols, schema)
        rows = len(next(iter(bucket_cols.values())))
        for lo in range(0, rows, config.max_rows_per_segment):
            chunk = _take(bucket_cols, np.arange(lo, min(lo + config.max_rows_per_segment,
                                                         rows)))
            name = f"{config.segment_prefix}_{seq}"
            seq += 1
            built.append(builder.build(chunk, out_dir, name))
    return built
