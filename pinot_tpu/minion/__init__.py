"""Minion: background segment maintenance (reference: pinot-minion + the
segment-processing framework in pinot-core).

`framework.py` is the data path (map/partition -> reduce/rollup -> segment build);
`tasks.py` is the control path (task generation on the controller, a task queue in the
catalog, minion workers executing registered task types).
"""

from .framework import ProcessorConfig, process_segments
from .tasks import (MergeRollupTaskGenerator, MinionWorker, PinotTaskManager,
                    RealtimeToOfflineTaskGenerator, TaskQueue, TaskSpec)

__all__ = ["ProcessorConfig", "process_segments", "TaskQueue", "TaskSpec",
           "PinotTaskManager", "MinionWorker", "MergeRollupTaskGenerator",
           "RealtimeToOfflineTaskGenerator"]
