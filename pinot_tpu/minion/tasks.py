"""Minion task framework: generation (controller) -> queue (catalog) -> execution.

Analog of the reference's task pipeline (SURVEY.md §2.8): `PinotTaskManager` runs task
generators per table config (`pinot-controller/.../helix/core/minion/PinotTaskManager.java`),
Helix's task framework queues them, and minion workers execute registered
`PinotTaskExecutor`s (`pinot-minion/.../taskfactory/TaskFactoryRegistry.java`). Here the
queue is a catalog property (the ZK analog), claims are atomic under the catalog lock,
and executors run in `MinionWorker.run_once()` — deterministic for tests, loopable for
production.

Built-in tasks:
* MergeRollupTask      — merge a time bucket's small segments into bigger ones,
  optionally rolling up metrics (`.../mergerollup/MergeRollupTaskExecutor.java`)
* RealtimeToOfflineSegmentsTask — move committed realtime data into the OFFLINE half
  of a hybrid table, window by window (`.../realtimetoofflinesegments/...Executor.java`)
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..table import TableConfig, TableType
from .framework import CONCAT, ProcessorConfig, process_segments

TASKS_KEY = "minionTasks"

GENERATED = "GENERATED"
RUNNING = "RUNNING"
COMPLETED = "COMPLETED"
ERROR = "ERROR"

MERGE_ROLLUP = "MergeRollupTask"
REALTIME_TO_OFFLINE = "RealtimeToOfflineSegmentsTask"
PURGE = "PurgeTask"
SEGMENT_GENERATION_AND_PUSH = "SegmentGenerationAndPushTask"
CONVERT_TO_RAW_INDEX = "ConvertToRawIndexTask"


@dataclass
class TaskSpec:
    """One unit of minion work (reference: PinotTaskConfig)."""
    task_id: str
    task_type: str
    table: str
    config: Dict[str, Any] = field(default_factory=dict)
    state: str = GENERATED
    worker: str = ""
    error: str = ""
    finished_ms: int = 0
    claimed_ms: int = 0  # lease start; stale RUNNING tasks get requeued (gc)

    def to_json(self):
        return dict(self.__dict__)

    @staticmethod
    def from_json(d):
        return TaskSpec(**d)


class TaskQueue:
    """Task queue in the catalog property store (the Helix task-queue analog)."""

    def __init__(self, catalog):
        self.catalog = catalog

    def _all(self) -> Dict[str, Dict]:
        return self.catalog.get_property(TASKS_KEY, {}) or {}

    def submit(self, spec: TaskSpec) -> None:
        def mutate(tasks):
            tasks = dict(tasks or {})
            tasks[spec.task_id] = spec.to_json()
            return tasks
        self.catalog.mutate_property(TASKS_KEY, mutate)

    def claim(self, worker_id: str, task_types: List[str]) -> Optional[TaskSpec]:
        """Atomically claim the oldest GENERATED task of a supported type."""
        claimed: List[TaskSpec] = []

        def mutate(tasks):
            tasks = dict(tasks or {})
            for tid in sorted(tasks):
                t = tasks[tid]
                if t["state"] == GENERATED and t["task_type"] in task_types:
                    t = dict(t, state=RUNNING, worker=worker_id,
                             claimed_ms=int(time.time() * 1000))
                    tasks[tid] = t
                    claimed.append(TaskSpec.from_json(t))
                    break
            return tasks
        self.catalog.mutate_property(TASKS_KEY, mutate)
        return claimed[0] if claimed else None

    def finish(self, task_id: str, error: str = "",
               worker_id: Optional[str] = None) -> bool:
        """Mark a task terminal. With `worker_id`, the write is FENCED: it applies
        only while this worker still holds the claim — a lease-expired task that was
        requeued/re-claimed ignores the stale worker's completion."""
        applied = []

        def mutate(tasks):
            tasks = dict(tasks or {})
            t = tasks.get(task_id)
            if t is not None and (worker_id is None
                                  or (t["state"] == RUNNING
                                      and t["worker"] == worker_id)):
                tasks[task_id] = dict(t, state=ERROR if error else COMPLETED,
                                      error=error,
                                      finished_ms=int(time.time() * 1000))
                applied.append(True)
            return tasks
        self.catalog.mutate_property(TASKS_KEY, mutate)
        return bool(applied)

    def tasks(self, table: Optional[str] = None,
              task_type: Optional[str] = None) -> List[TaskSpec]:
        out = [TaskSpec.from_json(t) for t in self._all().values()]
        if table is not None:
            out = [t for t in out if t.table == table]
        if task_type is not None:
            out = [t for t in out if t.task_type == task_type]
        return sorted(out, key=lambda t: t.task_id)

    def has_pending(self, table: str, task_type: str) -> bool:
        return any(t.state in (GENERATED, RUNNING)
                   for t in self.tasks(table, task_type))

    def in_error_backoff(self, table: str, task_type: str,
                         backoff_ms: int = 300_000,
                         now_ms: Optional[int] = None) -> bool:
        """True while the most recent task of this type failed recently — generators
        wait out the backoff instead of re-queueing a failing task every tick."""
        now_ms = now_ms or int(time.time() * 1000)
        recent = [t for t in self.tasks(table, task_type) if t.finished_ms]
        if not recent:
            return False
        last = max(recent, key=lambda t: t.finished_ms)
        return last.state == ERROR and now_ms - last.finished_ms < backoff_ms

    def gc(self, max_age_ms: int = 3600_000, keep: int = 100,
           lease_ms: int = 600_000, now_ms: Optional[int] = None) -> int:
        """Drop old terminal tasks so the property (shipped in every catalog
        snapshot) stays bounded, and requeue RUNNING tasks whose lease expired —
        a worker that died mid-task must not block generation forever. Returns how
        many entries were removed."""
        now_ms = now_ms or int(time.time() * 1000)
        removed = []

        def mutate(tasks):
            tasks = dict(tasks or {})
            for tid, t in tasks.items():
                if t["state"] != RUNNING:
                    continue
                claimed = t.get("claimed_ms", 0)
                if not claimed:
                    # legacy entry without a lease stamp: start its lease now
                    # rather than treating it as infinitely stale
                    tasks[tid] = dict(t, claimed_ms=now_ms)
                elif now_ms - claimed > lease_ms:
                    tasks[tid] = dict(t, state=GENERATED, worker="", claimed_ms=0)
            terminal = sorted(
                (tid for tid, t in tasks.items()
                 if t["state"] in (COMPLETED, ERROR)),
                key=lambda tid: tasks[tid].get("finished_ms", 0), reverse=True)
            for tid in terminal[keep:]:
                removed.append(tasks.pop(tid))
            for tid in terminal[:keep]:
                if now_ms - tasks[tid].get("finished_ms", 0) > max_age_ms:
                    removed.append(tasks.pop(tid))
            return tasks or None
        self.catalog.mutate_property(TASKS_KEY, mutate)
        return len(removed)


# ---------------------------------------------------------------------------
# Task generation (controller side)
# ---------------------------------------------------------------------------

class TaskGenerator:
    """SPI (reference: PinotTaskGenerator). One instance per task type."""

    task_type = ""

    def generate(self, catalog, cfg: TableConfig, queue: TaskQueue) -> List[TaskSpec]:
        raise NotImplementedError


def _mergeable_segments(catalog, table: str, bucket_ms: int, now_ms: int,
                        buffer_ms: int) -> Dict[int, List]:
    """Completed segments grouped by CLOSED time bucket, excluding merge outputs."""
    from ..cluster.catalog import STATUS_DONE, STATUS_UPLOADED
    out: Dict[int, List] = {}
    for name, meta in catalog.segments.get(table, {}).items():
        if meta.status not in (STATUS_DONE, STATUS_UPLOADED):
            continue  # consuming segments are not merge inputs
        if meta.start_time_ms is None or meta.end_time_ms is None:
            continue
        if meta.custom.get("task") == MERGE_ROLLUP:
            continue  # single merge level: don't re-merge outputs
        lo_b, hi_b = meta.start_time_ms // bucket_ms, meta.end_time_ms // bucket_ms
        if lo_b != hi_b:
            continue  # spans buckets: already bucket-sized or bigger
        if (lo_b + 1) * bucket_ms > now_ms - buffer_ms:
            continue  # bucket not closed yet
        out.setdefault(int(lo_b), []).append(meta)
    return out


class MergeRollupTaskGenerator(TaskGenerator):
    """Reference: MergeRollupTaskGenerator — one task per closed time bucket holding
    more than one un-merged segment."""

    task_type = MERGE_ROLLUP

    def generate(self, catalog, cfg: TableConfig, queue: TaskQueue) -> List[TaskSpec]:
        tcfg = cfg.task_configs.get(self.task_type)
        table = cfg.table_name_with_type
        if tcfg is None or not cfg.time_column:
            return []
        if queue.has_pending(table, self.task_type) \
                or queue.in_error_backoff(table, self.task_type):
            return []  # one in-flight task per table (reference: same guard)
        bucket_ms = int(tcfg.get("bucketMs", 24 * 3600 * 1000))
        buffer_ms = int(tcfg.get("bufferMs", 0))
        now_ms = int(time.time() * 1000)
        specs = []
        for bucket, metas in sorted(_mergeable_segments(
                catalog, table, bucket_ms, now_ms, buffer_ms).items()):
            if len(metas) < 2:
                continue
            specs.append(TaskSpec(
                task_id=f"{self.task_type}_{table}_{bucket}_{uuid.uuid4().hex[:8]}",
                task_type=self.task_type, table=table,
                config={
                    "segments": sorted(m.name for m in metas),
                    "bucketMs": bucket_ms,
                    "mergeType": tcfg.get("mergeType", CONCAT),
                    "roundTimeTo": tcfg.get("roundTimeTo"),
                    "aggregations": tcfg.get("aggregations", {}),
                    "maxRowsPerSegment": int(tcfg.get("maxRowsPerSegment", 5_000_000)),
                    "bucket": bucket,
                }))
        for s in specs:
            queue.submit(s)
        return specs


class RealtimeToOfflineTaskGenerator(TaskGenerator):
    """Reference: RealtimeToOfflineSegmentsTaskGenerator — advance a per-table
    watermark window; only windows fully covered by COMMITTED segments qualify."""

    task_type = REALTIME_TO_OFFLINE

    def generate(self, catalog, cfg: TableConfig, queue: TaskQueue) -> List[TaskSpec]:
        from ..cluster.catalog import STATUS_DONE, STATUS_UPLOADED
        tcfg = cfg.task_configs.get(self.task_type)
        table = cfg.table_name_with_type
        if (tcfg is None or cfg.table_type is not TableType.REALTIME
                or not cfg.time_column):
            return []
        if queue.has_pending(table, self.task_type) \
                or queue.in_error_backoff(table, self.task_type):
            return []
        bucket_ms = int(tcfg.get("bucketMs", 24 * 3600 * 1000))
        metas = list(catalog.segments.get(table, {}).values())
        done = [m for m in metas if m.status in (STATUS_DONE, STATUS_UPLOADED)
                and m.start_time_ms is not None]
        if not done:
            return []
        wm_key = f"rtToOffline/{table}/watermark"
        watermark = catalog.get_property(wm_key)
        if watermark is None:
            watermark = (min(m.start_time_ms for m in done) // bucket_ms) * bucket_ms
        window_start, window_end = int(watermark), int(watermark) + bucket_ms
        # window completeness: per partition, COMMITTED segments must already cover
        # data past the window end — per-partition stream order then guarantees the
        # still-consuming segment holds only newer rows (reference: the generator's
        # check against each partition's latest completed segment end time)
        partitions = {m.partition_group for m in metas}
        for pg in partitions:
            ends = [m.end_time_ms for m in done
                    if m.partition_group == pg and m.end_time_ms is not None]
            if not ends or max(ends) < window_end:
                return []
        inputs = [m.name for m in done
                  if m.start_time_ms < window_end
                  and (m.end_time_ms or m.start_time_ms) >= window_start]
        if not inputs:
            # nothing in this window: advance the watermark and retry next round
            catalog.put_property(wm_key, window_end)
            return []
        spec = TaskSpec(
            task_id=f"{self.task_type}_{table}_{window_start}_{uuid.uuid4().hex[:8]}",
            task_type=self.task_type, table=table,
            config={
                "segments": sorted(inputs),
                "windowStartMs": window_start,
                "windowEndMs": window_end,
                "mergeType": tcfg.get("mergeType", CONCAT),
                "roundTimeTo": tcfg.get("roundTimeTo"),
                "aggregations": tcfg.get("aggregations", {}),
                "maxRowsPerSegment": int(tcfg.get("maxRowsPerSegment", 5_000_000)),
            })
        queue.submit(spec)
        return [spec]


class ConvertToRawIndexTaskGenerator(TaskGenerator):
    """Reference: ConvertToRawIndexTaskGenerator — one task per batch of
    segments whose target columns are still dictionary-encoded. The custom
    mark on rewritten segments keeps them out of later rounds."""

    task_type = CONVERT_TO_RAW_INDEX

    def generate(self, catalog, cfg: TableConfig, queue: TaskQueue) -> List[TaskSpec]:
        tcfg = cfg.task_configs.get(self.task_type)
        table = cfg.table_name_with_type
        if tcfg is None:
            return []
        if queue.has_pending(table, self.task_type) \
                or queue.in_error_backoff(table, self.task_type):
            return []
        max_tasks = int(tcfg.get("tableMaxNumTasks", 1))
        per_task = int(tcfg.get("maxNumSegmentsPerTask", 10))
        done = set(catalog.get_property(f"convertRawDone/{table}", []) or [])
        todo = sorted(
            m.name for m in catalog.segments.get(table, {}).values()
            if m.status != "IN_PROGRESS"   # committed realtime OR uploaded
            and m.custom.get("task") != CONVERT_TO_RAW_INDEX
            and m.name not in done)
        specs = []
        for lo in range(0, min(len(todo), max_tasks * per_task), per_task):
            specs.append(TaskSpec(
                task_id=f"{self.task_type}_{table}_{uuid.uuid4().hex[:8]}",
                task_type=self.task_type, table=table,
                config={"segments": todo[lo:lo + per_task],
                        "columnsToConvert":
                            tcfg.get("columnsToConvert", [])}))
        for s in specs:
            queue.submit(s)
        return specs


class PinotTaskManager:
    """Controller-side periodic generation over all tables (reference: PinotTaskManager)."""

    def __init__(self, catalog):
        self.catalog = catalog
        self.queue = TaskQueue(catalog)
        self.generators: Dict[str, TaskGenerator] = {}
        for gen in (MergeRollupTaskGenerator(), RealtimeToOfflineTaskGenerator(),
                    ConvertToRawIndexTaskGenerator()):
            self.generators[gen.task_type] = gen

    def register_generator(self, gen: TaskGenerator) -> None:
        self.generators[gen.task_type] = gen

    def generate_all(self) -> List[TaskSpec]:
        self.queue.gc()
        specs: List[TaskSpec] = []
        for cfg in list(self.catalog.table_configs.values()):
            for task_type in cfg.task_configs:
                gen = self.generators.get(task_type)
                if gen is not None:
                    specs.extend(gen.generate(self.catalog, cfg, self.queue))
        return specs


# ---------------------------------------------------------------------------
# Execution (minion worker side)
# ---------------------------------------------------------------------------

class TaskExecutor:
    """SPI (reference: PinotTaskExecutor)."""

    task_type = ""

    def execute(self, spec: TaskSpec, worker: "MinionWorker") -> None:
        raise NotImplementedError


class StaleTaskError(Exception):
    """The task's inputs no longer exist — another worker (after a lease expiry)
    already completed it. Treated as success with no side effects."""


class BaseMergeExecutor(TaskExecutor):
    """Shared download -> process -> publish pipeline for merge-shaped tasks."""

    def _load_inputs(self, spec: TaskSpec, worker: "MinionWorker"):
        from ..segment.reader import load_segment
        live = worker.catalog.segments.get(spec.table, {})
        missing = [n for n in spec.config["segments"] if n not in live]
        if missing:
            raise StaleTaskError(f"inputs gone (completed elsewhere?): {missing}")
        segs = []
        for name in spec.config["segments"]:
            segs.append(load_segment(worker.fetch_segment(spec.table, name)))
        return segs

    @staticmethod
    def _generator_config(cfg: TableConfig):
        from ..segment.writer import SegmentGeneratorConfig
        idx = cfg.indexing
        return SegmentGeneratorConfig.from_indexing(idx)

    def _processor_config(self, spec: TaskSpec, cfg: TableConfig,
                          prefix: str) -> ProcessorConfig:
        return ProcessorConfig(
            merge_type=spec.config.get("mergeType", CONCAT),
            time_column=cfg.time_column,
            bucket_ms=spec.config.get("bucketMs"),
            round_time_to=spec.config.get("roundTimeTo"),
            window_start=spec.config.get("windowStartMs"),
            window_end=spec.config.get("windowEndMs"),
            max_rows_per_segment=spec.config.get("maxRowsPerSegment", 5_000_000),
            aggregations=spec.config.get("aggregations", {}),
            segment_prefix=prefix,
            generator_config=self._generator_config(cfg))


class MergeRollupTaskExecutor(BaseMergeExecutor):
    task_type = MERGE_ROLLUP

    def execute(self, spec: TaskSpec, worker: "MinionWorker") -> None:
        cfg = worker.catalog.table_configs[spec.table]
        schema = worker.catalog.schemas[cfg.name]
        segs = self._load_inputs(spec, worker)
        prefix = f"merged_{cfg.name}_{spec.config['bucket']}_{uuid.uuid4().hex[:6]}"
        out_dir = os.path.join(worker.work_dir, spec.task_id, "out")
        built = process_segments(segs, schema, self._processor_config(spec, cfg, prefix),
                                 out_dir)
        # atomic swap via segment lineage: queries never see inputs+outputs together;
        # the custom mark keeps outputs out of the next generation round
        worker.controller.replace_segments(spec.table, spec.config["segments"], built,
                                           custom={"task": MERGE_ROLLUP})


class RealtimeToOfflineTaskExecutor(BaseMergeExecutor):
    task_type = REALTIME_TO_OFFLINE

    def execute(self, spec: TaskSpec, worker: "MinionWorker") -> None:
        rt_cfg = worker.catalog.table_configs[spec.table]
        offline_table = f"{rt_cfg.name}_{TableType.OFFLINE.value}"
        if offline_table not in worker.catalog.table_configs:
            raise ValueError(f"hybrid table {rt_cfg.name!r} has no OFFLINE half")
        schema = worker.catalog.schemas[rt_cfg.name]
        segs = self._load_inputs(spec, worker)
        start = spec.config["windowStartMs"]
        # DETERMINISTIC per-window prefix: a retry after partial failure first sweeps
        # leftovers of the previous attempt, so the window's rows appear exactly once
        prefix = f"{rt_cfg.name}_rto_{start}"
        leftovers = [n for n in worker.catalog.segments.get(offline_table, {})
                     if n.startswith(prefix + "_")]
        for n in leftovers:
            worker.controller.delete_segment(offline_table, n, permanent=True)
        out_dir = os.path.join(worker.work_dir, spec.task_id, "out")
        built = process_segments(segs, schema, self._processor_config(spec, rt_cfg, prefix),
                                 out_dir)
        for seg_dir in built:
            worker.controller.upload_segment(offline_table, seg_dir,
                                             custom={"task": REALTIME_TO_OFFLINE,
                                                     "windowStartMs": str(start)})
        # advance the watermark only after every upload landed; a crash before this
        # re-runs the window, and the sweep above keeps that idempotent
        worker.catalog.put_property(f"rtToOffline/{spec.table}/watermark",
                                    spec.config["windowEndMs"])
        # realtime copies stay until retention expires them; the broker's hybrid time
        # boundary keeps them from double-counting (cluster/broker.py)


class PurgeTaskExecutor(BaseMergeExecutor):
    """Rewrite segments dropping rows that match a purge predicate (reference:
    PurgeTaskExecutor + RecordPurger)."""

    task_type = PURGE

    def execute(self, spec: TaskSpec, worker: "MinionWorker") -> None:
        import numpy as np
        from .framework import concat_columns, read_columns
        from ..segment.writer import SegmentBuilder
        cfg = worker.catalog.table_configs[spec.table]
        schema = worker.catalog.schemas[cfg.name]
        segs = self._load_inputs(spec, worker)
        column = spec.config["column"]
        values = set(spec.config["values"])
        out_dir = os.path.join(worker.work_dir, spec.task_id, "out")
        os.makedirs(out_dir, exist_ok=True)
        builder = SegmentBuilder(schema, self._generator_config(cfg))
        old_names, new_dirs = [], []
        for seg, name in zip(segs, spec.config["segments"]):
            cols = read_columns(seg, schema)
            keep = np.array([v not in values for v in cols[column].tolist()], dtype=bool)
            if keep.all():
                continue
            old_names.append(name)
            if not keep.any():
                continue  # fully purged: drop the input with no replacement
            kept = {k: v[keep] for k, v in cols.items()}
            new_dirs.append(builder.build(kept, out_dir,
                                          f"{name}_purged_{uuid.uuid4().hex[:6]}"))
        if old_names:
            worker.controller.replace_segments(spec.table, old_names, new_dirs)


class ConvertToRawIndexTaskExecutor(BaseMergeExecutor):
    """Rewrite segments with the given columns as RAW (no-dictionary)
    forward indexes (reference: converttorawindex/
    ConvertToRawIndexTaskExecutor.java — there a refresh push, here the
    same lineage-protected replace the other rewrite tasks use). An empty
    `columnsToConvert` converts every single-value column, matching the
    reference's default."""

    task_type = CONVERT_TO_RAW_INDEX

    def execute(self, spec: TaskSpec, worker: "MinionWorker") -> None:
        from .framework import read_columns
        from ..segment.writer import SegmentBuilder
        cfg = worker.catalog.table_configs[spec.table]
        schema = worker.catalog.schemas[cfg.name]
        segs = self._load_inputs(spec, worker)
        columns = list(spec.config.get("columnsToConvert") or [])
        if not columns:
            columns = [f.name for f in schema.fields if f.single_value]
        gen = self._generator_config(cfg)
        gen.no_dictionary_columns = sorted(
            set(gen.no_dictionary_columns) | set(columns))
        out_dir = os.path.join(worker.work_dir, spec.task_id, "out")
        os.makedirs(out_dir, exist_ok=True)
        builder = SegmentBuilder(schema, gen)
        schema_names = {f.name for f in schema.fields}
        old_names, new_dirs = [], []
        already_raw: List[str] = []
        for seg, name in zip(segs, spec.config["segments"]):
            if all(not seg.column(c).has_dictionary
                   for c in columns if c in schema_names):
                already_raw.append(name)
                continue
            cols = read_columns(seg, schema)
            old_names.append(name)
            new_dirs.append(builder.build(
                cols, out_dir, f"{name}_raw_{uuid.uuid4().hex[:6]}"))
        if old_names:
            worker.controller.replace_segments(
                spec.table, old_names, new_dirs,
                custom={"task": CONVERT_TO_RAW_INDEX})
        if already_raw:
            # record no-op inputs in the done-set property: the generator
            # filters on it, so an already-raw segment (e.g. uploaded raw,
            # or the table's indexing config already lists the columns)
            # would otherwise be re-generated — and re-downloaded — every
            # controller task tick forever
            worker.catalog.mutate_property(
                f"convertRawDone/{spec.table}",
                lambda cur: sorted(set(cur or []) | set(already_raw)))


class SegmentGenerationAndPushExecutor(TaskExecutor):
    """One input FILE -> transformed segment(s) -> controller push (reference:
    `SegmentGenerationAndPushTaskExecutor` + the hadoop/spark batch runners'
    per-file unit). The controller's /ingestJobs endpoint splits a batch job
    into one task per input file, so N minion processes ingest N files in
    parallel — the distributed runner the standalone in-process one scales
    out to. Input paths must be readable by the minion (shared filesystem or
    mounted staging)."""

    task_type = SEGMENT_GENERATION_AND_PUSH

    def execute(self, spec: TaskSpec, worker: "MinionWorker") -> None:
        from ..ingest.batch import ingest_file_to_segments
        cfg = worker.catalog.table_configs[spec.table]
        schema = worker.catalog.schemas[cfg.name]
        c = spec.config
        prefix = (c.get("segmentNamePrefix") or cfg.name)
        seg_dirs = ingest_file_to_segments(
            schema, cfg, c["inputPath"],
            input_format=c.get("inputFormat"),
            filter_expr=c.get("filterExpr"),
            column_transforms=c.get("columnTransforms"),
            segment_rows=int(c.get("segmentRows", 1_000_000)),
            prefix=f"{prefix}_{c.get('sequence', 0)}",
            build_dir=os.path.join(worker.work_dir, spec.task_id, "out"))
        for seg_dir in seg_dirs:
            worker.controller.upload_segment(
                spec.table, seg_dir,
                custom={"task": SEGMENT_GENERATION_AND_PUSH,
                        "inputPath": c["inputPath"]})


class MinionWorker:
    """Minion role: claims queued tasks and runs the registered executor.

    `controller` is the controller API surface it needs (upload_segment,
    replace_segments) — the in-proc Controller object or an HTTP proxy with the
    same methods.
    """

    def __init__(self, instance_id: str, catalog, deepstore, controller,
                 work_dir: str, queue=None):
        from ..cluster.catalog import InstanceInfo
        self.instance_id = instance_id
        self.catalog = catalog
        self.deepstore = deepstore
        self.controller = controller
        self.work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)
        # remote minions claim through the controller's REST queue
        # (RemoteTaskQueue) — a RemoteCatalog mirror cannot run the atomic
        # read-modify-write a claim needs
        self.queue = queue if queue is not None else TaskQueue(catalog)
        self.executors: Dict[str, TaskExecutor] = {}
        for ex in (MergeRollupTaskExecutor(), RealtimeToOfflineTaskExecutor(),
                   PurgeTaskExecutor(), SegmentGenerationAndPushExecutor(),
                   ConvertToRawIndexTaskExecutor()):
            self.executors[ex.task_type] = ex
        self.completed = 0
        self.failed = 0
        catalog.register_instance(InstanceInfo(instance_id, "minion"))

    def register_executor(self, ex: TaskExecutor) -> None:
        self.executors[ex.task_type] = ex

    def fetch_segment(self, table: str, segment: str) -> str:
        """Download + unpack one segment (deep store, falling back to a
        serving PEER replica for peer-scheme or outage cases); returns its
        dir."""
        from ..cluster.deepstore import untar_segment
        from ..cluster.peers import download_segment_tar
        meta = self.catalog.segments[table][segment]
        tar_path = os.path.join(self.work_dir, "fetch", f"{segment}.tar.gz")
        download_segment_tar(self.deepstore, self.catalog, table, segment,
                             tar_path, meta.download_path)
        seg_dir = untar_segment(tar_path, os.path.join(self.work_dir, "fetch", segment))
        os.remove(tar_path)
        return seg_dir

    def run_once(self) -> Optional[TaskSpec]:
        """Claim and execute one task; returns it (state reflects the outcome)."""
        spec = self.queue.claim(self.instance_id, list(self.executors))
        if spec is None:
            return None
        try:
            self.executors[spec.task_type].execute(spec, self)
            self.queue.finish(spec.task_id, worker_id=self.instance_id)
            spec.state = COMPLETED
            self.completed += 1
        except StaleTaskError:
            # another worker finished it after our (or a predecessor's) lease
            # lapsed; nothing to do and nothing failed
            self.queue.finish(spec.task_id, worker_id=self.instance_id)
            spec.state = COMPLETED
        except Exception as e:  # task failure must not kill the worker loop
            self.queue.finish(spec.task_id, error=f"{type(e).__name__}: {e}",
                              worker_id=self.instance_id)
            spec.state = ERROR
            spec.error = str(e)
            self.failed += 1
        return spec

    def drain(self, max_tasks: int = 64) -> List[TaskSpec]:
        out = []
        for _ in range(max_tasks):
            spec = self.run_once()
            if spec is None:
                break
            out.append(spec)
        return out
