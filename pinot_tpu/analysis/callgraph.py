"""Interprocedural layer: project symbol table, call graph, function summaries.

The per-scope rule packs see one function at a time; this module gives them
the project view the ROADMAP called for ("cross-function taint tracking for
device values"). One extraction pass per parsed Module collects, per function:

* ordered assignment/return facts (what taints what, resolved lazily),
* `self._attr` stores and their value facts,
* call sites with param-forwarding (`self.m()`, `helper(self)`, aliases),
* attribute accesses on parameters with the lock-attrs held at the site.

A single fixpoint pass then computes summaries:

* `returns_device` + a representative producer chain (`g() -> f()`), so a
  caller in another module that host-syncs `x = g(...)` is a finding with the
  whole propagation path in the message;
* class-level `device_attrs` (`self._x = jnp...` in one method taints
  `self._x` reads in every other method);
* transitive unguarded attribute accesses per parameter, so a thread-entry
  method that reaches `self._buf` through two helpers (possibly in another
  module, via `drain(self)`) is still visible to the race detector.

Everything is resolved through per-module import tables (plain, aliased and
relative imports), so `from jax import device_get as dg` cannot hide a sync.
The build is one walk + one fixpoint and is cached on the AnalysisContext —
rules share it, nothing is recomputed per rule.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

from .core import Module, dotted_name

#: value producers that put data on the device (same set as jit_hygiene)
DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")

#: calls that bring a value back to host (a summary "kill"): their result is
#: host data, whatever went in
HOST_FETCHERS = {"jax.device_get", "device_get", "np.asarray", "np.array",
                 "numpy.asarray", "numpy.array", "float", "int", "bool",
                 "len", "str"}

#: container method calls treated as writes to the receiver attribute
MUTATORS = {"append", "appendleft", "add", "pop", "popleft", "update",
            "clear", "extend", "remove", "discard", "setdefault"}

_CHAIN_CAP = 5          # representative chains stay readable
_FIXPOINT_CAP = 20      # safety bound; monotone facts converge in 2-4 passes


# -- facts collected during extraction ---------------------------------------

class Access:
    """One attribute access on a function parameter (`p.attr`)."""

    __slots__ = ("attr", "kind", "rel", "line", "held", "chain")

    def __init__(self, attr: str, kind: str, rel: str, line: int,
                 held: FrozenSet[str], chain: Tuple[str, ...]):
        self.attr = attr
        self.kind = kind            # 'read' | 'write'
        self.rel = rel              # module the access physically lives in
        self.line = line
        self.held = held            # lock-ish attrs of the SAME receiver held
        self.chain = chain          # call path from the summarized function

    def key(self) -> Tuple[str, str, FrozenSet[str]]:
        return (self.attr, self.kind, self.held)


class CallFact:
    """One call site, with enough to resolve + forward parameters later."""

    __slots__ = ("func", "line", "forwards", "held")

    def __init__(self, func: ast.AST, line: int,
                 forwards: List[Tuple[int, int]], held: FrozenSet[str]):
        self.func = func            # the ast func expression (resolved later)
        self.line = line
        self.forwards = forwards    # (caller_param_idx, callee_param_idx)
        self.held = held            # locks held on param 0's receiver at site


class FunctionInfo:
    """One module function / class method plus its interprocedural summary."""

    __slots__ = ("name", "display", "module", "node", "cls", "params",
                 "assign_facts", "return_facts", "attr_stores", "calls",
                 "param_accesses", "returns_device", "device_chain",
                 "local_taint")

    def __init__(self, name: str, display: str, module: Module,
                 node: ast.AST, cls: Optional["ClassInfo"],
                 params: List[str]):
        self.name = name
        self.display = display      # e.g. 'Broker.handle' or 'helper'
        self.module = module
        self.node = node
        self.cls = cls
        self.params = params
        # extraction output (source order)
        self.assign_facts: List[Tuple[Tuple[str, ...], tuple]] = []
        self.return_facts: List[tuple] = []
        #: (attr, fact, line, kind): kind 'attr' for `self.X = v`, 'elem' for
        #: element stores (`self.X[k] = v`, `self.X.append(v)`, setdefault)
        self.attr_stores: List[Tuple[str, tuple, int, str]] = []
        self.calls: List[CallFact] = []
        #: param idx -> {Access.key(): Access}, grows to fixpoint
        self.param_accesses: Dict[int, Dict[tuple, Access]] = {}
        # summary
        self.returns_device = False
        self.device_chain: Tuple[str, ...] = ()
        self.local_taint: Dict[str, Tuple[str, ...]] = {}


class ClassInfo:
    __slots__ = ("name", "module", "node", "methods", "bases",
                 "device_attrs", "lock_attrs")

    def __init__(self, name: str, module: Module, node: ast.ClassDef):
        self.name = name
        self.module = module
        self.node = node
        self.methods: Dict[str, FunctionInfo] = {}
        self.bases: List[str] = []          # resolved class keys, best-effort
        #: attr -> producer chain for self-attrs stored from device values
        self.device_attrs: Dict[str, Tuple[str, ...]] = {}
        self.lock_attrs: FrozenSet[str] = frozenset()

    def method(self, name: str, cg: "CallGraph",
               _seen: Optional[set] = None) -> Optional[FunctionInfo]:
        """Method lookup through project-resolvable bases."""
        if name in self.methods:
            return self.methods[name]
        _seen = _seen or set()
        for b in self.bases:
            if b in _seen:
                continue
            _seen.add(b)
            base = cg.classes.get(b)
            if base is not None:
                m = base.method(name, cg, _seen)
                if m is not None:
                    return m
        return None


# -- module symbol/import tables ----------------------------------------------

def module_name_for(rel: str) -> str:
    """'pinot_tpu/cluster/broker.py' -> 'pinot_tpu.cluster.broker'."""
    name = rel[:-3] if rel.endswith(".py") else rel
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


class _ModuleTable:
    """Per-module name bindings: local defs + imports (aliases, relative)."""

    __slots__ = ("module", "modname", "is_pkg", "bindings")

    def __init__(self, module: Module):
        self.module = module
        self.modname = module_name_for(module.rel)
        self.is_pkg = module.rel.endswith("__init__.py")
        #: name -> ('mod', module_name) | ('sym', 'module_name:Symbol')
        self.bindings: Dict[str, Tuple[str, str]] = {}

    def _package(self) -> str:
        if self.is_pkg:
            return self.modname
        return self.modname.rpartition(".")[0]

    def scan_imports(self) -> None:
        for node in self.module.nodes_of(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.bindings[a.asname] = ("mod", a.name)
                    else:
                        self.bindings[a.name.split(".")[0]] = \
                            ("mod", a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg_parts = self._package().split(".")
                    if node.level - 1:
                        pkg_parts = pkg_parts[: -(node.level - 1)] \
                            if node.level - 1 <= len(pkg_parts) else []
                    base = ".".join(p for p in (".".join(pkg_parts), base)
                                    if p)
                for a in node.names:
                    bound = a.asname or a.name
                    if a.name == "*":
                        continue
                    self.bindings[bound] = ("sym", f"{base}:{a.name}")


# -- value facts ---------------------------------------------------------------

# fact shapes (tuples so the fixpoint loop stays allocation-light):
#   ('device',)            direct jnp./lax. producer
#   ('host',)              known host materializer — kills taint
#   ('call', CallNode)     resolved at fixpoint time
#   ('name', 'x')          alias of a local
#   ('selfattr', 'attr')   read of self.attr
#   ('multi', [facts])     tuple/ifexp/binop — tainted if any member is
#   ('other',)

#: container methods whose RESULT is an element of the receiver — a read of
#: the container's element taint
ELEMENT_GETTERS = {"get", "pop", "popleft", "setdefault"}


def classify_value(expr: ast.AST) -> tuple:
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
        if name.startswith(DEVICE_PREFIXES):
            return ("device",)
        if name in HOST_FETCHERS:
            return ("host",)
        # `self._cache.get(k)` / `.pop(k)` read an element: classify as a
        # read of the container itself so element stores taint the result
        if isinstance(expr.func, ast.Attribute) and \
                expr.func.attr in ELEMENT_GETTERS:
            recv = expr.func.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                return ("selfattr", recv.attr)
            if isinstance(recv, ast.Name):
                return ("name", recv.id)
        return ("call", expr)
    if isinstance(expr, ast.Name):
        return ("name", expr.id)
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return ("selfattr", expr.attr)
        return ("other",)
    if isinstance(expr, ast.Subscript):
        return classify_value(expr.value)
    if isinstance(expr, (ast.Await, ast.Starred)):
        return classify_value(expr.value)
    if isinstance(expr, ast.BinOp):
        return ("multi", [classify_value(expr.left),
                          classify_value(expr.right)])
    if isinstance(expr, ast.Tuple):
        return ("multi", [classify_value(e) for e in expr.elts])
    if isinstance(expr, ast.IfExp):
        return ("multi", [classify_value(expr.body),
                          classify_value(expr.orelse)])
    return ("other",)


_LOCKISH = ("lock", "cond", "mutex")


def _lockish(attr: str) -> bool:
    low = attr.lower()
    return any(t in low for t in _LOCKISH)


class _Extractor(ast.NodeVisitor):
    """One pass over a function body collecting ordered facts.

    Maintains the `with p.lock:` stack so every param-attr access and call
    site records the locks held on its receiver — no parent links needed."""

    def __init__(self, fi: FunctionInfo):
        self.fi = fi
        self.param_idx = {p: i for i, p in enumerate(fi.params)}
        #: rootname -> set of lock-ish attrs currently held on it
        self.held: Dict[str, set] = {}

    def _held_for(self, root: str) -> FrozenSet[str]:
        return frozenset(self.held.get(root, ()))

    def _is_lock_attr(self, root: str, attr: str) -> bool:
        """Lock-ish by name; for `self`, also by the owning class's actual
        lock attrs (a `self._mu = threading.Lock()` is a lock whatever it's
        called)."""
        if _lockish(attr):
            return True
        return root == "self" and self.fi.cls is not None and \
            attr in self.fi.cls.lock_attrs

    # -- with/lock tracking
    def visit_With(self, node: ast.With) -> None:
        added: List[Tuple[str, str]] = []
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):  # with self._lock is not a call;
                expr = expr.func            # but `with self._cond:` wrappers
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name):
                root, attr = expr.value.id, expr.attr
                if self._is_lock_attr(root, attr):
                    self.held.setdefault(root, set()).add(attr)
                    added.append((root, attr))
        for item in node.items:
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for root, attr in added:
            self.held[root].discard(attr)

    visit_AsyncWith = visit_With

    # -- assignments / returns
    def _targets(self, t: ast.AST) -> Tuple[str, ...]:
        if isinstance(t, ast.Name):
            return (t.id,)
        if isinstance(t, (ast.Tuple, ast.List)):
            out: List[str] = []
            for e in t.elts:
                out.extend(self._targets(e))
            return tuple(out)
        return ()

    def _record_assign(self, targets: Sequence[ast.AST],
                       value: Optional[ast.AST]) -> None:
        if value is None:
            return
        fact = classify_value(value)
        names: List[str] = []
        for t in targets:
            names.extend(self._targets(t))
            # self.X = <value> stores
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                self.fi.attr_stores.append((t.attr, fact, t.lineno, "attr"))
            # self.X[k] = <value>: an ELEMENT store — taints reads of the
            # container's elements (self.X[j], self.X.get(j)) without ever
            # killing existing taint (other keys keep their values)
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Attribute) and \
                    isinstance(t.value.value, ast.Name) and \
                    t.value.value.id == "self":
                self.fi.attr_stores.append(
                    (t.value.attr, fact, t.lineno, "elem"))
            # `p.attr[k] = v` is a write to p.attr (the Attribute itself
            # carries Load ctx — record the write explicitly)
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Attribute) and \
                    isinstance(t.value.value, ast.Name) and \
                    t.value.value.id in self.param_idx and \
                    not self._is_lock_attr(t.value.value.id, t.value.attr):
                idx = self.param_idx[t.value.value.id]
                acc = Access(t.value.attr, "write", self.fi.module.rel,
                             t.lineno, self._held_for(t.value.value.id),
                             (self.fi.display,))
                self.fi.param_accesses.setdefault(idx, {}) \
                    .setdefault(acc.key(), acc)
        if names:
            self.fi.assign_facts.append((tuple(names), fact))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._record_assign([node.target], node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_assign([node.target], node.value)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self.fi.return_facts.append(classify_value(node.value))
        self.generic_visit(node)

    # -- calls (edges + param forwarding)
    def visit_Call(self, node: ast.Call) -> None:
        # container-element taint: `self._q.append(dev)`, `self._c.update(d)`,
        # `self._m.setdefault(k, dev)` make element reads device-tainted
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS:
            recv = node.func.value
            value_args = node.args[1:] if node.func.attr == "setdefault" \
                else node.args
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                for a in value_args:
                    self.fi.attr_stores.append(
                        (recv.attr, classify_value(a), node.lineno, "elem"))
            elif isinstance(recv, ast.Name):
                # local container mutated in place: augment (never kill)
                for a in value_args:
                    self.fi.assign_facts.append(
                        ((recv.id,), ("augment", classify_value(a))))
        # `p.attr.append(...)`-style mutators are writes to p.attr
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS and \
                isinstance(node.func.value, ast.Attribute) and \
                isinstance(node.func.value.value, ast.Name) and \
                node.func.value.value.id in self.param_idx and \
                not self._is_lock_attr(node.func.value.value.id,
                                       node.func.value.attr):
            root = node.func.value.value.id
            acc = Access(node.func.value.attr, "write", self.fi.module.rel,
                         node.func.value.lineno, self._held_for(root),
                         (self.fi.display,))
            self.fi.param_accesses.setdefault(self.param_idx[root], {}) \
                .setdefault(acc.key(), acc)
        forwards: List[Tuple[int, int]] = []
        shift = 0
        # `self.m(...)` / `p.m(...)`: the receiver is forwarded as param 0
        if isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in self.param_idx:
            forwards.append((self.param_idx[node.func.value.id], 0))
            shift = 1
        for j, a in enumerate(node.args):
            if isinstance(a, ast.Name) and a.id in self.param_idx:
                forwards.append((self.param_idx[a.id], j + shift))
        root = node.func.value.id if (
            isinstance(node.func, ast.Attribute) and
            isinstance(node.func.value, ast.Name)) else "self"
        self.fi.calls.append(CallFact(
            node.func, node.lineno, forwards, self._held_for(root)))
        self.generic_visit(node)

    # -- param attr accesses (for the race detector)
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and \
                node.value.id in self.param_idx and \
                not self._is_lock_attr(node.value.id, node.attr):
            idx = self.param_idx[node.value.id]
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) \
                else "read"
            acc = Access(node.attr, kind, self.fi.module.rel, node.lineno,
                         self._held_for(node.value.id), (self.fi.display,))
            self.fi.param_accesses.setdefault(idx, {}) \
                .setdefault(acc.key(), acc)
        self.generic_visit(node)

    # nested defs: facts inside belong to the enclosing function's walk (the
    # per-scope rules make the same choice); nested defs also get their OWN
    # FunctionInfo only when bound at module/class level, which these are not.


# -- the graph -----------------------------------------------------------------

class CallGraph:
    def __init__(self, modules: Sequence[Module]):
        self.modules = [m for m in modules if m.tree is not None]
        self.tables: Dict[str, _ModuleTable] = {}
        self.by_modname: Dict[str, _ModuleTable] = {}
        self.functions: Dict[str, FunctionInfo] = {}   # key -> info
        self.classes: Dict[str, ClassInfo] = {}
        self.by_node: Dict[int, FunctionInfo] = {}     # id(ast node) -> info
        self.class_by_node: Dict[int, ClassInfo] = {}
        #: rel -> set of module rels it imports (project-internal)
        self.imports: Dict[str, set] = {}
        self._resolution: Dict[int, Optional[FunctionInfo]] = {}
        self._adhoc: Dict[int, FunctionInfo] = {}
        self._build()
        self._fixpoint()

    # -- construction
    def _build(self) -> None:
        for m in self.modules:
            table = _ModuleTable(m)
            table.scan_imports()
            self.tables[m.rel] = table
            self.by_modname[table.modname] = table
        for m in self.modules:
            self._index_module(m)
        for m in self.modules:
            self._link_imports(m)
        for cls in self.classes.values():
            self._resolve_bases(cls)
        for fi in self.functions.values():
            extractor = _Extractor(fi)
            body = fi.node.body if hasattr(fi.node, "body") else []
            for stmt in body:
                extractor.visit(stmt)

    def _index_module(self, m: Module) -> None:
        modname = self.tables[m.rel].modname
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{modname}:{node.name}"
                fi = FunctionInfo(node.name, node.name, m, node, None,
                                  [a.arg for a in node.args.args])
                self.functions[key] = fi
                self.by_node[id(node)] = fi
            elif isinstance(node, ast.ClassDef):
                ckey = f"{modname}:{node.name}"
                ci = ClassInfo(node.name, m, node)
                self.classes[ckey] = ci
                self.class_by_node[id(node)] = ci
                from .lock_discipline import _lock_attrs
                ci.lock_attrs = frozenset(_lock_attrs(node))
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        mkey = f"{ckey}.{sub.name}"
                        fi = FunctionInfo(
                            sub.name, f"{node.name}.{sub.name}", m, sub, ci,
                            [a.arg for a in sub.args.args])
                        self.functions[mkey] = fi
                        ci.methods[sub.name] = fi
                        self.by_node[id(sub)] = fi

    def _link_imports(self, m: Module) -> None:
        deps = self.imports.setdefault(m.rel, set())
        for kind, target in self.tables[m.rel].bindings.values():
            modname = target if kind == "mod" else target.split(":", 1)[0]
            t = self.by_modname.get(modname)
            if t is None and kind == "sym":
                # `from pkg import submodule` binds a module, not a symbol
                t = self.by_modname.get(
                    f"{modname}.{target.split(':', 1)[1]}"
                    if modname else target.split(":", 1)[1])
            if t is not None:
                deps.add(t.module.rel)

    def _resolve_bases(self, ci: ClassInfo) -> None:
        table = self.tables[ci.module.rel]
        for b in ci.node.bases:
            name = dotted_name(b)
            if not name:
                continue
            key = self._resolve_name(table, name)
            if key is not None and key in self.classes:
                ci.bases.append(key)

    # -- name/call resolution
    def _resolve_name(self, table: _ModuleTable, name: str) -> Optional[str]:
        """Resolve a dotted name in a module to a function/class key."""
        parts = name.split(".")
        head, rest = parts[0], parts[1:]
        # local definition?
        local = f"{table.modname}:{head}"
        if not rest and (local in self.functions or local in self.classes):
            return local
        binding = table.bindings.get(head)
        if binding is None:
            return None
        kind, target = binding
        if kind == "sym":
            base_mod, sym = target.split(":", 1)
            if not rest:
                key = f"{base_mod}:{sym}"
                if key in self.functions or key in self.classes:
                    return key
                # `from pkg import module` — nothing more to resolve here
                return None
            # from pkg import module; module.f(...)
            sub = self.by_modname.get(f"{base_mod}.{sym}" if base_mod
                                      else sym)
            if sub is not None:
                return self._resolve_in_module(sub.modname, rest)
            # Class.method via from-import
            ckey = f"{base_mod}:{sym}"
            if ckey in self.classes and len(rest) == 1:
                mi = self.classes[ckey].method(rest[0], self)
                return self._key_of(mi) if mi else None
            return None
        # module import: walk the longest module prefix, then symbols
        modpath = target
        idx = 0
        while idx < len(rest):
            nxt = f"{modpath}.{rest[idx]}"
            if nxt in self.by_modname or idx < len(rest) - 1 and \
                    f"{nxt}" in self.by_modname:
                modpath = nxt
                idx += 1
            else:
                break
        if modpath not in self.by_modname:
            return None
        return self._resolve_in_module(modpath, rest[idx:])

    def _resolve_in_module(self, modname: str,
                           parts: Sequence[str]) -> Optional[str]:
        if not parts:
            return None
        key = f"{modname}:{parts[0]}"
        if len(parts) == 1:
            if key in self.functions or key in self.classes:
                return key
            return None
        if key in self.classes and len(parts) == 2:
            mi = self.classes[key].method(parts[1], self)
            return self._key_of(mi) if mi else None
        return None

    def _key_of(self, fi: Optional[FunctionInfo]) -> Optional[str]:
        if fi is None:
            return None
        table = self.tables[fi.module.rel]
        if fi.cls is not None:
            return f"{table.modname}:{fi.cls.name}.{fi.name}"
        return f"{table.modname}:{fi.name}"

    def resolve_call(self, caller: FunctionInfo,
                     func: ast.AST) -> Optional[FunctionInfo]:
        """Resolve a call's func expression from `caller`'s context to a
        project FunctionInfo (constructors resolve to __init__'s class via
        `resolve_callable`, not here). Memoized per func node — the fixpoint
        loop re-evaluates facts but resolution never changes."""
        nid = id(func)
        if nid in self._resolution:
            return self._resolution[nid]
        key = self.resolve_callable(caller, func)
        out = self.functions.get(key) if key is not None else None
        self._resolution[nid] = out
        return out

    def resolve_callable(self, caller: FunctionInfo,
                         func: ast.AST) -> Optional[str]:
        table = self.tables[caller.module.rel]
        name = dotted_name(func)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] in ("self", "cls") and caller.cls is not None:
            if len(parts) == 2:
                mi = caller.cls.method(parts[1], self)
                return self._key_of(mi)
            return None
        return self._resolve_name(table, name)

    def function_for(self, node: ast.AST) -> Optional[FunctionInfo]:
        return self.by_node.get(id(node))

    def class_for(self, node: ast.AST) -> Optional[ClassInfo]:
        return self.class_by_node.get(id(node))

    def adhoc_scope(self, module: Module, node: ast.AST,
                    cls: Optional[ClassInfo]) -> FunctionInfo:
        """A throwaway FunctionInfo for scopes outside the registry (module
        bodies, nested defs) so rules can reuse the same taint evaluation.
        Memoized per node — check_module may revisit scopes."""
        nid = id(node)
        cached = self._adhoc.get(nid)
        if cached is not None:
            return cached
        params = [a.arg for a in node.args.args] \
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            else []
        name = getattr(node, "name", "<module>")
        fi = FunctionInfo(name, name, module, node, cls, params)
        ex = _Extractor(fi)
        for stmt in getattr(node, "body", ()):
            ex.visit(stmt)
        self._adhoc[nid] = fi
        return fi

    def taint_for(self, fi: FunctionInfo,
                  seed: Optional[Dict[str, Tuple[str, ...]]] = None
                  ) -> Dict[str, Tuple[str, ...]]:
        """Name -> producer chain for `fi`'s scope, seeded with enclosing
        taint for nested defs (closures see the outer names)."""
        if not seed:
            return (self._compute_local_taint(fi) if not fi.local_taint
                    else fi.local_taint)
        taint = dict(seed)
        taint.update(self._compute_local_taint(fi))
        return taint

    def expand_name(self, module_rel: str, name: str) -> str:
        """Canonicalize a dotted name through the module's import table:
        `dg` (from jax import device_get as dg) -> 'jax.device_get',
        `xnp.asarray` (import jax.numpy as xnp) -> 'jax.numpy.asarray'."""
        table = self.tables.get(module_rel)
        if table is None or not name:
            return name
        head, _, rest = name.partition(".")
        binding = table.bindings.get(head)
        if binding is None:
            return name
        kind, target = binding
        if kind == "mod":
            expanded = target
        else:
            base, _, sym = target.partition(":")
            expanded = f"{base}.{sym}" if base else sym
        return f"{expanded}.{rest}" if rest else expanded

    # -- reverse import closure (for --changed-only)
    def dependents_closure(self, rels: Iterable[str]) -> set:
        """`rels` plus every module that (transitively) imports one of them."""
        reverse: Dict[str, set] = {}
        for src, deps in self.imports.items():
            for d in deps:
                reverse.setdefault(d, set()).add(src)
        out = set(rels)
        frontier = list(out)
        while frontier:
            cur = frontier.pop()
            for dep in reverse.get(cur, ()):
                if dep not in out:
                    out.add(dep)
                    frontier.append(dep)
        return out

    # -- summaries (fixpoint)
    def _eval_fact(self, fi: FunctionInfo, fact: tuple,
                   taint: Dict[str, Tuple[str, ...]]
                   ) -> Optional[Tuple[str, ...]]:
        """Chain if `fact` currently evaluates device-tainted, else None."""
        kind = fact[0]
        if kind == "device":
            return ()
        if kind in ("host", "other"):
            return None
        if kind == "augment":
            return self._eval_fact(fi, fact[1], taint)
        if kind == "name":
            return taint.get(fact[1])
        if kind == "selfattr":
            if fi.cls is not None and fact[1] in fi.cls.device_attrs:
                return fi.cls.device_attrs[fact[1]]
            return None
        if kind == "call":
            callee = self.resolve_call(fi, fact[1].func)
            if callee is not None and callee.returns_device:
                return callee.device_chain
            # taint through identity-ish helpers: a resolved callee whose
            # return is its param and that param is a tainted arg
            return None
        if kind == "multi":
            for sub in fact[1]:
                c = self._eval_fact(fi, sub, taint)
                if c is not None:
                    return c
            return None
        return None

    def _compute_local_taint(self, fi: FunctionInfo
                             ) -> Dict[str, Tuple[str, ...]]:
        taint: Dict[str, Tuple[str, ...]] = {}
        for names, fact in fi.assign_facts:
            chain = self._eval_fact(fi, fact, taint)
            if chain is not None:
                for n in names:
                    taint[n] = chain[:_CHAIN_CAP]
            elif fact[0] != "augment":
                # an in-place mutation with a clean value never CLEARS the
                # container's taint — other elements keep theirs
                for n in names:
                    taint.pop(n, None)
        return taint

    def _fixpoint(self) -> None:
        fns = list(self.functions.values())
        for _ in range(_FIXPOINT_CAP):
            changed = False
            for fi in fns:
                taint = self._compute_local_taint(fi)
                fi.local_taint = taint
                # returns_device
                if not fi.returns_device:
                    for fact in fi.return_facts:
                        chain = self._eval_fact(fi, fact, taint)
                        if chain is not None:
                            fi.returns_device = True
                            fi.device_chain = (
                                (f"{fi.display}()",) + chain)[:_CHAIN_CAP]
                            changed = True
                            break
                # class device attrs (plain stores and element stores)
                if fi.cls is not None:
                    for attr, fact, _line, skind in fi.attr_stores:
                        if attr in fi.cls.device_attrs:
                            continue
                        chain = self._eval_fact(fi, fact, taint)
                        if chain is not None:
                            stored = f"self.{attr}[...]" if skind == "elem" \
                                else f"self.{attr}"
                            fi.cls.device_attrs[attr] = (
                                (f"{fi.display}() stores {stored}",)
                                + chain)[:_CHAIN_CAP]
                            changed = True
                # transitive param attr accesses
                for call in fi.calls:
                    if not call.forwards:
                        continue
                    callee = self.resolve_call(fi, call.func)
                    if callee is None or callee is fi:
                        continue
                    for mine, theirs in call.forwards:
                        for acc in list(
                                callee.param_accesses.get(theirs, {})
                                .values()):
                            if len(acc.chain) >= _CHAIN_CAP:
                                continue
                            folded = Access(
                                acc.attr, acc.kind, acc.rel, acc.line,
                                acc.held | call.held,
                                (fi.display,) + acc.chain)
                            bucket = fi.param_accesses.setdefault(mine, {})
                            if folded.key() not in bucket:
                                bucket[folded.key()] = folded
                                changed = True
            if not changed:
                break


def build(modules: Sequence[Module]) -> CallGraph:
    return CallGraph(modules)
