"""graftcheck rule engine: Rule/Finding, package walker, suppressions, baseline.

Design mirrors the metric registry's "one flat process-wide surface" idiom:
one walk parses every module once, every rule sees every module (plus a
project-level hook for cross-file drift guards), and the output is a flat
finding list keyed by stable fingerprints.

Fingerprints are `(rule, relative path, message)` — deliberately line-free so
unrelated edits above a known finding don't churn the committed baseline.
The baseline stores a COUNT per fingerprint: only findings *beyond* the
baselined count are "new" and fail the run.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: inline suppression: `# graftcheck: ignore[rule-id, ...] -- reason`.
#: The reason is MANDATORY — a suppression without one is itself a finding
#: (bad-suppression), because "why is this OK" is the whole point of the
#: mechanism.
_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*ignore\[([A-Za-z0-9_*,\- ]+)\]"
    r"(?:\s*--\s*(?P<reason>\S.*))?")

BAD_SUPPRESSION = "bad-suppression"
PARSE_ERROR = "parse-error"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # repo-relative posix path
    line: int
    message: str
    #: propagation chain for interprocedural findings (`f() -> g() ->
    #: float(x)`). Shown in the rendered message, EXCLUDED from the
    #: fingerprint: renaming a caller or re-routing the chain must not churn
    #: the committed baseline, exactly like line edits must not.
    chain: str = ""

    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        via = f" [via {self.chain}]" if self.chain else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{via}"


class Module:
    """One parsed source file: tree + raw lines + inline suppressions."""

    def __init__(self, abspath: str, rel: str, source: str):
        self.path = abspath
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        #: line -> set of rule ids suppressed on that line ('*' = all)
        self.suppressions: Dict[int, set] = {}
        #: suppression comments missing their `-- reason` (line numbers)
        self.bad_suppressions: List[int] = []
        self._nodes: Optional[List[ast.AST]] = None
        self._by_type: Optional[Dict[type, List[ast.AST]]] = None
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        self._scan_suppressions()

    def nodes(self) -> List[ast.AST]:
        """Every node of the tree in `ast.walk` order, computed once and
        shared by every rule pack — 19 rules re-walking the same tree is
        the dominant cost of a full-package run."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree)) \
                if self.tree is not None else []
        return self._nodes

    def nodes_of(self, *types: type) -> List[ast.AST]:
        """Cached per-type node index. Order within a type follows
        `ast.walk`; asking for several types concatenates per-type lists
        (use `nodes()` when interleaved source order matters)."""
        if self._by_type is None:
            by: Dict[type, List[ast.AST]] = {}
            for n in self.nodes():
                by.setdefault(type(n), []).append(n)
            self._by_type = by
        if len(types) == 1:
            return self._by_type.get(types[0], [])
        out: List[ast.AST] = []
        for t in types:
            out.extend(self._by_type.get(t, []))
        return out

    def _scan_suppressions(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            line = tok.start[0]
            if not m.group("reason"):
                self.bad_suppressions.append(line)
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            standalone = self.lines[line - 1].lstrip().startswith("#")
            # a trailing comment suppresses its own line; a standalone
            # comment suppresses the next CODE line (skipping the rest of a
            # wrapped comment block)
            target = line
            if standalone:
                target = line + 1
                while target <= len(self.lines) and (
                        not self.lines[target - 1].strip() or
                        self.lines[target - 1].lstrip().startswith("#")):
                    target += 1
            self.suppressions.setdefault(target, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "*" in rules)


@dataclass
class AnalysisContext:
    """Shared run state rules may consult (repo docs for drift guards, the
    interprocedural call graph for the cross-function rules)."""

    repo_root: str
    modules: List[Module] = field(default_factory=list)
    _readme: Optional[str] = None
    _callgraph: Optional[object] = None
    _cfgs: Dict[int, object] = field(default_factory=dict)

    def module(self, rel_suffix: str) -> Optional[Module]:
        for m in self.modules:
            if m.rel.endswith(rel_suffix):
                return m
        return None

    def callgraph(self):
        """Project call graph + summaries, built once per run (the "summary
        cache": every rule shares one fixpoint pass)."""
        if self._callgraph is None:
            from .callgraph import build
            self._callgraph = build(self.modules)
        return self._callgraph

    def cfg(self, fn: ast.AST):
        """Control-flow graph for a function node, built once per run and
        shared by every flow-sensitive rule (same economics as the call
        graph: one lowering, many analyses). Keyed by node identity —
        modules are parsed once, so the same def is the same object."""
        cached = self._cfgs.get(id(fn))
        if cached is None:
            from .cfg import build_cfg
            cached = build_cfg(fn)
            self._cfgs[id(fn)] = cached
        return cached

    def readme(self) -> str:
        if self._readme is None:
            path = os.path.join(self.repo_root, "README.md")
            try:
                with open(path, encoding="utf-8") as f:
                    self._readme = f.read()
            except OSError:
                self._readme = ""
        return self._readme


class Rule:
    """Base rule: subclass and override one (or both) hooks.

    `check_module` runs once per parsed file; `check_project` runs once per
    analysis run with the full context (for cross-file drift guards)."""

    id: str = "abstract"
    description: str = ""

    def check_module(self, module: Module, ctx: AnalysisContext
                     ) -> Iterable[Finding]:
        return ()

    def check_project(self, ctx: AnalysisContext) -> Iterable[Finding]:
        return ()


# -- AST helpers shared by the rule packs ------------------------------------

def dotted_name(node: ast.AST) -> str:
    """Render `a.b.c` attribute/name chains ('' when not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def attach_parents(tree) -> None:
    """Set `.graft_parent` on every node (rules walk up for enclosing scope).
    Accepts a Module (reuses its cached node list) or a bare AST."""
    nodes = tree.nodes() if isinstance(tree, Module) else ast.walk(tree)
    for parent in nodes:
        for child in ast.iter_child_nodes(parent):
            child.graft_parent = parent  # type: ignore[attr-defined]


def enclosing(node: ast.AST, kinds) -> Optional[ast.AST]:
    """Nearest ancestor of one of `kinds` (requires attach_parents)."""
    cur = getattr(node, "graft_parent", None)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = getattr(cur, "graft_parent", None)
    return None


def is_constant_expr(node: ast.AST) -> bool:
    """True for literal-only expressions (numbers, strings, and lists/tuples
    thereof) — the `jnp.array([1, 2, 3])`-inside-jit shape of constant."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(is_constant_expr(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return is_constant_expr(node.operand)
    if isinstance(node, ast.BinOp):
        return is_constant_expr(node.left) and is_constant_expr(node.right)
    return False


# -- walker ------------------------------------------------------------------

def _iter_py_files(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def repo_root_for_package() -> str:
    """The directory holding the `pinot_tpu` package (== repo root)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def collect_modules(paths: Sequence[str], repo_root: Optional[str] = None
                    ) -> List[Module]:
    repo_root = repo_root or repo_root_for_package()
    modules: List[Module] = []
    for path in paths:
        for fp in _iter_py_files(os.path.abspath(path)):
            try:
                rel = os.path.relpath(fp, repo_root)
            except ValueError:  # different drive (windows) — keep absolute
                rel = fp
            rel = rel.replace(os.sep, "/")
            if rel.startswith(".."):
                rel = os.path.basename(fp)
            with open(fp, encoding="utf-8") as f:
                source = f.read()
            modules.append(Module(fp, rel, source))
    return modules


def run_rules(rules: Sequence[Rule], modules: Sequence[Module],
              ctx: AnalysisContext,
              targets: Optional[Sequence[Module]] = None
              ) -> Tuple[List[Finding], List[Finding]]:
    """Run every rule; returns (active findings, suppressed findings).

    Parse failures and reason-less suppressions surface as findings too —
    a file the checker cannot read is not a clean file.

    `targets` (for --changed-only) narrows which modules the per-module
    rules scan and which paths project-wide findings may land on; `modules`
    stays the full set so the call graph keeps whole-project summaries."""
    active: List[Finding] = []
    suppressed: List[Finding] = []
    target_rels = None if targets is None else {m.rel for m in targets}
    for m in (modules if targets is None else targets):
        if m.parse_error:
            active.append(Finding(PARSE_ERROR, m.rel, 1, m.parse_error))
        for line in m.bad_suppressions:
            active.append(Finding(
                BAD_SUPPRESSION, m.rel, line,
                "graftcheck suppression without a `-- reason` "
                "(the rationale is mandatory)"))
        if m.tree is None:
            continue
        attach_parents(m)
        for rule in rules:
            for f in rule.check_module(m, ctx):
                (suppressed if m.suppressed(f.rule, f.line) else
                 active).append(f)
    by_rel = {m.rel: m for m in modules}
    for rule in rules:
        for f in rule.check_project(ctx):
            if target_rels is not None and f.path not in target_rels:
                continue
            m = by_rel.get(f.path)
            if m is not None and m.suppressed(f.rule, f.line):
                suppressed.append(f)
            else:
                active.append(f)
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    return active, suppressed


def all_rules() -> List[Rule]:
    from . import (accumulation, admission_hygiene, blocking_in_loop,
                   collective_hygiene, drift_guards, events_drift,
                   exception_hygiene, filter_path, fused_path,
                   ingest_hot_loop, jit_hygiene, join_path, lock_discipline,
                   lock_order, memory_hygiene, transport_bypass)
    rules: List[Rule] = []
    for pack in (jit_hygiene, lock_discipline, lock_order, blocking_in_loop,
                 drift_guards, events_drift, transport_bypass,
                 collective_hygiene, ingest_hot_loop, exception_hygiene,
                 admission_hygiene, filter_path, fused_path, join_path,
                 memory_hygiene, accumulation):
        rules.extend(pack.rules())
    return rules


def run_project(paths: Optional[Sequence[str]] = None,
                rules: Optional[Sequence[Rule]] = None,
                repo_root: Optional[str] = None,
                restrict_rels: Optional[Sequence[str]] = None
                ) -> Tuple[List[Finding], List[Finding], AnalysisContext]:
    """Analyse `paths` (default: the pinot_tpu package) with every rule.

    `restrict_rels` (--changed-only) limits rule execution to the given
    repo-relative files PLUS every module that transitively imports one of
    them (a caller's cross-function findings can change when its callee
    does); the call graph is still built over the whole package so
    interprocedural summaries stay accurate."""
    repo_root = repo_root or repo_root_for_package()
    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    modules = collect_modules(paths, repo_root)
    ctx = AnalysisContext(repo_root=repo_root, modules=modules)
    targets: Optional[List[Module]] = None
    if restrict_rels is not None:
        closure = ctx.callgraph().dependents_closure(restrict_rels)
        targets = [m for m in modules if m.rel in closure]
    active, suppressed = run_rules(rules if rules is not None else all_rules(),
                                   modules, ctx, targets=targets)
    return active, suppressed, ctx


# -- baseline ----------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[str, int]:
    path = path or BASELINE_PATH
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return {str(k): int(v) for k, v in data.get("fingerprints", {}).items()}


def save_baseline(findings: Sequence[Finding],
                  path: Optional[str] = None) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    payload = {"version": 1,
               "note": "accepted pre-existing graftcheck findings; only "
                       "findings beyond these counts fail the run "
                       "(python -m pinot_tpu.analysis --update-baseline)",
               "fingerprints": dict(sorted(counts.items()))}
    with open(path or BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def unbaselined(findings: Sequence[Finding],
                baseline: Dict[str, int]) -> List[Finding]:
    """Findings beyond their baselined count (order-stable)."""
    remaining = dict(baseline)
    out: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            out.append(f)
    return out
