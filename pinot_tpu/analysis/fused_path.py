"""fused-path-materialization: decoded-column materialization inside the
fused kernel modules.

The fused single-launch plan (PR 16) keeps value columns in their compressed
resident forms — dict ids gathered through an in-register LUT
(`take_along_axis` on the VMEM-resident table), FOR deltas re-based in the
kernel body — so filter+aggregate never writes a decoded full-width column
back to HBM. What silently regresses it is a "convenience" decode inside the
kernel builders: a `jnp.take`/`np.take` dict-LUT gather that materializes the
whole column, or a call back into the staged decode surface
(`block.values(...)` / `block.decoded(...)`) from code that is supposed to
consume compressed forms.

This rule flags, in the fused kernel hot modules only:

* any `jnp.take` / `np.take` / `jax.numpy.take` call (the full-column gather
  shape; `take_along_axis` on an in-register LUT is the sanctioned fused
  decode and is NOT flagged), and
* any `.values(...)` / `.decoded(...)` method call (the staged decoded-HBM
  column surface),

unless the nearest enclosing function chain includes a name the module
declares in `__graft_slow_paths__ = ("fn", ...)` — the explicit allowlist of
staged/fallback decode paths — or the line carries an inline suppression
with a reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .core import AnalysisContext, Finding, Module, Rule, dotted_name
from .ingest_hot_loop import slow_path_names

#: fused-execution hot modules (repo-relative suffixes): the kernel builder,
#: the hand-tiled Pallas scan, and the compressed-form datablock. The
#: executor routes between fused and staged plans, so its staged input
#: builder legitimately calls `block.values(...)` — it is not listed here.
HOT_MODULES = (
    "pinot_tpu/engine/kernels.py",
    "pinot_tpu/engine/pallas_scan.py",
    "pinot_tpu/engine/datablock.py",
)

#: the full-column gather spellings (exact names: `take_along_axis` is the
#: in-register fused decode and must stay legal)
_TAKE_NAMES = ("jnp.take", "np.take", "jax.numpy.take", "numpy.take")

#: the staged decoded-column surface
_DECODE_ATTRS = ("values", "decoded")


class FusedPathMaterializationRule(Rule):
    id = "fused-path-materialization"
    description = ("decoded-column materialization (`jnp.take` dict gather "
                   "or a `.values()`/`.decoded()` staged-surface call) "
                   "inside a fused kernel module outside a declared "
                   "__graft_slow_paths__ function")

    def check_module(self, module: Module, ctx: AnalysisContext
                     ) -> Iterable[Finding]:
        if not any(module.rel.endswith(suffix) for suffix in HOT_MODULES):
            return ()
        slow = slow_path_names(module)
        out: List[Finding] = []
        seen_lines: Set[int] = set()

        def _enclosing(node: ast.AST) -> Set[str]:
            names: Set[str] = set()
            cur = getattr(node, "graft_parent", None)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    names.add(cur.name)
                cur = getattr(cur, "graft_parent", None)
            return names

        def _flag(node: ast.AST, message: str) -> None:
            fns = _enclosing(node)
            if fns & slow:
                return
            if node.lineno in seen_lines:
                return
            seen_lines.add(node.lineno)
            where = (f"`{sorted(fns)[0]}`" if fns else "module scope")
            out.append(Finding(self.id, module.rel, node.lineno,
                               f"{message} in {where} — fused kernels "
                               "consume compressed forms (in-register LUT "
                               "gather / FOR re-base); move the decode to a "
                               "declared __graft_slow_paths__ function"))

        for node in module.nodes_of(ast.Call):
            name = dotted_name(node.func)
            if name in _TAKE_NAMES:
                _flag(node, f"full-column dict gather `{name}(...)`")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _DECODE_ATTRS:
                _flag(node, "staged decoded-column surface "
                            f"`.{node.func.attr}(...)`")
        return out


def rules() -> List[Rule]:
    return [FusedPathMaterializationRule()]
