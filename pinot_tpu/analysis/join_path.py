"""join-path-host-materialization: host round-trips inside the device
hash-join hot modules.

The device join fast path (PR 17) keeps both sides' key codes and the
candidate index pairs device-resident across the build/probe launches: the
build side sorts (or scatters) on device, the probe is a jitted
gather+compare, and only the final verified index vectors come back to the
host. What silently regresses it is a "convenience" host materialization in
the middle of that pipeline: a per-row `np.fromiter(...)` loop over a column
that has a vectorized path, a `.tolist()` that turns a code array into a
Python list (every later op is then interpreter-speed), or an explicit
`jax.device_get(...)` that drags a device buffer home between launches
instead of letting the final fetch batch it.

This rule flags, in the join hot modules only:

* any `np.fromiter` / `numpy.fromiter` call (the per-row Python-loop shape),
* any `.tolist(...)` method call, and
* any `device_get` call (`jax.device_get`, dotted or bare),

unless the nearest enclosing function chain includes a name the module
declares in `__graft_slow_paths__ = ("fn", ...)` — the explicit allowlist of
host fallback paths (the object-dtype hash tail, the host `hash_join_host`
oracle) — or the line carries an inline suppression with a reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .core import AnalysisContext, Finding, Module, Rule, dotted_name
from .ingest_hot_loop import slow_path_names

#: device-join hot modules (repo-relative suffixes): the build/probe kernel
#: module and the multistage runtime that stages inputs for it. shuffle.py
#: routes frames between processes, so its codec legitimately touches host
#: memory — it is not listed here.
HOT_MODULES = (
    "pinot_tpu/engine/join_kernels.py",
    "pinot_tpu/multistage/runtime.py",
)

#: the per-row Python-loop spelling
_FROMITER_NAMES = ("np.fromiter", "numpy.fromiter")

#: explicit device->host fetches (bare or dotted)
_DEVICE_GET_NAMES = ("device_get", "jax.device_get")


class JoinPathHostMaterializationRule(Rule):
    id = "join-path-host-materialization"
    description = ("host materialization (`np.fromiter` per-row loop, "
                   "`.tolist()`, or `jax.device_get`) inside a device-join "
                   "hot module outside a declared __graft_slow_paths__ "
                   "function")

    def check_module(self, module: Module, ctx: AnalysisContext
                     ) -> Iterable[Finding]:
        if not any(module.rel.endswith(suffix) for suffix in HOT_MODULES):
            return ()
        slow = slow_path_names(module)
        out: List[Finding] = []
        seen_lines: Set[int] = set()

        def _enclosing(node: ast.AST) -> Set[str]:
            names: Set[str] = set()
            cur = getattr(node, "graft_parent", None)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    names.add(cur.name)
                cur = getattr(cur, "graft_parent", None)
            return names

        def _flag(node: ast.AST, message: str) -> None:
            fns = _enclosing(node)
            if fns & slow:
                return
            if node.lineno in seen_lines:
                return
            seen_lines.add(node.lineno)
            where = (f"`{sorted(fns)[0]}`" if fns else "module scope")
            out.append(Finding(self.id, module.rel, node.lineno,
                               f"{message} in {where} — the join fast path "
                               "keeps key codes and candidate pairs device-"
                               "resident (vectorized host staging only); "
                               "move the host loop to a declared "
                               "__graft_slow_paths__ function"))

        for node in module.nodes_of(ast.Call):
            name = dotted_name(node.func)
            if name in _FROMITER_NAMES:
                _flag(node, f"per-row host loop `{name}(...)`")
            elif name in _DEVICE_GET_NAMES:
                _flag(node, f"explicit device fetch `{name}(...)`")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "tolist":
                _flag(node, "host list materialization `.tolist(...)`")
        return out


def rules() -> List[Rule]:
    return [JoinPathHostMaterializationRule()]
