"""lock-order-inversion: a global lock-acquisition-order graph.

Every function contributes edges `A -> B` whenever lock B is acquired while
lock A is held — directly (`with self._a: ... with self._b:`) or through a
call chain (`with self._a: self._flush()` where `_flush` takes `self._b`,
possibly in another module).  Transitive acquisition sets are folded through
the PR 13 call graph with a small fixpoint, then strongly-connected
components of the order graph are reported as potential deadlocks: two
threads taking the same pair of locks in opposite orders can block each
other forever.

Lock identity is class-qualified (`Broker._lock`) for `self.` locks and
module-qualified (`pinot_tpu.ingest.stream._LOCK`) for module-level locks,
so the same attribute name on different classes never aliases.  Locks that
cannot be resolved to an owner (a lock passed in as a parameter) are skipped
— better silent than wrong.

The finding message lists only the sorted lock set (line-free, path-free) so
the fingerprint survives refactors; the conflicting acquisition sites are
rendered in the chain.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, Rule
from .lock_discipline import _is_lockish, _module_level_locks

_FIXPOINT_CAP = 20

_Site = Tuple[str, int, str]    # (rel, line, function display)


class _FnOrder:
    __slots__ = ("acquires", "edges", "calls")

    def __init__(self) -> None:
        #: lock id -> first acquisition site in this function
        self.acquires: Dict[str, _Site] = {}
        #: (outer, inner) -> site of the inner acquisition
        self.edges: Dict[Tuple[str, str], _Site] = {}
        #: (call node, locks held at the site, line)
        self.calls: List[Tuple[ast.Call, Tuple[str, ...], int]] = []


class LockOrderRule(Rule):
    id = "lock-order-inversion"
    description = ("two locks are acquired in opposite orders on different "
                   "code paths (folded through the call graph) — a potential "
                   "deadlock")

    def check_project(self, ctx: AnalysisContext) -> Iterable[Finding]:
        cg = ctx.callgraph()
        key_of = {id(fi): key for key, fi in cg.functions.items()}
        module_locks = {m.rel: _module_level_locks(m) for m in ctx.modules}

        orders: Dict[str, _FnOrder] = {}
        for key, fi in cg.functions.items():
            orders[key] = self._collect(fi, module_locks.get(
                fi.module.rel, set()), cg)

        # transitive acquisition sets, to fixpoint through the call graph
        acq: Dict[str, Set[str]] = {
            key: set(o.acquires) for key, o in orders.items()}
        for _ in range(_FIXPOINT_CAP):
            changed = False
            for key, fi in cg.functions.items():
                mine = acq[key]
                for call, _held, _line in orders[key].calls:
                    callee = cg.resolve_call(fi, call.func)
                    ckey = key_of.get(id(callee)) if callee else None
                    if ckey is None or ckey == key:
                        continue
                    extra = acq[ckey] - mine
                    if extra:
                        mine |= extra
                        changed = True
            if not changed:
                break

        # global order edges: direct nesting + call-sites under held locks
        edges: Dict[Tuple[str, str], _Site] = {}
        for key, fi in cg.functions.items():
            o = orders[key]
            for e, site in o.edges.items():
                edges.setdefault(e, site)
            for call, held, line in o.calls:
                if not held:
                    continue
                callee = cg.resolve_call(fi, call.func)
                ckey = key_of.get(id(callee)) if callee else None
                if ckey is None or ckey == key:
                    continue
                for inner in acq[ckey]:
                    for outer in held:
                        if outer == inner:
                            continue
                        edges.setdefault(
                            (outer, inner),
                            (fi.module.rel, line,
                             f"{fi.display}() -> {callee.display}()"))

        return self._report(edges)

    # -- per-function collection -------------------------------------------

    def _collect(self, fi, module_locks: Set[str], cg) -> _FnOrder:
        out = _FnOrder()
        rel = fi.module.rel

        def lock_id(expr: ast.AST) -> Optional[str]:
            # with self._a: / with cls._a:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id in ("self", "cls"):
                if fi.cls is None:
                    return None
                attr = expr.attr
                if attr in fi.cls.lock_attrs or _is_lockish(attr):
                    return f"{fi.cls.name}.{attr}"
                return None
            # with _LOCK: — module-level lock (possibly imported: canonicalize
            # through the module's import table so `from x import _LOCK`
            # aliases to the owning module, not the user's)
            if isinstance(expr, ast.Name):
                name = expr.id
                if name in module_locks or \
                        (_is_lockish(name) and name.isupper()):
                    from .callgraph import module_name_for
                    expanded = cg.expand_name(rel, name)
                    if expanded != name:
                        return expanded
                    return f"{module_name_for(rel)}.{name}"
            return None

        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Lambda)

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, nested):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner_held = held
                for item in node.items:
                    walk(item.context_expr, inner_held)
                    lid = lock_id(item.context_expr)
                    if lid is not None:
                        out.acquires.setdefault(
                            lid, (rel, item.context_expr.lineno, fi.display))
                        for outer in inner_held:
                            if outer != lid:
                                out.edges.setdefault(
                                    (outer, lid),
                                    (rel, item.context_expr.lineno,
                                     fi.display))
                        inner_held = inner_held + (lid,)
                for stmt in node.body:
                    walk(stmt, inner_held)
                return
            if isinstance(node, ast.Call):
                out.calls.append((node, held, node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in getattr(fi.node, "body", ()):
            walk(stmt, ())
        return out

    # -- cycle reporting ----------------------------------------------------

    def _report(self, edges: Dict[Tuple[str, str], _Site]
                ) -> Iterable[Finding]:
        succs: Dict[str, Set[str]] = {}
        for a, b in edges:
            succs.setdefault(a, set()).add(b)
            succs.setdefault(b, set())
        out: List[Finding] = []
        for scc in self._sccs(succs):
            if len(scc) < 2:
                continue
            cycle = sorted(scc)
            # render the witness edges inside the component
            witness = []
            for e in sorted(edges):
                if e[0] in scc and e[1] in scc:
                    rel_, line_, fn_ = edges[e]
                    witness.append(
                        f"{e[0]} -> {e[1]} ({fn_} at {rel_}:{line_})")
            first = edges[min(e for e in edges
                              if e[0] in scc and e[1] in scc)]
            out.append(Finding(
                self.id, first[0], first[1],
                "lock-order inversion between "
                f"{', '.join(cycle)} — these locks are acquired in "
                "conflicting orders on different paths; two threads can "
                "deadlock",
                chain="; ".join(witness[:6])))
        return out

    @staticmethod
    def _sccs(succs: Dict[str, Set[str]]) -> List[Set[str]]:
        """Tarjan's strongly-connected components (iterative)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[Set[str]] = []
        counter = [0]

        def strongconnect(root: str) -> None:
            work: List[Tuple[str, Optional[str], List[str]]] = [
                (root, None, list(succs.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, parent, iters = work[-1]
                advanced = False
                while iters:
                    w = iters.pop()
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, v, list(succs.get(w, ()))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if parent is not None:
                    low[parent] = min(low[parent], low[v])
                if low[v] == index[v]:
                    comp: Set[str] = set()
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.add(w)
                        if w == v:
                            break
                    sccs.append(comp)

        for node in succs:
            if node not in index:
                strongconnect(node)
        return sccs


def rules() -> List[Rule]:
    return [LockOrderRule()]
